"""Score updaters: raw model scores kept as [K, N] device arrays.

Reference: /root/reference/src/boosting/score_updater.hpp (three AddScore
paths: whole-data tree predict, leaf-partition fast path for train, and
constant adds).  Tree traversal over the BINNED matrix is a vectorized
node-walk instead of the reference's per-row pointer chase
(tree.cpp:99-192): all rows advance one tree level per step, with each
level's per-node fields fetched by ONE one-hot matmul (ops/lookup.py) and
the row's split-feature bin by a fused masked sum — no gathers, which
serialize on TPU.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.lookup import select_bin_by_feature, table_lookup
from ..ops.predict import sparse_bin_lookup


def _bins_rows(bins_t):
    """(per-row store view, N).  Dense [N+1, C] stores carry a sentinel
    row that slices off; the sparse ELL triple (cols [N, R], binsv
    [N, R], zero_bin [C]) has no sentinel — its probe answers every
    column for every row by construction."""
    if isinstance(bins_t, (tuple, list)):
        return tuple(bins_t), bins_t[0].shape[0]
    N = bins_t.shape[0] - 1
    return bins_t[:N], N


def _walk_step(node, bins_nt, split_feature, threshold, decision,
               left_child, right_child, num_nodes, feat_tbl=None):
    """One tree level for every row at once.  All per-node lookups go
    through the one-hot matmul (ops/lookup.py) — XLA's [N] table gathers
    and 2-D `bins[rows, feat]` gathers serialize on TPU and cost more than
    the whole histogram pass; child ids are exact in f32 (|v| < 2^24).

    bins_nt may be the sparse ELL triple (cols, binsv, zero_bin): the
    bin lookup then probes the row's stored entries directly
    (ops/predict.sparse_bin_lookup — compare + masked sum, also
    gather-free) and the store never densifies.  Decision logic is
    identical either way.

    feat_tbl (optional [5, F]: col, offset, default, nslots, packed) maps
    the node's ORIGINAL inner feature onto a bundled store column and
    recovers the original bin from the packed slot — trees always speak
    original (feature, threshold-bin) space, so an EFB store needs this
    second lookup; unbundled stores skip it entirely."""
    if isinstance(bins_nt, tuple):
        def bin_of(c):
            return sparse_bin_lookup(*bins_nt, c)
    else:
        def bin_of(c):
            return select_bin_by_feature(bins_nt.T, c)
    nd = jnp.maximum(node, 0)
    tbl = jnp.stack([split_feature.astype(jnp.float32),
                     threshold.astype(jnp.float32),
                     decision.astype(jnp.float32),
                     left_child.astype(jnp.float32),
                     right_child.astype(jnp.float32)])
    r = table_lookup(tbl, nd, num_slots=num_nodes)
    feat = r[0].astype(jnp.int32)
    t = r[1].astype(jnp.int32)
    d = r[2]
    if feat_tbl is None:
        bv = bin_of(feat)
    else:
        fr = table_lookup(jnp.asarray(feat_tbl), feat,
                          num_slots=feat_tbl.shape[1])
        col = fr[0].astype(jnp.int32)
        off = fr[1].astype(jnp.int32)
        dflt = fr[2].astype(jnp.int32)
        ns = fr[3].astype(jnp.int32)
        pk = fr[4] > 0
        bv_store = bin_of(col)
        s = bv_store - off
        in_r = (s >= 0) & (s < ns)
        orig = jnp.where(in_r, s + (s >= dflt).astype(jnp.int32), dflt)
        bv = jnp.where(pk, orig, bv_store)
    go_left = jnp.where(d == 1, bv == t, bv <= t)
    nxt = jnp.where(go_left, r[3], r[4]).astype(jnp.int32)
    return jnp.where(node < 0, node, nxt)


@functools.partial(jax.jit, static_argnames=("depth",))
def predict_binned_leaf(bins_t: jax.Array, split_feature_inner: jax.Array,
                        threshold_in_bin: jax.Array, decision_type: jax.Array,
                        left_child: jax.Array, right_child: jax.Array,
                        feat_tbl=None, *, depth: int) -> jax.Array:
    """Leaf index per row by walking the tree `depth` levels.

    bins_t: [N+1, C] int STORE bins (C = original features, or bundled
    columns with `feat_tbl` given), or the sparse ELL triple
    (cols, binsv, zero_bin) — see _bins_rows.  Tree arrays are padded
    to fixed length so the jit cache keys only on `depth`.
    """
    bins_nt, N = _bins_rows(bins_t)
    node = jnp.zeros(N, jnp.int32)
    nn = split_feature_inner.shape[0]

    def step(_, node):
        return _walk_step(node, bins_nt, split_feature_inner,
                          threshold_in_bin, decision_type, left_child,
                          right_child, nn, feat_tbl)

    node = jax.lax.fori_loop(0, max(depth, 1), step, node)
    return ~node


@jax.jit
def traverse_tree_device(bins_t, split_feature, threshold_bin, is_cat,
                         left_child, right_child, num_leaves,
                         feat_tbl=None) -> jax.Array:
    """Leaf index per row from DEVICE tree arrays (learner TreeArrays) —
    no host tree needed, so the pipelined training path can score valid
    sets without waiting for the tree fetch.  A `while_loop` walks until
    every row parked at a leaf (negative node), so cost tracks the actual
    tree depth instead of a static worst-case bound."""
    bins_nt, N = _bins_rows(bins_t)
    # stump: everything is leaf 0 (node -1 == ~0) from the start
    n0 = jnp.where(num_leaves < 2, jnp.int32(-1), jnp.int32(0))
    node = jnp.full(N, n0, jnp.int32)
    max_steps = split_feature.shape[0] + 1

    def cond(st):
        i, node = st
        return (i < max_steps) & jnp.any(node >= 0)

    nn = split_feature.shape[0]

    def body(st):
        i, node = st
        node = _walk_step(node, bins_nt, split_feature, threshold_bin,
                          is_cat, left_child, right_child, nn, feat_tbl)
        return i + 1, node

    _, node = jax.lax.while_loop(cond, body, (jnp.int32(0), node))
    return ~node


@jax.jit
def shrink_clip_leaves(leaf_value: jax.Array, num_leaves: jax.Array,
                       shrink: jax.Array) -> jax.Array:
    """Shrinkage + kMaxTreeOutput clamp (tree.h: ±100) + stump zeroing,
    fused in ONE device program.  The eager formulation uploaded the
    shrinkage scalar and both clamp constants host→device on every
    boosting iteration (three implicit transfers per iteration on the
    pipelined path — the sanitizer's `sanitize/implicit_transfers`
    counter flags them); here they are trace constants / an explicit
    device-resident scalar (GBDT._shrink_dev)."""
    lv = jnp.clip(leaf_value * shrink, -100.0, 100.0)
    # a no-split tree must contribute zero score: the rounds learner
    # guarantees leaf_value[0]==0 for stumps, but enforce it so every
    # train_device implementation is safe (the stump is popped next
    # iteration with no score rollback)
    return lv * (num_leaves >= 2)


@jax.jit
def _add_raw(score, raw):
    """score += raw, one program (whole-model replay — add_trees)."""
    return score + raw


@jax.jit
def _add_from_leaf(score_row, leaf_idx, leaf_values):
    # one-hot matmul, not table gather: XLA's [N] gather from a leaf-sized
    # table runs at <1 GB/s on TPU (see ops/lookup.py) and cost ~65 ms per
    # iteration at N=4M; the matmul is exact for f32 leaf values
    val = table_lookup(leaf_values[None], leaf_idx,
                       num_slots=leaf_values.shape[0])[0]
    return score_row + val


@functools.partial(jax.jit, static_argnames=("tree_id",))
def _add_leaf_to_row(score, leaf_id, leaf_values, *, tree_id: int):
    """score[tree_id] += leaf_values[leaf_id], all inside ONE program.
    Eager `score[tree_id]` / `score.at[tree_id].set(...)` lower to
    dynamic_slice/scatter whose start index is uploaded host→device on
    every call — one implicit transfer per boosting iteration under the
    sanitizer's guard; a STATIC tree_id is a trace constant (the jit
    cache holds K entries, K = trees per iteration)."""
    val = _add_from_leaf(score[tree_id], leaf_id,
                         leaf_values.astype(jnp.float32))
    return score.at[tree_id].set(val)


@functools.partial(jax.jit, static_argnames=("tree_id",))
def _add_const_to_row(score, val, *, tree_id: int):
    return score.at[tree_id].add(val)


@functools.partial(jax.jit, static_argnames=("k",))
def select_class_row(x, *, k: int):
    """x[k] with a trace-constant index (the eager integer index lowers
    to dynamic_slice and uploads its start scalar host→device on every
    boosting iteration)."""
    return x[k]


class ScoreUpdater:
    """Holds [K, N] float32 raw scores for one dataset."""

    def __init__(self, bins_t, num_data: int, K: int,
                 init_score: Optional[np.ndarray] = None, feat_tbl=None):
        # bins_t: [N+1, C] array, the sparse ELL triple (cols, binsv,
        # zero_bin), None, or a ZERO-ARG CALLABLE resolved on first
        # traversal.  Sparse stores hand the triple so every traversal
        # consumer (replay, valid scoring, refit routing) probes the ELL
        # segments directly and the store NEVER densifies
        # (tree/sparse_fallbacks stays 0 — docs/Sparse.md)
        self._bins_src = bins_t
        # [5, F] bundle walk table when bins_t is an EFB store (see
        # _walk_step), None for the plain per-feature layout
        self.feat_tbl = None if feat_tbl is None else jnp.asarray(feat_tbl)
        self.num_data = num_data
        self.K = K
        self.has_init_score = init_score is not None
        score = np.zeros((K, num_data), np.float32)
        if init_score is not None:
            init_score = np.asarray(init_score, np.float64).reshape(-1)
            if init_score.size == num_data * K:
                score = init_score.reshape(K, num_data).astype(np.float32)
            elif init_score.size == num_data:
                score[:] = init_score[None, :].astype(np.float32)
            else:
                raise ValueError("init score size mismatch")
        self.score = jnp.asarray(score)

    @property
    def bins_t(self):
        src = self._bins_src
        if callable(src):
            src = self._bins_src = src()
        return src

    def add_constant(self, val: float, tree_id: int) -> None:
        self.score = _add_const_to_row(
            self.score, jax.device_put(np.float32(val)), tree_id=tree_id)

    def _tree_leaf_idx(self, tree) -> jax.Array:
        d = tree.as_device_arrays()
        # pad tree arrays to the tree's max capacity for stable jit shapes
        return predict_binned_leaf(
            self.bins_t, d["split_feature_inner"], d["threshold_in_bin"],
            d["decision_type"], d["left_child"], d["right_child"],
            self.feat_tbl, depth=d["depth"])

    def add_tree(self, tree, tree_id: int, scale: float = 1.0) -> None:
        """Whole-data tree predict path (score_updater.hpp AddScore(tree))."""
        if tree.num_leaves <= 1:
            self.add_constant(float(tree.leaf_value[0]) * scale, tree_id)
            return
        leaf_idx = self._tree_leaf_idx(tree)
        # scale on HOST (f32*f32 is IEEE-identical either side), then ONE
        # explicit upload — the eager jnp.asarray + np-scalar multiply
        # was two implicit transfers per call
        lv = jax.device_put(
            tree.leaf_value[: tree.max_leaves].astype(np.float32)
            * np.float32(scale))
        self.score = _add_leaf_to_row(self.score, leaf_idx, lv,
                                      tree_id=tree_id)

    def add_trees(self, trees, K: int, kernel: str = "auto") -> None:
        """Replay a WHOLE model onto the scores (add_valid / continued-
        training replay).  With ``predict_kernel=tensorized`` the replay
        is ONE binned ensemble traversal — `depth` fused gather/select
        passes over the store with integer bin compares (ops/predict.py
        predict_ensemble_binned, EFB packed-slot remap included) —
        instead of ``len(trees)`` sequential per-tree walk programs.
        Stump constants ride in the stack (leaf 0), so the result matches
        the sequential add_tree/add_constant loop to f32 addition
        reassociation (exact on dyadic leaf values).  A sparse store
        replays through `predict_ensemble_binned_sparse` — same walk,
        ELL probes instead of dense gathers, zero densification."""
        from ..ops.predict import (build_ensemble, predict_ensemble_binned,
                                   predict_ensemble_binned_sparse,
                                   resolve_predict_kernel)
        if (resolve_predict_kernel(kernel) != "tensorized"
                or len(trees) < 2 or self._bins_src is None):
            for i, t in enumerate(trees):
                self.add_tree(t, i % K)
            return
        trees_by_class = [[t for i, t in enumerate(trees) if i % K == k]
                          for k in range(K)]
        stack, meta = build_ensemble(trees_by_class, binned=True,
                                     layout="soa")
        stack = jax.device_put(stack)
        bt = self.bins_t
        if isinstance(bt, (tuple, list)):
            raw = predict_ensemble_binned_sparse(
                stack, *bt, self.feat_tbl, meta=meta)           # [K, N]
        else:
            raw = predict_ensemble_binned(stack, bt, self.feat_tbl,
                                          meta=meta)            # [K, N]
        self.score = _add_raw(self.score, raw)

    def add_tree_arrays_dev(self, arrs, leaf_values: jax.Array,
                            tree_id: int) -> None:
        """Whole-data score update from DEVICE TreeArrays (pipelined path
        for datasets that don't have the training leaf_id — valid sets).
        `leaf_values` carries shrinkage/clamp pre-applied."""
        leaf_idx = traverse_tree_device(
            self.bins_t, arrs.split_feature, arrs.threshold_bin,
            arrs.is_cat, arrs.left_child, arrs.right_child, arrs.num_leaves,
            self.feat_tbl)
        self.score = _add_leaf_to_row(self.score, leaf_idx, leaf_values,
                                      tree_id=tree_id)

    def add_tree_by_leaf_id_dev(self, leaf_id: jax.Array,
                                leaf_values: jax.Array, tree_id: int
                                ) -> None:
        """Leaf-partition score update with DEVICE leaf values (shrinkage
        pre-applied) — no host tree needed; used by the pipelined
        training path."""
        self.score = _add_leaf_to_row(self.score, leaf_id, leaf_values,
                                      tree_id=tree_id)

    def add_tree_by_leaf_id(self, tree, leaf_id: jax.Array, tree_id: int
                            ) -> None:
        """Leaf-partition fast path for the training set
        (serial_tree_learner.h:52-64): leaf_id -1 rows (out-of-bag) match
        no one-hot slot and contribute exactly 0.0 — callers follow with
        add_tree for OOB when bagging."""
        lv = jax.device_put(
            tree.leaf_value[: tree.max_leaves].astype(np.float32))
        self.score = _add_leaf_to_row(self.score, leaf_id, lv,
                                      tree_id=tree_id)

    def get(self) -> np.ndarray:
        """Fetch the whole [K, N] score to host — the ONE deliberate
        bulk sync of the host-metric fallback path (explicit, so the
        sanitizer's guard distinguishes it from accidental syncs)."""
        return jax.device_get(self.score).astype(np.float64)
