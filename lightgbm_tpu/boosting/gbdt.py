"""GBDT boosting driver.

Parity with /root/reference/src/boosting/gbdt.cpp:
- TrainOneIter (gbdt.cpp:332-451): boost-from-average init tree
  (:333-355, a 2-leaf tree whose both leaves carry the label average),
  gradients from the objective or user-supplied (custom fobj), bagging
  (:232-317, without-replacement subset re-drawn every `bagging_freq`
  iterations), one tree per class, Shrinkage, score update via leaf
  partition + out-of-bag path (:495-518, :319-330).
- RollbackOneIter (:453-470), early stopping over valid metrics
  (:472-578), model text save/load (:694-848), JSON dump (:658-692),
  split-count feature importance (:850-872), Predict* (:874-923).

TPU mapping: gradients/scores live on device as [K, N] float32; the
per-iteration flow is (1) one fused elementwise gradient program,
(2) the tree learner's device split loop, (3) one score-update program.
"""
from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config, default_metric_for_objective
from ..dataset import Dataset
from ..learner.fused import create_tree_learner
from ..metrics import Metric, create_metric
from ..objectives import Objective, create_objective, objective_from_model_string
from ..tree import Tree, NUMERICAL_DECISION
from .score_updater import ScoreUpdater


CHECKPOINT_VERSION = 1

# fields that may legitimately differ between the run that wrote a
# checkpoint and the run resuming it (paths, logging, and the resume
# machinery itself); everything else participates in the fingerprint —
# resuming under a different training recipe is an error, not a merge
_FINGERPRINT_EXCLUDE = frozenset({
    "task", "verbose", "num_threads", "num_iterations", "input_model",
    "output_model", "output_result", "config_file", "output_freq",
    "checkpoint_path", "checkpoint_interval",
    # serving / online-daemon knobs: they configure how a model is
    # SERVED or refreshed, never how it trains — editing serve_port in
    # the config file between crash and resume must not discard the run
    "serve_host", "serve_port", "max_batch_rows", "flush_deadline_ms",
    "model_poll_seconds", "min_bucket_rows", "serve_replicas",
    "max_pending_rows", "serve_request_timeout_ms",
    "replica_failure_threshold",
    "refit_decay_rate", "refit_min_rows", "online_trigger_rows",
    "online_mode",
    # observability knobs: where spans/metrics go never changes what a
    # run trains — pointing telemetry elsewhere between crash and
    # resume must not discard the checkpoint
    "telemetry_path", "metrics_port",
})


def config_fingerprint(config: Config) -> str:
    """Stable digest of every training-relevant Config field."""
    d = dataclasses.asdict(config)
    items = sorted((k, repr(v)) for k, v in d.items()
                   if k not in _FINGERPRINT_EXCLUDE)
    return hashlib.sha1(repr(items).encode()).hexdigest()


def _rng_state_to_json(rng: np.random.RandomState) -> Dict:
    kind, keys, pos, has_gauss, cached = rng.get_state()
    return {"kind": kind, "keys": np.asarray(keys).tolist(), "pos": int(pos),
            "has_gauss": int(has_gauss), "cached": float(cached)}


def _rng_state_from_json(d: Dict) -> Tuple:
    return (str(d["kind"]), np.asarray(d["keys"], np.uint32), int(d["pos"]),
            int(d["has_gauss"]), float(d["cached"]))


def load_checkpoint(path: str) -> Optional[Dict]:
    """Parse a training checkpoint; None when absent or unreadable.

    A torn/corrupt checkpoint (a crash artifact) must not wedge the
    restarted run: it logs a warning and training starts from scratch
    (or from ``input_model``), exactly as if no checkpoint existed.
    """
    from .. import log
    try:
        with open(path) as f:
            state = json.load(f)
    except FileNotFoundError:
        return None
    except OSError as e:
        # an existing-but-unreadable checkpoint (EACCES/EIO) must not
        # look like "no checkpoint": losing the resume silently discards
        # every checkpointed iteration
        log.warning(f"could not read checkpoint {path} "
                    f"({type(e).__name__}: {e}); starting fresh")
        return None
    except ValueError as e:
        log.warning(f"ignoring unreadable checkpoint {path} "
                    f"({type(e).__name__}: {e}); starting fresh")
        return None
    if (not isinstance(state, dict)
            or state.get("version") != CHECKPOINT_VERSION
            or "model" not in state):
        log.warning(f"ignoring incompatible checkpoint {path} "
                    f"(version {state.get('version') if isinstance(state, dict) else '?'}); "
                    "starting fresh")
        return None
    return state


class GBDT:
    """Gradient Boosting Decision Tree driver."""

    def __init__(self, config: Config, train_set: Optional[Dataset] = None,
                 objective: Optional[Objective] = None):
        self.config = config
        self.models: List[Tree] = []
        self.iter_ = 0
        self.num_init_iteration = 0
        self.boost_from_average_used = False
        self.best_msg = ""
        self.train_set = None
        self.objective = objective
        self.shrinkage_rate = config.learning_rate
        self.num_class = config.num_class
        self.K = config.num_tree_per_iteration
        self.train_metrics: List[Metric] = []
        self.valid_sets: List[Tuple[str, Dataset, ScoreUpdater, List[Metric]]] = []
        self.label_idx = 0
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.max_feature_idx = 0
        self._early_stopping_state: Dict = {}
        self._predict_stack_cache: Dict = {}
        # checkpoint resume forces the SEQUENTIAL per-tree score replay
        # ("walk"): it adds trees in exactly training's accumulation
        # order, so resumed scores are bitwise the uninterrupted run's.
        # The tensorized ensemble replay reassociates the f32 sum —
        # exact on dyadic leaf values, last-ULP different otherwise.
        self._replay_kernel: Optional[str] = None
        if train_set is not None:
            self.reset_training_data(train_set, objective)

    # ------------------------------------------------------------------
    def reset_training_data(self, train_set: Dataset,
                            objective: Optional[Objective] = None) -> None:
        cfg = self.config
        self.train_set = train_set
        self.num_data = train_set.num_data
        self.objective = objective or create_objective(cfg)
        self.objective.init(train_set.metadata, self.num_data)
        self.K = self.objective.num_tree_per_iteration
        self.learner = create_tree_learner(train_set, cfg)
        # bins_t resolves LAZILY (sparse stores materialize the dense
        # transpose only if a consumer actually walks trees over it)
        self.train_score = ScoreUpdater(
            lambda: self.learner.bins_t, self.num_data, self.K,
            train_set.metadata.init_score,
            feat_tbl=train_set.bundle_feat_table())
        # continued training (input_model): replay the loaded model onto
        # the fresh training scores (the reference re-scores via a
        # Predictor closure during loading, application.cpp:106-113) —
        # one tensorized binned traversal for the whole model under
        # predict_kernel=tensorized (score_updater.add_trees)
        for t in self.models:
            t.rebin_to_dataset(train_set)
        if self.models:
            self.train_score.add_trees(self.models, self.K,
                                       self._replay_kernel
                                       or cfg.predict_kernel)
        self.feature_names = list(train_set.feature_names)
        self.feature_infos = train_set.feature_infos()
        self.max_feature_idx = train_set.num_total_features - 1
        # metrics
        names = cfg.metric or (default_metric_for_objective(cfg.objective),)
        self.train_metrics = []
        for nm in names:
            m = create_metric(nm, cfg)
            if m is not None:
                m.init(train_set.metadata, self.num_data)
                self.train_metrics.append(m)
        # pipelined-tree state (see _train_one_iter_pipelined)
        self._pending = None
        self._pending_stop = False
        # bagging state
        self.bag_rng = np.random.RandomState(cfg.bagging_seed)
        self.bag_idx = None
        self.bag_cnt = self.num_data
        self.need_bagging = (cfg.bagging_fraction < 1.0 and cfg.bagging_freq > 0)
        # degenerate-class bookkeeping (gbdt.cpp:166-195)
        self.class_need_train = [True] * self.K
        self.class_default_output = [0.0] * self.K
        if self.K > 1 and cfg.objective in ("multiclass", "multiclassova"):
            lab = np.asarray(train_set.metadata.label).astype(np.int64)
            for k in range(self.K):
                cnt = int((lab == k).sum())
                if cnt == 0:
                    self.class_need_train[k] = False
                    self.class_default_output[k] = -np.log(1e10)
                elif cnt == self.num_data:
                    self.class_need_train[k] = False
                    self.class_default_output[k] = -np.log(1e-10)

    def add_valid(self, valid_set: Dataset, name: str) -> None:
        self._flush_pending()
        cfg = self.config
        if valid_set.sparse is not None:
            # sparse valid sets hand the ELL triple: scoring walks the
            # row segments directly (predict_ensemble_binned_sparse /
            # the sparse _walk_step) and never densifies
            bins_t = valid_set.sparse_triple()
        else:
            bins_np = valid_set.bins.astype(np.int32)
            pad = np.zeros((bins_np.shape[0], 1), np.int32)
            bins_t = jnp.asarray(
                np.concatenate([bins_np, pad], axis=1).T.copy())
        su = ScoreUpdater(bins_t, valid_set.num_data, self.K,
                          valid_set.metadata.init_score,
                          feat_tbl=valid_set.bundle_feat_table())
        names = cfg.metric or (default_metric_for_objective(cfg.objective),)
        ms = []
        for nm in names:
            m = create_metric(nm, cfg)
            if m is not None:
                m.init(valid_set.metadata, valid_set.num_data)
                ms.append(m)
        # replay existing model onto the new valid scores (loaded trees
        # first need in-bin thresholds for this dataset's mappers); the
        # tensorized kernel replays the whole model in `depth` passes
        for t in self.models:
            t.rebin_to_dataset(valid_set)
        if self.models:
            su.add_trees(self.models, self.K,
                         self._replay_kernel or cfg.predict_kernel)
        self.valid_sets.append((name, valid_set, su, ms))

    # ------------------------------------------------------------------
    def _boost_from_average(self) -> None:
        cfg = self.config
        if (self.models or not cfg.boost_from_average
                or self.train_score.has_init_score or self.num_class > 1
                or self.objective is None
                or not self.objective.boost_from_average):
            return
        # reference uses the plain label average for all objectives
        lab = np.asarray(self.train_set.metadata.label, np.float64)
        import jax
        if jax.process_count() > 1:
            # every rank must seed the SAME constant or the grown trees
            # diverge — average over the GLOBAL label set (bit-exact f64
            # gather: a f32 round here shifts every leaf value)
            from ..distributed import allgather_f64
            sums = allgather_f64(np.asarray([lab.sum(), float(len(lab))]))
            init_score = float(sums[:, 0].sum() / max(sums[:, 1].sum(), 1.0))
        else:
            init_score = float(lab.mean())
        t = Tree(2)
        t.split(0, 0, NUMERICAL_DECISION, 0, 0, 0.0, init_score, init_score,
                0, self.num_data, 1.0)
        self.train_score.add_constant(init_score, 0)
        for _, _, su, _ in self.valid_sets:
            su.add_constant(init_score, 0)
        self.models.append(t)
        self.boost_from_average_used = True

    def _bagging(self, iter_: int) -> None:
        """Re-draw the bag every bagging_freq iterations (gbdt.cpp:257-317)."""
        if not self.need_bagging or iter_ % self.config.bagging_freq != 0:
            return
        n = self.num_data
        cnt = int(self.config.bagging_fraction * n)
        idx = self.bag_rng.choice(n, size=cnt, replace=False)
        idx.sort()
        cap = 1 << max(cnt - 1, 1).bit_length()
        cap = min(cap, n)
        if cap < cnt:
            cap = cnt
        padded = np.full(cap, n, np.int32)
        padded[:cnt] = idx
        # explicit upload: the bag redraw runs mid-loop under the
        # sanitizer's transfer guard (jnp.asarray would be implicit)
        self.bag_idx = jax.device_put(padded)
        self.bag_cnt = cnt

    def boosting_gradients(self) -> Tuple[jax.Array, jax.Array]:
        return self.objective.get_gradients(self.train_score.score)

    def _shrink_dev(self) -> jax.Array:
        """Device-resident shrinkage scalar, re-uploaded (explicitly)
        only when the learning rate changes (reset_parameter callback):
        passing the Python float each iteration was one implicit
        host→device transfer per tree."""
        cached = getattr(self, "_shrink_cache", None)
        if cached is None or cached[0] != self.shrinkage_rate:
            cached = (self.shrinkage_rate,
                      jax.device_put(np.float32(self.shrinkage_rate)))
            self._shrink_cache = cached
        return cached[1]

    # ------------------------------------------------------------------
    def _flush_pending(self) -> None:
        """Materialize the pipelined tree from the previous iteration
        (see train_one_iter: the packed-tree device→host transfer is
        overlapped with the next iteration's work — on remote-attached
        TPUs the fetch round-trip alone costs ~70 ms)."""
        if getattr(self, "_pending", None) is None:
            return
        packed, slot, shrink = self._pending
        self._pending = None
        from ..learner.fused import unpack_tree_arrays, tree_arrays_to_host
        # explicit fetch (jax.device_get, not np.asarray): the packed
        # vector was copy_to_host_async'd an iteration ago, and the
        # explicit API keeps the transfer-guarded hot path clean
        arrs = unpack_tree_arrays(jax.device_get(packed),
                                  self.config.num_leaves)
        tree = tree_arrays_to_host(arrs, self.train_set,
                                   self.config.num_leaves)
        tree.apply_shrinkage(shrink)
        self.models[slot] = tree
        if tree.num_leaves <= 1:
            self._pending_stop = True

    def _can_pipeline(self) -> bool:
        import jax
        return (self.K == 1
                and hasattr(self.learner, "train_device")
                and self.__class__.__name__ in ("GBDT", "GOSS")
                # multi-process training keeps the sync path: the
                # pipelined device-side score update would need local
                # shard extraction from the global leaf_id
                and jax.process_count() == 1)

    def _train_one_iter_pipelined(self) -> bool:
        """Boosting iteration with a one-iteration-delayed tree fetch: the
        packed tree's device→host transfer overlaps the NEXT iteration's
        gradient/build/score work instead of stalling on the round-trip."""
        from .. import profiling
        self._flush_pending()
        if getattr(self, "_pending_stop", False):
            self._pending_stop = False
            self.models.pop()
            self.iter_ -= 1
            import warnings
            warnings.warn("Stopped training because there are no more "
                          "leaves that meet the split requirements.")
            return True
        self._boost_from_average()
        with profiling.phase("boosting"):
            gradient, hessian = self.boosting_gradients()
        with profiling.phase("bagging"):
            self._bagging(self.iter_)
        bag = (self.bag_idx
               if self.need_bagging and self.bag_cnt < self.num_data
               else None)
        with profiling.phase("tree"):
            # K == 1 here (_can_pipeline): reshape instead of [0] — the
            # eager integer index lowers to dynamic_slice and uploads
            # its start index host→device every iteration
            packed, leaf_id, arrs = self.learner.train_device(
                gradient.reshape(-1), hessian.reshape(-1), bag,
                self.bag_cnt if bag is not None else None)
        with profiling.phase("score"):
            from .score_updater import shrink_clip_leaves
            lv = shrink_clip_leaves(arrs.leaf_value, arrs.num_leaves,
                                    self._shrink_dev())
            self.train_score.add_tree_by_leaf_id_dev(leaf_id, lv, 0)
            # valid sets stay on the fast path too: traverse the device
            # TreeArrays directly (no host tree, no pipeline stall)
            for _, _, su, _ in self.valid_sets:
                su.add_tree_arrays_dev(arrs, lv, 0)
        # the DELIBERATE transfer of the pipelined design: start the
        # packed tree's device→host copy now so next iteration's
        # device_get finds it done.  Marked explicitly allowed so the
        # sanitizer's disallow-guard (diagnostics/sanitize.py) doesn't
        # count the prefetch as an accidental sync on backends that
        # guard device→host.
        with jax.transfer_guard("allow"):
            packed.copy_to_host_async()
        self.models.append(None)      # placeholder until _flush_pending
        self._pending = (packed, len(self.models) - 1, self.shrinkage_rate)
        self.iter_ += 1
        return False

    # -- per-iteration telemetry (docs/Observability.md) ---------------

    def _telemetry_iter_begin(self) -> None:
        """Snapshot host-side accumulators so the end-of-iteration
        record can report deltas.  Costs one cached check when
        telemetry is off; never touches the device either way — the
        pipelined path's zero-sync contract holds with telemetry on.
        Deliberate: iterations that ABORT (no splittable leaves — the
        trees are popped and iter_ rolled back) emit no record; only
        completed iterations exist in the stream, matching the model
        they describe."""
        from .. import telemetry
        if not telemetry.enabled():
            self._telem_t0 = None
            return
        from .. import profiling
        self._telem_t0 = time.perf_counter()
        self._telem_phases = profiling.timings()
        self._telem_ctrs = profiling.counters_nosync("tree/")

    def _telemetry_iter_end(self) -> None:
        t0 = getattr(self, "_telem_t0", None)
        if t0 is None:
            return
        from .. import profiling, telemetry
        dt = time.perf_counter() - t0
        phases = profiling.timings()
        ctrs = profiling.counters_nosync("tree/")
        ph = {}
        for k, v in phases.items():
            d = v - self._telem_phases.get(k, 0.0)
            if d > 1e-9:
                ph[k] = round(d, 6)
        # host-visible deltas only: count_deferred device totals fold
        # in at the next drain (a /metrics scrape or bench read), so on
        # the pipelined path these lag rather than force a sync
        deltas = {k.rsplit("/", 1)[-1]: round(v - self._telem_ctrs.get(k,
                                                                       0.0),
                                              1)
                  for k, v in ctrs.items()}
        telemetry.event("train.iteration", iteration=self.iter_,
                        trees=len(self.models), rows=self.num_data,
                        seconds=round(dt, 6), phases=ph,
                        counters=deltas)

    def _telemetry_eval(self, out: List) -> None:
        """Eval results ride the span stream too — emitted only where
        the caller already materialized them (ONE batched device_get),
        so telemetry never adds a sync of its own."""
        from .. import telemetry
        if out and telemetry.enabled():
            telemetry.event("train.eval", iteration=self.iter_,
                            results=[[s, n, v] for s, n, v, _ in out])

    def train_one_iter(self, gradient: Optional[jax.Array] = None,
                       hessian: Optional[jax.Array] = None,
                       is_eval: bool = False) -> bool:
        """One boosting iteration.  Returns True when training should stop
        (early stopping or no splittable leaves)."""
        from .. import profiling
        self._telemetry_iter_begin()
        if gradient is None and hessian is None and self._can_pipeline():
            if self._train_one_iter_pipelined():
                return True
            stop = (self.eval_and_check_early_stopping() if is_eval
                    else False)
            self._telemetry_iter_end()
            return stop
        self._flush_pending()
        self._boost_from_average()
        if gradient is None or hessian is None:
            with profiling.phase("boosting"):
                gradient, hessian = self.boosting_gradients()
        with profiling.phase("bagging"):
            self._bagging(self.iter_)

        should_continue = False
        bag = self.bag_idx if (self.need_bagging and self.bag_cnt < self.num_data) else None
        from .score_updater import select_class_row
        for k in range(self.K):
            if self.class_need_train[k]:
                with profiling.phase("tree"):
                    tree, leaf_id = self.learner.train(
                        select_class_row(gradient, k=k),
                        select_class_row(hessian, k=k), bag,
                        self.bag_cnt if bag is not None else None)
            else:
                tree = Tree(2)
                leaf_id = None
            if tree.num_leaves > 1:
                should_continue = True
                tree.apply_shrinkage(self.shrinkage_rate)
                with profiling.phase("score"):
                    if leaf_id is not None and (
                            bag is None
                            or getattr(self.learner, "full_leaf_id", False)):
                        self.train_score.add_tree_by_leaf_id(tree, leaf_id, k)
                    else:
                        self.train_score.add_tree(tree, k)
                for _, _, su, _ in self.valid_sets:
                    su.add_tree(tree, k)
            else:
                if (not self.class_need_train[k]
                        and len(self.models) < self.K):
                    out = self.class_default_output[k]
                    tree.leaf_value[0] = out
                    self.train_score.add_constant(out, k)
                    for _, _, su, _ in self.valid_sets:
                        su.add_constant(out, k)
            self.models.append(tree)

        if not should_continue:
            import warnings
            warnings.warn("Stopped training because there are no more leaves "
                          "that meet the split requirements.")
            for _ in range(self.K):
                self.models.pop()
            return True
        self.iter_ += 1
        stop = self.eval_and_check_early_stopping() if is_eval else False
        self._telemetry_iter_end()
        return stop

    def rollback_one_iter(self) -> None:
        self._flush_pending()
        if self.iter_ <= 0:
            return
        for k in range(self.K):
            tree = self.models[-self.K + k]
            tree.apply_shrinkage(-1.0)
            self.train_score.add_tree(tree, k)
            for _, _, su, _ in self.valid_sets:
                su.add_tree(tree, k)
        del self.models[-self.K:]
        self.iter_ -= 1

    # ------------------------------------------------------------------
    def _eval_one_set(self, set_name: str, su: ScoreUpdater,
                      ms: List[Metric], out: List) -> None:
        """Device metric kernels first (lazy device scalars — see
        _materialize_evals); host fallback fetches the score vector at
        most once per dataset."""
        host_score = None
        for m in ms:
            res = m.eval_device(su.score, self.objective)
            if res is None:
                if host_score is None:
                    host_score = su.get()
                res = m.eval(host_score, self.objective)
            for nm, v in res:
                out.append((set_name, nm, v, m.factor_to_bigger_better > 0))

    @staticmethod
    def _materialize_evals(out: List) -> List[Tuple[str, str, float, bool]]:
        """Resolve collected (set, name, value, bigger_better) rows whose
        values may still be 0-d device scalars with ONE batched
        jax.device_get.  The old contract (each metric float()ing its
        own result) cost one blocking device→host round-trip per metric
        per iteration — the per-iteration pipeline stall the sanitizer's
        transfer guard flags; V valid sets × M metrics now cost exactly
        one sync."""
        if not out:
            return out
        vals = jax.device_get([v for _, _, v, _ in out])
        return [(s, n, float(v), b)
                for (s, n, _, b), v in zip(out, vals)]

    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        from .. import profiling
        out: List = []
        with profiling.phase("metric"):
            self._eval_one_set("training", self.train_score,
                               self.train_metrics, out)
            out = self._materialize_evals(out)
        self._telemetry_eval(out)
        return out

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        from .. import profiling
        out: List = []
        with profiling.phase("metric"):
            for name, _, su, ms in self.valid_sets:
                self._eval_one_set(name, su, ms, out)
            out = self._materialize_evals(out)
        self._telemetry_eval(out)
        return out

    def eval_and_check_early_stopping(self, results=None) -> bool:
        """CLI-path early stopping (gbdt.cpp:472-578): stop when no valid
        metric improved for early_stopping_round iterations.  `results`
        lets a caller that already evaluated (for logging) avoid a second
        full metric pass."""
        esr = self.config.early_stopping_round
        if esr <= 0:
            return False
        res = self.eval_valid() if results is None else results
        if not res:
            return False
        st = self._early_stopping_state
        improved = False
        for name, metric, value, bigger_better in res:
            key = (name, metric)
            cmp = value if bigger_better else -value
            if key not in st or cmp > st[key][0]:
                st[key] = (cmp, self.iter_)
                improved = True
        best_iter = max(v[1] for v in st.values())
        if self.iter_ - best_iter >= esr:
            self._flush_pending()   # materialize before dropping models
            n_drop = (self.iter_ - best_iter) * self.K
            del self.models[-n_drop:]
            self.iter_ = best_iter
            return True
        return False

    # ------------------------------------------------------------------
    @property
    def num_trees(self) -> int:
        return len(self.models)

    def current_iteration(self) -> int:
        extra = 1 if self.boost_from_average_used else 0
        return (len(self.models) - extra) // self.K

    # batch-size/ensemble-size product above which prediction moves to the
    # stacked device walk (ops/predict.py); small calls keep the host f64
    # walk (no jit latency, reference-exact double comparisons)
    _DEVICE_PREDICT_MIN_WORK = 2_000_000
    _PREDICT_CHUNK = 262_144

    def _cache_predict_stack(self, key, value):
        """Bounded-size put: the stack cache never outgrows a few model
        generations (stale generations evict wholesale)."""
        if len(self._predict_stack_cache) >= 4 * max(self.K, 1):
            self._predict_stack_cache.clear()
        self._predict_stack_cache[key] = value
        return value

    def _run_chunked(self, X: np.ndarray, out: np.ndarray, kernel_fn):
        """Shared device-predict chunk loop: full `_PREDICT_CHUNK` slabs
        plus ONE padded remainder, so the jitted kernel only ever sees
        one compiled shape.  `kernel_fn` maps a [chunk, F] f32 slab to
        device values whose LAST axis is rows; rows land in
        ``out[..., a:b]``."""
        import jax.numpy as jnp
        n = X.shape[0]
        CHUNK = self._PREDICT_CHUNK
        for a in range(0, n, CHUNK):
            b = min(a + CHUNK, n)
            chunk = X[a:b]
            if b - a < CHUNK and n > CHUNK:
                chunk = np.pad(chunk, ((0, CHUNK - (b - a)), (0, 0)))
            vals = kernel_fn(jnp.asarray(chunk, jnp.float32))
            out[..., a:b] = jax.device_get(vals)[..., : b - a]

    def _predict_raw_device(self, X: np.ndarray, used: int) -> np.ndarray:
        """Stacked-ensemble device predictor (predictor.hpp:24-159 is the
        reference's parallel batch path; here all trees × all rows advance
        one level per step on device).  f32 feature/threshold compares —
        the same single-precision trade the reference GPU learner makes
        (docs/GPU-Performance.md:130-134).

        ``predict_kernel=tensorized`` (the `auto` resolution) traverses
        ALL classes' trees in one fused program; ``walk`` keeps the
        per-class vmapped walk.
        """
        from ..ops.predict import (stack_trees, predict_trees,
                                   resolve_predict_kernel)
        kernel = resolve_predict_kernel(self.config.predict_kernel)
        if kernel == "tensorized":
            return self._predict_raw_device_tensorized(X, used)
        n = X.shape[0]
        out = np.zeros((self.K, n), np.float64)
        for k in range(self.K):
            key = (used, k, len(self.models))
            cached = self._predict_stack_cache.get(key)
            if cached is None:
                trees = [self.models[i] for i in range(used)
                         if i % self.K == k]
                if not trees:
                    continue
                stack = stack_trees(trees, binned=False)
                depth = max((t.max_depth_grown for t in trees), default=1)
                cached = self._cache_predict_stack(
                    key, (stack, max(depth, 1)))
            stack, depth = cached
            self._run_chunked(
                X, out[k],
                lambda c, _s=stack, _d=depth: predict_trees(_s, c, depth=_d))
        return out[0] if self.K == 1 else out.T

    def _predict_raw_device_tensorized(self, X: np.ndarray,
                                       used: int) -> np.ndarray:
        """One ensemble-wide traversal program for all classes (ops/
        predict.py predict_ensemble_any): `depth` fused steps instead of
        one walk per class."""
        from ..ops.predict import build_ensemble, predict_ensemble_any
        n = X.shape[0]
        key = ("ens", used, len(self.models))
        cached = self._predict_stack_cache.get(key)
        if cached is None:
            trees_by_class = [
                [self.models[i] for i in range(used) if i % self.K == k]
                for k in range(self.K)]
            stack, meta = build_ensemble(trees_by_class, binned=False)
            cached = self._cache_predict_stack(
                key, (jax.device_put(stack), meta))
        stack, meta = cached
        out = np.zeros((self.K, n), np.float64)
        self._run_chunked(
            X, out,
            lambda c: predict_ensemble_any(stack, c, meta=meta))
        return out[0] if self.K == 1 else out.T

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        self._flush_pending()
        """Raw scores for a dense matrix (rows, raw features) -> [N] or [N, K]."""
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        n = X.shape[0]
        used = self._num_used_models(num_iteration)
        force = os.environ.get("LIGHTGBM_TPU_DEVICE_PREDICT", "")
        use_dev = (force != "0"
                   and (force == "1"
                        or n * max(used, 1) >= self._DEVICE_PREDICT_MIN_WORK))
        if use_dev and used > 0:
            return self._predict_raw_device(X, used)
        out = np.zeros((self.K, n), np.float64)
        for i in range(used):
            out[i % self.K] += self.models[i].predict_raw(X)
        return out[0] if self.K == 1 else out.T

    def predict(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        raw = self.predict_raw(X, num_iteration)
        if self.objective is not None:
            return self.objective.convert_output(raw)
        return raw

    def predict_leaf_index(self, X: np.ndarray, num_iteration: int = -1
                           ) -> np.ndarray:
        """Leaf index per (row, model) — [N, num_models] int32.

        ``predict_kernel=walk`` is the host per-tree walk (exact f64
        compares); ``tensorized`` routes through the device ensemble
        leaf traversal (ops/predict.predict_ensemble_leaf) under the
        same work gating as predict_raw.  The two return IDENTICAL
        indices (tests/test_online.py leaf-parity suite): the device
        stack is built one-class-per-tree in MODEL order (the class-
        major flatten of the value kernels would silently permute
        multiclass models' columns), and the device categorical compare
        carries the host's explicit finite mask.
        """
        self._flush_pending()
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        used = self._num_used_models(num_iteration)
        from ..ops.predict import resolve_predict_kernel
        kernel = resolve_predict_kernel(self.config.predict_kernel)
        force = os.environ.get("LIGHTGBM_TPU_DEVICE_PREDICT", "")
        n = X.shape[0]
        use_dev = (kernel == "tensorized" and used > 0 and force != "0"
                   and (force == "1"
                        or n * used >= self._DEVICE_PREDICT_MIN_WORK))
        if use_dev:
            return self._predict_leaf_device(X, used)
        return np.stack([self.models[i].predict_leaf_index(X)
                         for i in range(used)], axis=1)

    def _predict_leaf_device(self, X: np.ndarray, used: int) -> np.ndarray:
        """Tensorized leaf routing: ONE ensemble traversal for all
        models (model-order stack, [T, N] leaves), chunked like the
        value kernels."""
        from ..ops.predict import predict_ensemble_leaf, stack_ensemble
        key = ("leaf", used, len(self.models))
        cached = self._predict_stack_cache.get(key)
        if cached is None:
            stack, meta = stack_ensemble(
                [[self.models[i]] for i in range(used)], binned=False)
            cached = self._cache_predict_stack(
                key, (jax.device_put(stack), meta))
        stack, meta = cached
        out = np.zeros((used, X.shape[0]), np.int32)
        self._run_chunked(
            X, out, lambda c: predict_ensemble_leaf(stack, c, meta=meta))
        return np.ascontiguousarray(out.T)

    def _num_used_models(self, num_iteration: int) -> int:
        n = len(self.models)
        if num_iteration > 0:
            ni = num_iteration + (1 if self.boost_from_average_used else 0)
            n = min(ni * self.K, n)
        return n

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split"
                           ) -> Dict[str, float]:
        """Per-feature importance (gbdt.cpp:850-872 split counts; "gain"
        sums split_gain per feature, the reference C API's
        importance_type=1)."""
        self._flush_pending()
        cnt = np.zeros(self.max_feature_idx + 1, np.float64)
        for t in self.models:
            for i in range(t.num_leaves - 1):
                if importance_type == "gain":
                    cnt[t.split_feature[i]] += float(t.split_gain[i])
                else:
                    cnt[t.split_feature[i]] += 1
        pairs = [(float(c), self.feature_names[i]
                  if i < len(self.feature_names) else f"Column_{i}")
                 for i, c in enumerate(cnt) if c > 0]
        pairs.sort(key=lambda p: -p[0])
        if importance_type == "gain":
            return {name: c for c, name in pairs}
        return {name: int(c) for c, name in pairs}

    def sub_model_name(self) -> str:
        return "tree"

    def save_model_to_string(self, num_iteration: int = -1) -> str:
        self._flush_pending()
        """LightGBM-compatible model text (gbdt.cpp:694-738)."""
        buf = io.StringIO()
        buf.write(self.sub_model_name() + "\n")
        buf.write(f"num_class={self.num_class}\n")
        buf.write(f"num_tree_per_iteration={self.K}\n")
        buf.write(f"label_index={self.label_idx}\n")
        buf.write(f"max_feature_idx={self.max_feature_idx}\n")
        if self.objective is not None:
            buf.write(f"objective={self.objective.to_string()}\n")
        if self.boost_from_average_used:
            buf.write("boost_from_average\n")
        buf.write("feature_names=" + " ".join(self.feature_names) + "\n")
        buf.write("feature_infos=" + " ".join(self.feature_infos) + "\n")
        buf.write("\n")
        used = self._num_used_models(num_iteration)
        for i in range(used):
            buf.write(f"Tree={i}\n")
            buf.write(self.models[i].to_string())
            buf.write("\n")
        buf.write("\nfeature importances:\n")
        for name, c in self.feature_importance().items():
            buf.write(f"{name}={c}\n")
        return buf.getvalue()

    def save_model_to_file(self, filename: str, num_iteration: int = -1) -> None:
        with open(filename, "w") as f:
            f.write(self.save_model_to_string(num_iteration))

    def load_model_from_string(self, model_str: str) -> None:
        """gbdt.cpp:752-848."""
        lines = model_str.splitlines()

        def find(prefix):
            for ln in lines:
                if ln.startswith(prefix):
                    return ln[len(prefix):].strip()
            return None

        nc = find("num_class=")
        if nc is not None:
            self.num_class = int(nc)
        k = find("num_tree_per_iteration=")
        self.K = int(k) if k is not None else self.num_class
        li = find("label_index=")
        if li is not None:
            self.label_idx = int(li)
        mf = find("max_feature_idx=")
        if mf is not None:
            self.max_feature_idx = int(mf)
        obj = find("objective=")
        if obj:
            self.objective = objective_from_model_string(obj, self.config)
        self.boost_from_average_used = any(
            ln.strip() == "boost_from_average" for ln in lines)
        fn = find("feature_names=")
        if fn:
            self.feature_names = fn.split()
        fi = find("feature_infos=")
        if fi:
            self.feature_infos = fi.split()
        # trees
        self.models = []
        text = "\n".join(lines)
        parts = text.split("Tree=")
        for p in parts[1:]:
            body = p.split("\n", 1)[1] if "\n" in p else ""
            stop = body.find("\nfeature importances")
            if stop >= 0:
                body = body[:stop]
            self.models.append(Tree.from_string(body))
        extra = 1 if self.boost_from_average_used else 0
        self.num_init_iteration = (len(self.models) - extra) // max(self.K, 1)
        self.iter_ = 0

    def to_json(self) -> Dict:
        """Field-for-field parity with the reference's DumpModel
        (gbdt.cpp:658-692): name, num_class, num_tree_per_iteration,
        label_index, max_feature_idx, feature_names, tree_info with a
        tree_index per entry; per-tree fields from Tree::ToJSON
        (tree.cpp:326-365).  `objective` is an extension (the reference
        omits it from the dump but needs it to reload)."""
        self._flush_pending()
        return {
            "name": self.sub_model_name(),
            "num_class": self.num_class,
            "num_tree_per_iteration": self.K,
            "label_index": self.label_idx,
            "max_feature_idx": self.max_feature_idx,
            "objective": self.objective.to_string() if self.objective else "",
            "feature_names": self.feature_names,
            "tree_info": [dict(tree_index=i, **t.to_json())
                          for i, t in enumerate(self.models)],
        }

    # -- checkpoint / resume (docs/Robustness.md) ----------------------

    def _extra_training_state(self) -> Dict:
        """Subclass hook: sampler/boosting state beyond the base GBDT's
        (GOSS key, DART drop RNG + tree weights)."""
        return {}

    def _restore_extra_training_state(self, state: Dict) -> None:
        pass

    def training_state(self) -> Dict:
        """Everything a resumed run needs to continue BITWISE where this
        one stands: the model text, the iteration/continuation counters,
        the early-stopping bests, and the exact sampler RNG state (a
        re-seeded RNG would re-draw the first bags and fork the run)."""
        self._flush_pending()
        state = {
            "version": CHECKPOINT_VERSION,
            "fingerprint": config_fingerprint(self.config),
            "boosting": self.sub_model_name(),
            "iteration": self.iter_,
            "num_init_iteration": self.num_init_iteration,
            "shrinkage_rate": self.shrinkage_rate,
            "early_stopping": [
                [name, metric, cmp, it]
                for (name, metric), (cmp, it)
                in self._early_stopping_state.items()],
            "bag_rng": _rng_state_to_json(self.bag_rng),
            "model": self.save_model_to_string(),
        }
        state.update(self._extra_training_state())
        return state

    def save_checkpoint(self, path: str,
                        extra: Optional[Dict] = None) -> None:
        """Atomic snapshot: tmp + os.replace, so a crash mid-write
        leaves the PREVIOUS checkpoint intact, never a torn one.
        ``extra`` rides along in the state dict (the CLI records a
        ``finished`` marker so reruns of a completed command no-op)."""
        from .. import log, telemetry
        from ..diagnostics import faults
        state = self.training_state()
        if extra:
            state.update(extra)
        with telemetry.span("train.checkpoint", path=path,
                            iteration=self.iter_,
                            trees=len(self.models)):
            payload = json.dumps(state)
            faults.torn_write("train.checkpoint", path, payload)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, path)
        log.debug(f"checkpoint saved to {path} (iteration {self.iter_}, "
                  f"{len(self.models)} trees)")
        faults.check("train.after_checkpoint")

    def restore_training_state(self, state: Dict) -> None:
        """Apply a checkpoint's counters + RNG state.  Call AFTER
        ``load_model_from_string(state['model'])`` + ``reset_training_data``
        (which replays the restored trees onto the training/valid
        scores) — this restores what the replay cannot."""
        from ..log import LightGBMError
        fp = config_fingerprint(self.config)
        if state.get("fingerprint") != fp:
            raise LightGBMError(
                "checkpoint was written under a different training "
                "config (fingerprint mismatch); resuming would silently "
                "mix recipes — delete the checkpoint to start fresh, or "
                "restore the original parameters")
        if state.get("boosting") != self.sub_model_name():
            raise LightGBMError(
                f"checkpoint holds a {state.get('boosting')!r} model, "
                f"this run is {self.sub_model_name()!r}")
        self.iter_ = int(state["iteration"])
        self.num_init_iteration = int(state.get("num_init_iteration", 0))
        self.shrinkage_rate = float(state["shrinkage_rate"])
        self._early_stopping_state = {
            (name, metric): (float(cmp), int(it))
            for name, metric, cmp, it in state.get("early_stopping", [])}
        if state.get("bag_rng"):
            self.bag_rng.set_state(_rng_state_from_json(state["bag_rng"]))
        self._restore_extra_training_state(state)

    def resume_from_checkpoint(self, state: Dict, train_set: Dataset,
                               objective: Optional[Objective] = None) -> int:
        """One-call resume: load the checkpoint model, replay it onto
        fresh training scores, restore counters/RNG.  Returns the
        iteration to continue from.  Valid sets added AFTER this call
        replay the restored model automatically (add_valid does)."""
        from .. import telemetry
        with telemetry.span(
                "train.resume",
                checkpoint_iteration=int(state.get("iteration", 0))) as sp:
            self.load_model_from_string(state["model"])
            self._replay_kernel = "walk"  # order-exact replay (__init__)
            self.reset_training_data(train_set, objective)
            self.restore_training_state(state)
            sp.set(trees=len(self.models))
        return self.iter_


def create_boosting(config: Config, model_file: str = "") -> "GBDT":
    """Factory (boosting.cpp:29-71): gbdt | dart | goss, with model-file
    resume reading the first line as the submodel type."""
    from .dart import DART
    from .goss import GOSS
    table = {"gbdt": GBDT, "tree": GBDT, "dart": DART, "goss": GOSS}
    btype = config.boosting_type
    model_str = ""
    if model_file:
        with open(model_file) as f:
            model_str = f.read()
        first = model_str.split("\n", 1)[0].strip()
        if first in table:
            btype = first
    if btype not in table:
        raise ValueError(f"unknown boosting type: {btype}")
    gbdt = table[btype](config)
    if model_str:
        gbdt.load_model_from_string(model_str)
    return gbdt
