"""GOSS: Gradient-based One-Side Sampling.

Parity with /root/reference/src/boosting/goss.hpp: replaces bagging — keep
the top `top_rate` fraction of rows by |g*h|, sample `other_rate` of the
rest and amplify their gradients/hessians by (1-a)/b (goss.hpp:79-124);
sampling is skipped for the first 1/learning_rate iterations (goss.hpp:129).

TPU mapping: the per-thread ArgMaxAtK partial selection becomes one
`jax.lax.top_k` on |g*h| summed over classes; the amplification is a
masked elementwise multiply.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from .gbdt import GBDT


@functools.partial(jax.jit, static_argnames=("top_k", "other_k", "cap"))
def _goss_select(gradients: jax.Array, hessians: jax.Array, rand_key,
                 *, top_k: int, other_k: int, cap: int):
    """Returns (bag_idx [cap] padded with N, amplified g, h)."""
    K, N = gradients.shape
    score = jnp.sum(jnp.abs(gradients * hessians), axis=0)
    # top_k selection
    _, top_idx = jax.lax.top_k(score, top_k)
    # sample other_k of the rest uniformly: use random keys on the
    # complement via masked scores
    mask_top = jnp.zeros(N, bool).at[top_idx].set(True)
    u = jax.random.uniform(rand_key, (N,))
    u = jnp.where(mask_top, -1.0, u)  # exclude top rows
    _, other_idx = jax.lax.top_k(u, other_k)
    multiply = jnp.ones(N, jnp.float32)
    amp = (1.0 - top_k / N) / max(other_k / N, 1e-30) if N else 1.0
    multiply = multiply.at[other_idx].set(amp)
    sel = jnp.concatenate([top_idx, other_idx]).astype(jnp.int32)
    sel = jnp.sort(sel)
    pad = jnp.full((cap - sel.shape[0],), N, jnp.int32)
    bag = jnp.concatenate([sel, pad])
    g = gradients * multiply[None, :]
    h = hessians * multiply[None, :]
    return bag, g, h


class GOSS(GBDT):
    def __init__(self, config: Config, train_set=None, objective=None):
        if config.bagging_freq > 0 and config.bagging_fraction < 1.0:
            raise ValueError("cannot use bagging in GOSS")
        super().__init__(config, train_set, objective)
        self._goss_key = jax.random.PRNGKey(config.bagging_seed)

    def sub_model_name(self) -> str:
        return "goss"

    def _extra_training_state(self):
        # the raw uint32 key words; jax.random.key_data unwraps typed
        # keys, raw legacy keys pass through np.asarray unchanged
        key = self._goss_key
        try:
            key = jax.random.key_data(key)
        except TypeError:
            pass
        return {"goss_key":
                jax.device_get(key).astype(np.uint32).tolist()}

    def _restore_extra_training_state(self, state):
        if "goss_key" in state:
            self._goss_key = jnp.asarray(
                np.asarray(state["goss_key"], np.uint32))

    def train_one_iter(self, gradient=None, hessian=None,
                       is_eval: bool = False) -> bool:
        self._boost_from_average()
        if gradient is None or hessian is None:
            gradient, hessian = self.boosting_gradients()
        cfg = self.config
        n = self.num_data
        top_k = max(int(n * cfg.top_rate), 1)
        other_k = max(int(n * cfg.other_rate), 1)
        # skip sampling during warmup (goss.hpp:129)
        warmup = int(1.0 / max(cfg.learning_rate, 1e-12))
        if self.iter_ >= warmup and top_k + other_k < n:
            self._goss_key, sub = jax.random.split(self._goss_key)
            cnt = top_k + other_k
            cap = min(1 << max(cnt - 1, 1).bit_length(), n)
            cap = max(cap, cnt)
            bag, gradient, hessian = _goss_select(
                gradient, hessian, sub, top_k=top_k, other_k=other_k, cap=cap)
            self.bag_idx = bag
            self.bag_cnt = cnt
            self.need_bagging = True
            self._goss_active = True
        else:
            self.bag_idx = None
            self.bag_cnt = n
            self.need_bagging = False
            self._goss_active = False
        return GBDT.train_one_iter(self, gradient, hessian, is_eval)

    def _bagging(self, iter_):
        return  # bagging replaced by GOSS selection above
