"""Host-side feature binning (BinMapper).

Behavioral parity with the reference's BinMapper::FindBin
(/root/reference/src/io/bin.cpp:67-240):

- numerical features: distinct-value bins when few distinct values, else
  greedy count-balanced boundaries with "big count" values pinned to their
  own bin; zero is injected as a distinct value with the implied zero count;
  `min_data_in_bin` merging; last upper bound is +inf.
- categorical features: categories sorted by frequency, kept until covering
  98% of samples (and at least max_bin categories when available).
- trivial-feature filtering (NeedFilter, bin.cpp:47-65).

The output is a plain-python BinMapper per feature; the device-side Dataset
packs `value -> bin` results into a [num_features, num_rows] integer array
(see dataset.py).  This replaces the reference's Bin/DenseBin/SparseBin
class zoo: on TPU everything is one dense HBM-resident array.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

NUMERICAL = 0
CATEGORICAL = 1


@dataclass
class BinMapper:
    bin_type: int = NUMERICAL
    num_bin: int = 1
    is_trivial: bool = True
    # numerical
    bin_upper_bound: np.ndarray = field(default_factory=lambda: np.array([np.inf]))
    # categorical: bin i holds category bin_2_categorical[i] (the inverse
    # map is the sorted lookup table value_to_bin builds lazily)
    bin_2_categorical: List[int] = field(default_factory=list)
    min_val: float = 0.0
    max_val: float = 0.0
    default_bin: int = 0
    sparse_rate: float = 0.0

    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin (reference bin.h:418-440).  NaN maps to
        value 0 (v2.0-era missing handling; searchsorted would otherwise
        return an out-of-range bin).  Unseen categories map to bin 0."""
        values = np.asarray(values, dtype=np.float64)
        values = np.where(np.isnan(values), 0.0, values)
        if self.bin_type == NUMERICAL:
            return np.searchsorted(self.bin_upper_bound, values, side="left").astype(
                np.int32)
        # categorical: one searchsorted over the sorted category table
        # instead of a Python loop per category (Expo-scale data has
        # hundreds of categories x millions of rows)
        cs = getattr(self, "_cat_sorted", None)
        # rebuild when the category list changed since the table was
        # built; the snapshot tuple compares by VALUE, so in-place
        # element mutation is caught too (not just replacement/append)
        snap = tuple(self.bin_2_categorical)
        if cs is None or cs[2] != snap:
            cats = np.asarray(self.bin_2_categorical, np.int64)
            order = np.argsort(cats)
            cs = (cats[order], np.arange(len(cats), dtype=np.int32)[order],
                  snap)
            self._cat_sorted = cs
        cats_sorted, bins_sorted = cs[0], cs[1]
        iv = values.astype(np.int64)
        pos = np.clip(np.searchsorted(cats_sorted, iv), 0,
                      max(len(cats_sorted) - 1, 0))
        if len(cats_sorted) == 0:
            return np.zeros(values.shape, np.int32)
        return np.where(cats_sorted[pos] == iv, bins_sorted[pos],
                        np.int32(0)).astype(np.int32)

    def bin_to_value(self, b: int) -> float:
        """Real-valued threshold stored in the model text for bin `b`."""
        if self.bin_type == NUMERICAL:
            return float(self.bin_upper_bound[min(b, self.num_bin - 1)])
        return float(self.bin_2_categorical[min(b, len(self.bin_2_categorical) - 1)])

    def feature_info(self) -> str:
        """`feature_infos` model-header entry (gbdt.cpp:715: [min:max] or cat list)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == NUMERICAL:
            return f"[{self.min_val:g}:{self.max_val:g}]"
        return ":".join(str(c) for c in self.bin_2_categorical)


def _distinct_with_zero(sample_values: np.ndarray, total_sample_cnt: int
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Distinct values + counts with zero injected at the right rank.

    `sample_values` are the NON-ZERO sampled values; zeros are implied
    (reference bin.cpp:70-103 treats zero_cnt = total - num_sampled).
    """
    sample_values = np.asarray(sample_values, dtype=np.float64)
    sample_values = sample_values[~np.isnan(sample_values)]
    zero_cnt = int(total_sample_cnt - sample_values.size)
    if sample_values.size == 0:
        return np.array([0.0]), np.array([max(zero_cnt, 1)], dtype=np.int64)
    vals, counts = np.unique(sample_values, return_counts=True)
    if zero_cnt > 0 and not np.any(vals == 0.0):
        pos = int(np.searchsorted(vals, 0.0))
        vals = np.insert(vals, pos, 0.0)
        counts = np.insert(counts, pos, zero_cnt)
    elif zero_cnt > 0:
        counts[vals == 0.0] += zero_cnt
    return vals, counts.astype(np.int64)


def _numerical_bins(vals: np.ndarray, counts: np.ndarray, total_sample_cnt: int,
                    max_bin: int, min_data_in_bin: int) -> Tuple[np.ndarray, List[int]]:
    """Greedy count-balanced boundaries (reference bin.cpp:109-186)."""
    n_distinct = vals.size
    cnt_in_bin: List[int] = []
    if n_distinct <= max_bin:
        ub: List[float] = []
        cur = 0
        for i in range(n_distinct - 1):
            cur += int(counts[i])
            if cur >= min_data_in_bin:
                ub.append((vals[i] + vals[i + 1]) / 2.0)
                cnt_in_bin.append(cur)
                cur = 0
        cur += int(counts[-1])
        cnt_in_bin.append(cur)
        ub.append(np.inf)
        return np.array(ub), cnt_in_bin

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_sample_cnt // min_data_in_bin))
    mean_bin_size = total_sample_cnt / max_bin
    zero_idx = np.flatnonzero(vals == 0.0)
    zero_cnt = int(counts[zero_idx[0]]) if zero_idx.size else 0
    if zero_cnt > mean_bin_size:
        non_zero_cnt = total_sample_cnt - zero_cnt
        max_bin = min(max_bin, 1 + non_zero_cnt // max(min_data_in_bin, 1))
    max_bin = max(int(max_bin), 1)

    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = total_sample_cnt - int(counts[is_big].sum())
    if rest_bin_cnt > 0:
        mean_bin_size = rest_sample_cnt / rest_bin_cnt

    if not is_big.any():
        # Fast path for the dominant continuous-data case (no value holds
        # >= a mean bin's worth of samples): the greedy scan reduces to
        # "emit a boundary where the count cumsum crosses the adaptive
        # threshold", which is one searchsorted per EMITTED BIN (<= 255)
        # instead of one Python iteration per DISTINCT VALUE (up to the
        # full sample count).  Emission-for-emission identical to the
        # general loop below: cur >= mean_bin_size with
        # mean = remaining_samples / remaining_bins recomputed per bin.
        # float64 cumsum: exact for any realistic count (< 2^53) and avoids
        # an int->float array promotion copy inside every searchsorted
        cumsum = np.cumsum(counts[: n_distinct - 1]).astype(np.float64)
        n_scan = cumsum.size
        upper_i: List[int] = []
        cum_prev = 0
        rest_bins = max_bin
        while len(upper_i) < max_bin - 1 and rest_bins > 0:
            mean = (total_sample_cnt - cum_prev) / rest_bins
            i = int(cumsum.searchsorted(cum_prev + mean, side="left"))
            if i >= n_scan:
                break
            upper_i.append(i)
            cnt_in_bin.append(int(cumsum[i]) - cum_prev)
            cum_prev = int(cumsum[i])
            rest_bins -= 1
        cnt_in_bin.append(total_sample_cnt - cum_prev)
        nb = len(upper_i) + 1
        ub = np.empty(nb)
        for k in range(nb - 1):
            ub[k] = (vals[upper_i[k]] + vals[upper_i[k] + 1]) / 2.0
        ub[nb - 1] = np.inf
        return ub, cnt_in_bin

    upper: List[float] = []
    lower: List[float] = [float(vals[0])]
    cur = 0
    bin_cnt = 0
    for i in range(n_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur += int(counts[i])
        if (is_big[i] or cur >= mean_bin_size or
                (is_big[i + 1] and cur >= max(1.0, mean_bin_size * 0.5))):
            upper.append(float(vals[i]))
            cnt_in_bin.append(cur)
            bin_cnt += 1
            lower.append(float(vals[i + 1]))
            if bin_cnt >= max_bin - 1:
                break
            cur = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                if rest_bin_cnt > 0:
                    mean_bin_size = rest_sample_cnt / rest_bin_cnt
    # remaining samples go to the last bin
    consumed = sum(cnt_in_bin)
    cnt_in_bin.append(int(total_sample_cnt - consumed))
    bin_cnt += 1
    ub = np.empty(bin_cnt)
    for i in range(bin_cnt - 1):
        ub[i] = (upper[i] + lower[i + 1]) / 2.0
    ub[bin_cnt - 1] = np.inf
    return ub, cnt_in_bin


def _need_filter(cnt_in_bin: Sequence[int], total_cnt: int, filter_cnt: int,
                 bin_type: int) -> bool:
    """A feature is trivial if no split leaves >= filter_cnt on both sides
    (reference bin.cpp:47-65)."""
    if bin_type == NUMERICAL:
        sum_left = 0
        for c in cnt_in_bin[:-1]:
            sum_left += c
            if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                return False
    else:
        for c in cnt_in_bin[:-1]:
            if c >= filter_cnt and total_cnt - c >= filter_cnt:
                return False
    return True


def find_bin(sample_values: np.ndarray, total_sample_cnt: int, max_bin: int,
             min_data_in_bin: int = 3, min_split_data: int = 20,
             bin_type: int = NUMERICAL) -> BinMapper:
    """Construct a BinMapper from sampled (non-zero) values of one feature.

    Mirrors reference BinMapper::FindBin (bin.cpp:67-240).
    """
    vals, counts = _distinct_with_zero(sample_values, total_sample_cnt)
    return find_bin_from_distinct(vals, counts, total_sample_cnt, max_bin,
                                  min_data_in_bin, min_split_data, bin_type)


def find_bin_from_distinct(vals: np.ndarray, counts: np.ndarray,
                           total_sample_cnt: int, max_bin: int,
                           min_data_in_bin: int = 3, min_split_data: int = 20,
                           bin_type: int = NUMERICAL) -> BinMapper:
    """BinMapper from an already-built distinct-value summary (sorted
    `vals` with per-value `counts`, zero already injected).  The body of
    `find_bin`, exposed so the mergeable quantile sketches
    (sharded/sketch.py) can reuse the exact same greedy boundary logic
    on their weighted summaries — a sketch that still holds every
    distinct value yields the bitwise-identical mapper."""
    m = BinMapper(bin_type=bin_type)
    counts = np.asarray(counts, np.int64)
    m.min_val, m.max_val = float(vals[0]), float(vals[-1])

    if bin_type == NUMERICAL:
        ub, cnt_in_bin = _numerical_bins(vals, counts, total_sample_cnt, max_bin,
                                         min_data_in_bin)
        m.bin_upper_bound = ub
        m.num_bin = int(ub.size)
    else:
        ivals = vals.astype(np.int64)
        # merge duplicates after int cast
        ivals_u, inv = np.unique(ivals, return_inverse=True)
        icounts = np.zeros(ivals_u.size, dtype=np.int64)
        np.add.at(icounts, inv, counts)
        order = np.argsort(-icounts, kind="stable")
        ivals_u, icounts = ivals_u[order], icounts[order]
        cut_cnt = int(total_sample_cnt * 0.98)
        eff_max_bin = min(ivals_u.size, max_bin)
        used_cnt = 0
        nb = 0
        while (used_cnt < cut_cnt or nb < eff_max_bin) and nb < ivals_u.size:
            m.bin_2_categorical.append(int(ivals_u[nb]))
            used_cnt += int(icounts[nb])
            nb += 1
        m.num_bin = nb
        cnt_in_bin = [int(c) for c in icounts[:nb]]
        cnt_in_bin[-1] += int(total_sample_cnt - used_cnt)

    m.is_trivial = m.num_bin <= 1
    if not m.is_trivial and _need_filter(cnt_in_bin, total_sample_cnt,
                                         min_split_data, bin_type):
        m.is_trivial = True
    if not m.is_trivial:
        m.default_bin = int(m.value_to_bin(np.array([0.0]))[0])
        idx = min(m.default_bin, len(cnt_in_bin) - 1)
        m.sparse_rate = cnt_in_bin[idx] / total_sample_cnt
    return m


# ----------------------------------------------------------------------------
# Exclusive Feature Bundling (EFB)
#
# The reference packs mutually-exclusive sparse features into shared
# FeatureGroups (src/io/dataset.cpp FindGroups/FastFeatureBundling); the
# sparse-GPU boosting literature (arXiv:1706.08359, arXiv:1806.11248) shows
# compacting exclusive columns is where dense-histogram accelerators win.
# Here a bundle is ONE stored column: bin 0 means "every member at its
# default bin", and member f's non-default bins occupy the slot range
# [offset_f, offset_f + num_bin_f - 1).  Slot packing removes the default
# bin from the middle of the range but keeps the bin ORDER, so a numerical
# threshold maps to one contiguous slot interval (ops/split.py
# bundle_predicate_params) and histograms unbundle by gather + a
# total-minus-sum reconstruction of the default bin.
# ----------------------------------------------------------------------------

@dataclass
class BundlePlan:
    """Static description of how used features map onto stored columns.

    All per-feature arrays are indexed by the INNER (used-feature) index.
    """
    feat_col: np.ndarray      # [F] int32 stored column holding feature k
    feat_offset: np.ndarray   # [F] int32 first slot of k (0 if not packed)
    feat_default: np.ndarray  # [F] int32 default bin of k
    feat_nslots: np.ndarray   # [F] int32 non-default slot count (nb - 1)
    feat_packed: np.ndarray   # [F] bool  k shares its column
    col_num_bins: np.ndarray  # [C] int32 bins per stored column
    est_conflict_rate: float = 0.0   # sampled estimate used by the planner
    sample_rows: int = 0

    @property
    def num_columns(self) -> int:
        return int(self.col_num_bins.shape[0])

    @property
    def num_features(self) -> int:
        return int(self.feat_col.shape[0])

    @property
    def num_packed(self) -> int:
        return int(self.feat_packed.sum())

    @property
    def num_bundles(self) -> int:
        """Multi-feature bundles (columns holding >= 2 features)."""
        return int(len(set(self.feat_col[self.feat_packed])))

    def feat_table(self) -> np.ndarray:
        """[5, F] float32 (col, offset, default, nslots, packed) — the
        device lookup table ops/split.bundle_predicate_params and the
        score-updater walk consume.  Exact in f32 (all values < 2^24)."""
        return np.stack([
            self.feat_col.astype(np.float32),
            self.feat_offset.astype(np.float32),
            self.feat_default.astype(np.float32),
            self.feat_nslots.astype(np.float32),
            self.feat_packed.astype(np.float32)])

    def unbundle_tables(self, num_bins: np.ndarray, B: int,
                        num_columns_padded: int = 0
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """Gather tables turning a bundled histogram [C, 3, B] into the
        original per-feature histogram [F, 3, B] (ops/split.unbundle_hist).

        Returns (src [F, B] int32 flat indices into the [C*B + 1] padded
        store histogram — index C*B is a zero sentinel — and dmask [F, B]
        bool marking each packed feature's default-bin slot, which is
        reconstructed as leaf_total - sum(other bins)).

        num_columns_padded: the column count of the histograms that will
        be unbundled, when the learner pads the store beyond
        `num_columns` (the rounds learner's int8 layout aligns columns
        to 32) — the zero sentinel must sit past the PADDED columns, or
        it would gather a padded column's bin-0 totals instead of zero."""
        F = self.num_features
        C = max(self.num_columns, int(num_columns_padded))
        sent = C * B
        src = np.full((F, B), sent, np.int32)
        dmask = np.zeros((F, B), bool)
        b = np.arange(B)
        for k in range(F):
            nb = int(num_bins[k])
            col = int(self.feat_col[k])
            if not self.feat_packed[k]:
                valid = b < nb
                src[k, valid] = col * B + b[valid]
                continue
            d = int(self.feat_default[k])
            off = int(self.feat_offset[k])
            valid = (b < nb) & (b != d)
            slot = b - (b > d)
            src[k, valid] = col * B + off + slot[valid]
            if d < nb:
                dmask[k, d] = True
        return src, dmask


def plan_bundles(sample_bins: np.ndarray, num_bins: np.ndarray,
                 default_bins: np.ndarray, max_conflict_rate: float,
                 max_bundle_bins: int = 256, max_probe: int = 128
                 ) -> Optional[BundlePlan]:
    """Greedy conflict-graph bundling over SAMPLED binned columns.

    sample_bins : [F, S] int original bin ids of up to S sampled rows
    num_bins / default_bins : [F] per-used-feature bin count / default bin

    Mirrors the reference's FindGroups greedy first-fit (dataset.cpp):
    features sorted by non-default count descending; a feature joins the
    first bundle whose accumulated conflict count stays within
    `max_conflict_rate * S` and whose bin budget (`max_bundle_bins`, the
    uint8-store / 256-lane kernel ceiling) is not exceeded.  Dense
    features (non-default fraction > 0.5) never enter the conflict graph
    — they become singleton columns immediately, which keeps planning
    O(sparse^2) instead of O(F^2) on dense data.

    Returns None when no bundle would hold >= 2 features (store unchanged).
    """
    F, S = sample_bins.shape
    if F == 0 or S == 0:
        return None
    nd = sample_bins != default_bins[:, None]           # [F, S] non-default
    nd_cnt = nd.sum(axis=1)
    budget = int(max_conflict_rate * S)
    cand = [k for k in range(F)
            if nd_cnt[k] <= 0.5 * S and 2 <= num_bins[k] <= max_bundle_bins]
    cand.sort(key=lambda k: -int(nd_cnt[k]))

    bundles: List[List[int]] = []       # member inner indices
    b_nd: List[np.ndarray] = []         # union non-default mask per bundle
    b_bins: List[int] = []              # 1 + sum(nb - 1)
    b_conf: List[int] = []              # accumulated conflict count
    for k in cand:
        extra = int(num_bins[k]) - 1
        placed = False
        for gi in range(min(len(bundles), max_probe)):
            if b_bins[gi] + extra > max_bundle_bins:
                continue
            c = int(np.count_nonzero(b_nd[gi] & nd[k]))
            if b_conf[gi] + c <= budget:
                bundles[gi].append(k)
                b_nd[gi] |= nd[k]
                b_bins[gi] += extra
                b_conf[gi] += c
                placed = True
                break
        if not placed:
            bundles.append([k])
            b_nd.append(nd[k].copy())
            b_bins.append(1 + extra)
            b_conf.append(0)

    if not any(len(m) > 1 for m in bundles):
        return None

    feat_col = np.zeros(F, np.int32)
    feat_offset = np.zeros(F, np.int32)
    feat_default = np.asarray(default_bins, np.int32).copy()
    feat_nslots = np.asarray(num_bins, np.int32) - 1
    feat_packed = np.zeros(F, bool)
    col_bins: List[int] = []
    in_bundle = set()
    for members, nb_total in zip(bundles, b_bins):
        if len(members) < 2:
            continue
        col = len(col_bins)
        off = 1
        for k in members:
            in_bundle.add(k)
            feat_col[k] = col
            feat_offset[k] = off
            feat_packed[k] = True
            off += int(num_bins[k]) - 1
        col_bins.append(nb_total)
    for k in range(F):
        if k not in in_bundle:
            feat_col[k] = len(col_bins)
            col_bins.append(int(num_bins[k]))
    return BundlePlan(
        feat_col=feat_col, feat_offset=feat_offset,
        feat_default=feat_default, feat_nslots=feat_nslots,
        feat_packed=feat_packed,
        col_num_bins=np.asarray(col_bins, np.int32),
        est_conflict_rate=float(sum(b_conf)) / max(S, 1),
        sample_rows=S)


def pack_bundle_column(b: np.ndarray, default_bin: int, offset: int,
                       out: np.ndarray) -> int:
    """Fold one member feature's original bins `b` into the bundle column
    `out` (in place, last writer wins on conflicts).  Returns the number
    of conflicting rows observed (slots already non-default)."""
    ndm = b != default_bin
    conflicts = int(np.count_nonzero(ndm & (out != 0)))
    slot = b - (b > default_bin)
    np.copyto(out, (offset + slot).astype(out.dtype), where=ndm)
    return conflicts


def allocate_bin_budgets(distinct: np.ndarray, mass: np.ndarray,
                         total_budget: int, min_bin: int = 2,
                         max_bin_cap: int = 255) -> np.ndarray:
    """Split a GLOBAL bin budget across features by distinct-value/mass
    share (the Vectorized Adaptive Histograms allocation rule,
    arXiv:2603.00326): feature f's weight is sqrt(distinct_f * mass_f)
    — mass being the non-default sample count, where split resolution
    actually matters — water-filled into [min(min_bin, distinct),
    min(distinct, max_bin_cap)] so no feature holds more bins than it
    has distinct values and none exceeds the uint8-store cap.  The
    result is a per-feature `max_bin` vector for find_bin;
    deterministic (pure integer numpy) so every rank/run agrees.

    distinct / mass : [F] per-feature distinct-value and non-default
        sample counts (zero injected — a constant feature has 1).
    total_budget : global bin budget (uniform max_bin spends about
        sum(min(distinct, max_bin)) of it).
    """
    d = np.maximum(np.asarray(distinct, np.int64), 1)
    m = np.maximum(np.asarray(mass, np.int64), 1)
    w = np.sqrt(d.astype(np.float64) * m.astype(np.float64))
    cap = np.minimum(d, max_bin_cap)
    lo = np.minimum(cap, min_bin)
    alloc = lo.astype(np.int64).copy()
    total = max(int(total_budget), int(lo.sum()))
    # proportional waterfill; features hitting their cap release budget
    # back to the pool (few rounds suffice: each round either exhausts
    # the remainder or caps at least one feature)
    for _ in range(64):
        rem = total - int(alloc.sum())
        if rem <= 0:
            break
        room = cap - alloc
        open_w = np.where(room > 0, w, 0.0)
        sw = open_w.sum()
        if sw <= 0:
            break
        add = np.minimum(np.floor(rem * open_w / sw).astype(np.int64),
                         room)
        if int(add.sum()) == 0:
            # sub-unit remainder: hand out one bin each down the weight
            # order (stable, so ties resolve by feature index)
            order = np.argsort(-open_w, kind="stable")
            for j in order:
                if rem <= 0:
                    break
                if room[j] > 0:
                    alloc[j] += 1
                    rem -= 1
            break
        alloc += add
    return np.minimum(alloc, cap).astype(np.int32)


def find_bin_mappers(X: np.ndarray, max_bin: int, min_data_in_bin: int,
                     min_split_data: int, categorical: Sequence[int] = (),
                     sample_cnt: int = 200000, seed: int = 1,
                     bin_budget: int = 0) -> List[BinMapper]:
    """Find bin mappers for all columns of a dense matrix.

    Equivalent of DatasetLoader::ConstructBinMappersFromTextData
    (dataset_loader.cpp:661-837) for in-memory data: sample up to
    `sample_cnt` rows, then per-feature FindBin on the non-zero sampled
    values.  ``bin_budget > 0`` replaces the uniform per-feature
    max_bin with the adaptive allocation of `allocate_bin_budgets`
    (the global budget split by distinct-value/mass share, read off
    each column's distinct-value summary — computed ONCE per column
    and shared with the boundary search via find_bin_from_distinct).
    """
    n, f = X.shape
    rng = np.random.RandomState(seed)
    if n > sample_cnt:
        idx = np.sort(rng.choice(n, size=sample_cnt, replace=False))
        sample = X[idx]
        total = sample_cnt
    else:
        sample = X
        total = n
    cats = set(int(c) for c in categorical)
    summaries = []
    for j in range(f):
        col = np.asarray(sample[:, j], dtype=np.float64)
        nonzero = col[col != 0.0]      # NaNs dropped by _distinct_*
        summaries.append(_distinct_with_zero(nonzero, total))
    if bin_budget > 0 and f:
        # distinct incl. the implied zero = vals.size; mass (non-zero
        # sample count) = total minus the zero value's count
        d = np.asarray([v.size for v, _ in summaries], np.int64)
        m = np.asarray(
            [total - int(c[v == 0.0].sum()) for v, c in summaries],
            np.int64)
        budgets = allocate_bin_budgets(d, m, bin_budget)
    else:
        budgets = None
    mappers = []
    for j, (vals, counts) in enumerate(summaries):
        bt = CATEGORICAL if j in cats else NUMERICAL
        mb = int(budgets[j]) if budgets is not None else max_bin
        mappers.append(find_bin_from_distinct(
            vals, counts, total, mb, min_data_in_bin, min_split_data,
            bt))
    return mappers
