"""Shared jitted micro-helpers that keep the hot path transfer-guard
clean.

Eager slicing/indexing/padding with Python scalars lowers to
dynamic_slice / scatter / pad whose start-index or fill operand is
uploaded host→device on EVERY call — one implicit transfer per boosting
iteration per site, flagged by the sanitizer
(diagnostics/sanitize.py) and measured as a dispatch stall on remote
TPUs.  Jitting with static bounds turns those scalars into trace
constants.  One home for the pattern, so the learners, the score
updater, and the metrics cannot drift apart (the same reason
learner/common.py exists for the split-search setup).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("pad",))
def pad_rows_dev(x: jax.Array, *, pad: int) -> jax.Array:
    """Zero-pad the trailing row axis on device (the eager jnp.pad
    uploads its fill scalar per call)."""
    return jnp.pad(x, (0, pad))


@functools.partial(jax.jit, static_argnames=("n",))
def slice_rows_dev(x: jax.Array, *, n: int) -> jax.Array:
    """x[:n] with a trace-constant bound (the eager slice lowers to
    dynamic_slice and uploads its start index per call)."""
    return x[:n]


@jax.jit
def bag_mask_dev(bag_idx: jax.Array, base_mask: jax.Array) -> jax.Array:
    """Bag membership mask on device (sentinel indices drop): jitted so
    the 1.0 fill is a trace constant, not a per-redraw scalar upload."""
    return (jnp.zeros_like(base_mask).at[bag_idx].set(1.0, mode="drop")
            * base_mask)


@functools.lru_cache(maxsize=None)
def unstack_scalars(n: int):
    """Jitted [n] vector → n lazy 0-d device scalars in ONE program
    (eager v[i] uploads a dynamic_slice start index per element).
    Returns the compiled callable; cached per n."""
    return jax.jit(lambda v: tuple(v[i] for i in range(n)))
