"""Phase-bucketed wall-clock tracing (reference TIMETAG subsystem:
std::chrono accumulators over boosting/bagging/tree/score/metric phases,
gbdt.cpp:20-29,50-60, serial_tree_learner.cpp:10-17, logged at teardown)
plus a hook into jax.profiler for device traces.

Enable with LIGHTGBM_TPU_TIMETAG=1 (compile-time macro in the reference →
environment switch here); totals print at interpreter exit or via
`report()`.
"""
from __future__ import annotations

import atexit
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

ENABLED = os.environ.get("LIGHTGBM_TPU_TIMETAG", "0") not in ("0", "", "false")

_totals: Dict[str, float] = defaultdict(float)
_counts: Dict[str, int] = defaultdict(int)


@contextmanager
def phase(name: str) -> Iterator[None]:
    """Accumulate wall-clock under `name`.  No-op unless enabled."""
    if not ENABLED:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _totals[name] += time.perf_counter() - t0
        _counts[name] += 1


def add(name: str, seconds: float) -> None:
    if ENABLED:
        _totals[name] += seconds
        _counts[name] += 1


def report() -> Dict[str, float]:
    """Totals per phase; also printed when TIMETAG is on (reference logs
    at destructor time)."""
    if ENABLED and _totals:
        print("[LightGBM-TPU] [Info] ===== timer totals =====", flush=True)
        for name in sorted(_totals, key=_totals.get, reverse=True):
            print(f"[LightGBM-TPU] [Info] {name}: {_totals[name]:.4f}s "
                  f"({_counts[name]} calls)", flush=True)
    return dict(_totals)


def reset() -> None:
    _totals.clear()
    _counts.clear()


if ENABLED:
    atexit.register(report)


@contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """jax.profiler trace wrapper — the TPU analog of the reference's GPU
    transfer/kernel timing logs (gpu_tree_learner.cpp:538-542).  View with
    TensorBoard or xprof."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
