"""Phase-bucketed wall-clock tracing (reference TIMETAG subsystem:
std::chrono accumulators over boosting/bagging/tree/score/metric phases,
gbdt.cpp:20-29,50-60, serial_tree_learner.cpp:10-17, logged at teardown)
plus a hook into jax.profiler for device traces.

Enable with LIGHTGBM_TPU_TIMETAG=1 (compile-time macro in the reference →
environment switch here); totals print at interpreter exit or via
`report()`.
"""
from __future__ import annotations

import atexit
import math
import os
import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Deque, Dict, Iterator, Optional, Tuple

ENABLED = os.environ.get("LIGHTGBM_TPU_TIMETAG", "0") not in ("0", "", "false")

# telemetry.configure() flips this so the phase accumulators run (and
# feed per-iteration records + /metrics) whenever span tracing is on,
# without requiring the LIGHTGBM_TPU_TIMETAG env switch too
_PHASES_FORCED = False


def force_phases(on: bool = True) -> None:
    """Force the phase accumulators on regardless of the TIMETAG env
    switch (telemetry.configure does; telemetry.reset undoes)."""
    global _PHASES_FORCED
    _PHASES_FORCED = bool(on)

_totals: Dict[str, float] = defaultdict(float)
_counts: Dict[str, int] = defaultdict(int)

# Always-on counters and bounded sample reservoirs (the serving layer's
# request/cache/latency metrics flow through these regardless of the
# TIMETAG switch — a production /stats endpoint cannot depend on a debug
# env var).  Guarded by one lock: serving increments from many threads.
_lock = threading.Lock()
_counters: Dict[str, float] = defaultdict(float)
_samples: Dict[str, Deque[float]] = {}
_SAMPLE_CAP = 4096
# one pending device scalar per name (count_deferred accumulates
# DEVICE-side, so an arbitrarily long training run holds exactly one
# live buffer per counter), folded into _counters on read
_deferred: Dict[str, object] = {}

# Canonical counter names of the data-parallel tree learners' comms
# layer, fed through count_deferred (device-side accumulation, no sync
# on the pipelined path) and read by bench.py / the MULTICHIP dryrun:
#  - HIST_ROWS_TOUCHED: rows processed by histogram kernels (global sum
#    across shards — the gathered-vs-masked live-traffic metric).
#  - HIST_EXCHANGE_BYTES: PER-DEVICE histogram-collective payload —
#    bytes of reduced histogram each device materializes per pass (the
#    full [K, F, 3, B] tensor under psum, its F/ndev slice under
#    psum_scatter), summed over passes.
#  - SPLIT_RECORDS_BYTES: per-device bytes of the psum_scatter path's
#    best-split-record allgather ([ndev, K, 11] f32 per pass; zero
#    under psum, which exchanges no records).
# The BENCH_SANITIZE divergence audit (diagnostics/sanitize.py
# DivergenceSanitizer) feeds two more counters through count():
# sanitize/divergence_checks (cross-shard fingerprint comparisons of
# the replicated tree state) and sanitize/divergences (bitwise
# mismatches — the hard-fail condition); bench.py and the MULTICHIP
# dryrun record both beside the retrace/transfer counters.
HIST_ROWS_TOUCHED = "tree/hist_rows_touched"
HIST_EXCHANGE_BYTES = "tree/hist_exchange_bytes"
SPLIT_RECORDS_BYTES = "tree/split_records_bytes"

# Canonical sparse-store counters (docs/Sparse.md), the nnz-scaling
# evidence behind the sparse-vs-dense CTR A/B:
#  - SPARSE_NNZ_TOUCHED: stored (column, bin) entries processed by the
#    nonzero-iterating histogram kernels, summed over passes (global
#    across shards, like HIST_ROWS_TOUCHED).  The dense equivalent is
#    rows_touched x store columns; the ratio is the bench gate.
#  - SPARSE_FALLBACKS: times a sparse store had to materialize its
#    dense [F_eff, N] matrix for a consumer without a sparse path
#    (feature-sharded learners, binned score replay, binary-cache
#    writes) — silent densification is an operator-visible signal.
SPARSE_NNZ_TOUCHED = "tree/sparse_nnz_touched"
SPARSE_FALLBACKS = "tree/sparse_fallbacks"

# Canonical robustness counters (docs/Robustness.md), fed through
# count() by the serving fleet's failover machinery and the registry:
#  - REGISTRY_SWAP_FAILURES: hot-swap candidates rejected (corrupt/torn
#    model files, failed compiles) — the old generation kept serving.
#  - serve.replica_failures / serve.replica_broken /
#    serve.replica_readmitted / serve.replica_probes: per-event breaker
#    transitions; serve.chunk_retries counts failed chunks re-run on a
#    healthy replica.  All surfaced at the server's /stats endpoint so
#    silent degradation is an operator-visible signal.
REGISTRY_SWAP_FAILURES = "registry/swap_failures"
SERVE_CHUNK_RETRIES = "serve.chunk_retries"
SERVE_REPLICA_FAILURES = "serve.replica_failures"
SERVE_REPLICA_BROKEN = "serve.replica_broken"
SERVE_REPLICA_READMITTED = "serve.replica_readmitted"
SERVE_REPLICA_PROBES = "serve.replica_probes"

# Canonical binned-inference counters (docs/serving.md "Binned
# inference"), fed through count() by the serving runtime's ingress
# quantization (serve_quantize=binned):
#  - SERVE_QUANTIZE_BYTES_IN: bytes of the quantized uint8/uint16
#    request buffers shipped to the device — ~4x below what the same
#    rows cost as f32, the memory-bandwidth win of fixed-point
#    traversal.
#  - SERVE_BINNED_REQUESTS: predict() calls that ran the binned kernel
#    variant (raw-variant runtimes count nothing here).
SERVE_QUANTIZE_BYTES_IN = "serve/quantize_bytes_in"
SERVE_BINNED_REQUESTS = "serve/binned_requests"

# Canonical multi-tenant catalog counters (docs/serving.md
# "Multi-tenant catalog"), fed through count() by the ModelCatalog's
# LRU budget enforcement and the registries' shadow-canary machinery:
#  - SERVE_CACHE_EVICTIONS: compiled executables dropped to fit the
#    `serve_cache_budget_mb` device-memory budget (the churn metric —
#    an evicted tenant's next request recompiles).
#  - SERVE_SHADOW_SCORED: requests double-scored on a staged candidate
#    generation (stable answered the client; the candidate's answer
#    only fed the divergence log).
#  - SERVE_SHADOW_ADOPTIONS / SERVE_SHADOW_REJECTIONS: canary verdicts
#    — candidates promoted to stable after `serve_shadow_requests`
#    comparisons vs candidates discarded (divergence over the gate, or
#    a candidate that could not score).
SERVE_CACHE_EVICTIONS = "serve/cache_evictions"
SERVE_SHADOW_SCORED = "serve/shadow_scored"
SERVE_SHADOW_ADOPTIONS = "serve/shadow_adoptions"
SERVE_SHADOW_REJECTIONS = "serve/shadow_rejections"

# Cross-model co-stacked serving (serving/superstack.py,
# docs/serving.md "Cross-model batching"):
#  - SERVE_GROUP_COMPILES: XLA compilations charged to a GROUP's shared
#    super-stack executable (the per-group labeled series rides the
#    same name) — the quantity co-stacking divides by the group size.
#  - SERVE_GROUP_RESTACKS: super-stack rebuilds after a member tenant's
#    hot swap (cache-transplanting restacks included; only restacks
#    whose program changed also show up as group compiles).
#  - SERVE_GROUP_SEGMENT_ROWS / SERVE_GROUP_STACKED_ROWS: mixed-batch
#    rows demuxed through a group executable, split by the RESOLVED
#    costack kernel — segment (per-row tree-segment gather: node math
#    ~1x a solo tenant's) vs stacked (walk-all: ~G x node math where
#    launch overhead hides it).  The per-group labeled series ride the
#    same names; summed they equal the grouped share of serve.rows.
#  - SERVE_GROUP_QUANTIZE_SHARED: rows a binned group quantized ONCE
#    against its members' shared refbin mapper set at ingress instead
#    of once per member job — the host-CPU dedup of the shared ingress
#    quantizer (rows also counted in SERVE_QUANTIZE_BYTES_IN by bytes).
SERVE_GROUP_COMPILES = "serve/group_compiles"
SERVE_GROUP_RESTACKS = "serve/group_restacks"
SERVE_GROUP_SEGMENT_ROWS = "serve/group_segment_rows"
SERVE_GROUP_STACKED_ROWS = "serve/group_stacked_rows"
SERVE_GROUP_QUANTIZE_SHARED = "serve/group_quantize_shared"

# Canonical router-tier counters (docs/Router.md), fed through count()
# by the task=route process fronting M backend serving processes:
#  - ROUTER_REQUESTS: /predict requests accepted by the router (the
#    per-model and per-backend labeled series ride the same base name).
#  - ROUTER_RETRIES: proxied dispatches that failed at the transport
#    layer and were re-run once on a different healthy backend (the
#    router-scope analogue of serve.chunk_retries).
#  - ROUTER_REJECTED: requests shed with 503 — the `route_max_inflight`
#    admission cap, or no healthy backend placeable for the model.
#  - ROUTER_BACKEND_FAILURES / ROUTER_BACKEND_BROKEN /
#    ROUTER_BACKEND_READMITTED / ROUTER_BACKEND_PROBES: per-event
#    breaker transitions of the per-backend circuit breakers (the PR 9
#    replica state machine one level up).
#  - ROUTER_REHASHES: requests whose placement (override target or
#    ring-home backend) was open-breaker and re-placed onto the next
#    healthy backend clockwise — the drain-re-placement churn metric.
ROUTER_REQUESTS = "router/requests"
ROUTER_RETRIES = "router/retries"
ROUTER_REJECTED = "router/rejected"
ROUTER_BACKEND_FAILURES = "router/backend_failures"
ROUTER_BACKEND_BROKEN = "router/backend_broken"
ROUTER_BACKEND_READMITTED = "router/backend_readmitted"
ROUTER_BACKEND_PROBES = "router/backend_probes"
ROUTER_REHASHES = "router/rehashes"

# Every canonical counter constant of this module, in one tuple: the
# Prometheus exposition (telemetry.prometheus_text) seeds each of these
# at 0 so a scrape always covers the full canonical set, and the
# counter-name lint (scripts/check_counter_names.py) enforces that call
# sites use the constants instead of re-typing the strings.
CANONICAL_COUNTERS = (
    HIST_ROWS_TOUCHED, HIST_EXCHANGE_BYTES, SPLIT_RECORDS_BYTES,
    SPARSE_NNZ_TOUCHED, SPARSE_FALLBACKS,
    REGISTRY_SWAP_FAILURES, SERVE_CHUNK_RETRIES, SERVE_REPLICA_FAILURES,
    SERVE_REPLICA_BROKEN, SERVE_REPLICA_READMITTED, SERVE_REPLICA_PROBES,
    SERVE_QUANTIZE_BYTES_IN, SERVE_BINNED_REQUESTS,
    SERVE_CACHE_EVICTIONS, SERVE_SHADOW_SCORED, SERVE_SHADOW_ADOPTIONS,
    SERVE_SHADOW_REJECTIONS, SERVE_GROUP_COMPILES, SERVE_GROUP_RESTACKS,
    SERVE_GROUP_SEGMENT_ROWS, SERVE_GROUP_STACKED_ROWS,
    SERVE_GROUP_QUANTIZE_SHARED,
    ROUTER_REQUESTS, ROUTER_RETRIES, ROUTER_REJECTED,
    ROUTER_BACKEND_FAILURES, ROUTER_BACKEND_BROKEN,
    ROUTER_BACKEND_READMITTED, ROUTER_BACKEND_PROBES, ROUTER_REHASHES,
)


def labeled(name: str, **labels) -> str:
    """Registry key for a LABELED counter/reservoir series.

    ``labeled("serve.requests", model="de")`` returns
    ``serve.requests{model="de"}``, which `telemetry.prometheus_text`
    renders as the Prometheus series
    ``lgbt_serve_requests_total{model="de"}`` — one metric FAMILY with
    one series per label set, instead of a name-mangled counter per
    tenant.  Label values must be identifier-shaped (the multi-tenant
    catalog validates model ids against ``[A-Za-z0-9._-]{1,64}`` before
    they reach here); the base name follows the same rules as unlabeled
    counters (scripts/check_counter_names.py lints `labeled` call sites
    like any other registry call)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


@contextmanager
def phase(name: str, force: bool = False) -> Iterator[None]:
    """Accumulate wall-clock under `name`.  No-op unless enabled, except
    `force=True` (serving phases) which always accumulates."""
    if not (ENABLED or force or _PHASES_FORCED):
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        with _lock:
            _totals[name] += time.perf_counter() - t0
            _counts[name] += 1


def add(name: str, seconds: float, force: bool = False) -> None:
    if ENABLED or force or _PHASES_FORCED:
        with _lock:
            _totals[name] += seconds
            _counts[name] += 1


def count(name: str, inc: float = 1.0) -> None:
    """Bump an always-on counter (thread-safe)."""
    with _lock:
        _counters[name] += inc


def count_deferred(name: str, value) -> None:
    """Accumulate a DEVICE scalar against a counter without forcing a
    host sync (the pipelined trainer must not stall on a metrics fetch
    — the device→host transfer that motivates
    _train_one_iter_pipelined).  Accumulation happens device-side (`+`
    dispatches asynchronously), so only one buffer per name stays live;
    the total is converted and folded into the counter on the next
    counter_value()/counters() read, where the caller has chosen to pay
    the sync."""
    with _lock:
        prev = _deferred.get(name)
        _deferred[name] = value if prev is None else prev + value


def _drain_deferred_locked() -> None:
    """Fold pending device totals into _counters; caller holds _lock.
    ONE batched explicit fetch for every pending counter (jax.device_get
    blocks until the values are ready; per-name float() was one sync per
    counter, and implicit under the sanitizer's transfer guard)."""
    if not _deferred:
        return
    import jax
    names = list(_deferred)
    vals = jax.device_get([_deferred[n] for n in names])
    for name, val in zip(names, vals):
        _counters[name] += float(val)
    _deferred.clear()


def counter_value(name: str) -> float:
    with _lock:
        _drain_deferred_locked()
        return _counters.get(name, 0.0)


def counters(prefix: str = "", sync: bool = True) -> Dict[str, float]:
    with _lock:
        if sync:
            _drain_deferred_locked()
        return {k: v for k, v in _counters.items() if k.startswith(prefix)}


def counters_nosync(prefix: str = "") -> Dict[str, float]:
    """Host-visible counter values WITHOUT draining the deferred device
    totals — safe on the pipelined training path (no device sync).
    `count_deferred` accumulations lag until the next counters()/
    snapshot() read pays the sync; counters recorded with count() are
    exact.  The per-iteration training telemetry reads through here."""
    return counters(prefix, sync=False)


def observe(name: str, value: float) -> None:
    """Record one sample into a bounded reservoir (for percentiles)."""
    with _lock:
        dq = _samples.get(name)
        if dq is None:
            dq = _samples[name] = deque(maxlen=_SAMPLE_CAP)
        dq.append(value)


def _summary_of(vals) -> Dict[str, float]:
    """Nearest-rank percentiles (ceil(p*n)-1) over pre-sorted samples.
    The previous ``int(p * n)`` indexing overshot nearest-rank by one
    position — p50 of [1, 2] returned 2 and p99 of 100 samples returned
    the max — which matters because p99 is the SLO number the serve
    bench gates on."""
    if not vals:
        return {"count": 0}

    def q(p: float) -> float:
        return vals[min(len(vals) - 1, max(0, math.ceil(p * len(vals)) - 1))]

    return {"count": len(vals), "p50": q(0.50), "p95": q(0.95),
            "p99": q(0.99), "max": vals[-1]}


def summary(name: str) -> Dict[str, float]:
    """count/p50/p95/p99/max over the retained samples of `name` — p99
    is the serving SLO metric the sustained-QPS bench gates on."""
    with _lock:
        vals = sorted(_samples.get(name, ()))
    return _summary_of(vals)


def snapshot() -> Tuple[Dict[str, float], Dict[str, Dict[str, float]]]:
    """ONE locked snapshot of the whole registry for a /metrics scrape:
    (counters, {name: summary}) — deferred device totals drain here
    (the scrape pays the sync, same contract as counters())."""
    with _lock:
        # graftlint: allow(blocking-under-lock) — the deferred drain syncs device buffers under _lock BY CONTRACT: the scrape pays the one sync so hot paths never do (counters_nosync is the lock-free read)
        _drain_deferred_locked()
        ctrs = dict(_counters)
        sums = {name: _summary_of(sorted(dq))
                for name, dq in _samples.items()}
    return ctrs, sums


def timings() -> Dict[str, float]:
    """Phase totals without printing (the /stats view of the TIMETAG
    accumulators)."""
    with _lock:
        return dict(_totals)


def report() -> Dict[str, float]:
    """Totals per phase; also printed when TIMETAG is on (reference logs
    at destructor time)."""
    with _lock:
        totals = dict(_totals)
        counts = dict(_counts)
    if ENABLED and totals:
        print("[LightGBM-TPU] [Info] ===== timer totals =====", flush=True)
        for name in sorted(totals, key=totals.get, reverse=True):
            print(f"[LightGBM-TPU] [Info] {name}: {totals[name]:.4f}s "
                  f"({counts[name]} calls)", flush=True)
    return totals


def reset() -> None:
    with _lock:
        _totals.clear()
        _counts.clear()
        _counters.clear()
        _samples.clear()
        _deferred.clear()


if ENABLED:
    atexit.register(report)


@contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """jax.profiler trace wrapper — the TPU analog of the reference's GPU
    transfer/kernel timing logs (gpu_tree_learner.cpp:538-542).  View with
    TensorBoard or xprof.  Also emitted as a telemetry span carrying the
    logdir, so the xprof device trace can be lined up against the host
    span timeline under the same trace id (scripts/trace_view.py)."""
    import jax

    from . import telemetry
    jax.profiler.start_trace(logdir)
    try:
        with telemetry.span("profiling.device_trace", logdir=logdir):
            yield
    finally:
        jax.profiler.stop_trace()
