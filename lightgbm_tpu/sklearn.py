"""scikit-learn wrapper interface.

Mirrors /root/reference/python-package/lightgbm/sklearn.py: LGBMModel
(sklearn.py:123+), LGBMRegressor (:488), LGBMClassifier (:536),
LGBMRanker (:645), plus the custom objective adapter (:15-121) translating
sklearn-style `fobj(y_true, y_pred)` into the engine's
`fobj(preds, dataset)` form.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset, LightGBMError
from .engine import train


def _objective_function_wrapper(func: Callable) -> Callable:
    """sklearn fobj(y_true, y_pred[, group]) -> engine fobj(preds, dataset)
    (reference sklearn.py:15-88)."""
    def inner(preds, dataset):
        labels = dataset.get_label()
        argc = func.__code__.co_argcount
        if argc == 2:
            grad, hess = func(labels, preds)
        elif argc == 3:
            grad, hess = func(labels, preds, dataset.get_group())
        else:
            raise TypeError(f"Self-defined objective should have 2 or 3 "
                            f"arguments, got {argc}")
        return grad, hess
    return inner


def _eval_function_wrapper(func: Callable) -> Callable:
    """sklearn feval(y_true, y_pred[, weight[, group]]) adapter
    (reference sklearn.py:88-121)."""
    def inner(preds, dataset):
        labels = dataset.get_label()
        argc = func.__code__.co_argcount
        if argc == 2:
            return func(labels, preds)
        if argc == 3:
            return func(labels, preds, dataset.get_weight())
        if argc == 4:
            return func(labels, preds, dataset.get_weight(),
                        dataset.get_group())
        raise TypeError(f"Self-defined eval function should have 2, 3, or 4 "
                        f"arguments, got {argc}")
    return inner


try:  # sklearn interop (clone / GridSearchCV need BaseEstimator tags)
    from sklearn.base import (BaseEstimator as _SkBase,
                              ClassifierMixin as _SkClassifierMixin,
                              RegressorMixin as _SkRegressorMixin)
except ImportError:  # sklearn not installed: plain-Python wrappers
    _SkBase = object

    class _SkClassifierMixin:
        pass

    class _SkRegressorMixin:
        pass


class LGBMModel(_SkBase):
    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 10, max_bin: int = 255,
                 subsample_for_bin: int = 50000, objective: str = "regression",
                 min_split_gain: float = 0.0, min_child_weight: float = 5,
                 min_child_samples: int = 10, subsample: float = 1.0,
                 subsample_freq: int = 1, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 scale_pos_weight: float = 1.0, is_unbalance: bool = False,
                 seed: int = 0, nthread: int = -1, silent: bool = True,
                 sigmoid: float = 1.0, huber_delta: float = 1.0,
                 gaussian_eta: float = 1.0, fair_c: float = 1.0,
                 poisson_max_delta_step: float = 0.7,
                 max_position: int = 20, label_gain=None,
                 drop_rate: float = 0.1, skip_drop: float = 0.5,
                 max_drop: int = 50, uniform_drop: bool = False,
                 xgboost_dart_mode: bool = False, **kwargs):
        self.boosting_type = boosting_type
        self.objective = objective
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.max_bin = max_bin
        self.subsample_for_bin = subsample_for_bin
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.scale_pos_weight = scale_pos_weight
        self.is_unbalance = is_unbalance
        self.seed = seed
        self.nthread = nthread
        self.silent = silent
        self.sigmoid = sigmoid
        self.huber_delta = huber_delta
        self.gaussian_eta = gaussian_eta
        self.fair_c = fair_c
        self.poisson_max_delta_step = poisson_max_delta_step
        self.max_position = max_position
        self.label_gain = label_gain
        self.drop_rate = drop_rate
        self.skip_drop = skip_drop
        self.max_drop = max_drop
        self.uniform_drop = uniform_drop
        self.xgboost_dart_mode = xgboost_dart_mode
        # arbitrary LightGBM params pass through (silent in the v2.0-era
        # fixed signature, a **kwargs superset like later LightGBM): they
        # participate in get_params/set_params so sklearn clone and
        # GridSearchCV see them
        self._other_param_names = sorted(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._Booster: Optional[Booster] = None
        self.evals_result: Dict = {}
        self.best_iteration: int = -1

    # sklearn plumbing ------------------------------------------------------

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        import inspect
        sig = inspect.signature(LGBMModel.__init__)
        out = {k: getattr(self, k) for k in sig.parameters
               if k not in ("self", "kwargs")}
        for k in getattr(self, "_other_param_names", ()):
            out[k] = getattr(self, k)
        return out

    def set_params(self, **params) -> "LGBMModel":
        import inspect
        known = set(inspect.signature(LGBMModel.__init__).parameters)
        for k, v in params.items():
            setattr(self, k, v)
            if k not in known and k not in self._other_param_names:
                self._other_param_names.append(k)
        return self

    def _lgbm_params(self) -> Dict[str, Any]:
        p = {
            "boosting_type": self.boosting_type,
            "objective": self.objective if isinstance(self.objective, str)
                         else "regression",
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "max_bin": self.max_bin,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "scale_pos_weight": self.scale_pos_weight,
            "is_unbalance": self.is_unbalance,
            "seed": self.seed,
            "sigmoid": self.sigmoid,
            "huber_delta": self.huber_delta,
            "gaussian_eta": self.gaussian_eta,
            "fair_c": self.fair_c,
            "poisson_max_delta_step": self.poisson_max_delta_step,
            "max_position": self.max_position,
            "verbose": 0,
        }
        for k in getattr(self, "_other_param_names", ()):
            p[k] = getattr(self, k)
        if self.label_gain is not None:
            p["label_gain"] = self.label_gain
        if self.boosting_type == "dart":
            p.update(drop_rate=self.drop_rate, skip_drop=self.skip_drop,
                     max_drop=self.max_drop, uniform_drop=self.uniform_drop,
                     xgboost_dart_mode=self.xgboost_dart_mode)
        return p

    # fitting ---------------------------------------------------------------

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_sample_weight=None, eval_init_score=None,
            eval_group=None, eval_metric=None, early_stopping_rounds=None,
            verbose: bool = False, feature_name="auto",
            categorical_feature="auto", callbacks=None) -> "LGBMModel":
        params = self._lgbm_params()
        fobj = None
        if callable(self.objective):
            fobj = _objective_function_wrapper(self.objective)
            params["objective"] = "regression"
        feval = None
        if callable(eval_metric):
            feval = _eval_function_wrapper(eval_metric)
        elif isinstance(eval_metric, str):
            params["metric"] = eval_metric
        elif isinstance(eval_metric, (list, tuple)):
            params["metric"] = ",".join(eval_metric)
        if getattr(self, "_n_classes", None) and self._n_classes > 2:
            params["num_class"] = self._n_classes
        train_set = Dataset(X, label=y, weight=sample_weight,
                            group=group, init_score=init_score,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                valid_sets.append(Dataset(vx, label=vy, weight=vw, group=vg,
                                          init_score=vi, reference=train_set))
        self.evals_result = {}
        self._Booster = train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, fobj=fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self.evals_result, verbose_eval=verbose,
            callbacks=callbacks)
        self.best_iteration = self._Booster.best_iteration
        return self

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1):
        if self._Booster is None:
            raise LightGBMError("Need to call fit beforehand")
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration)

    def apply(self, X, num_iteration: int = -1):
        if self._Booster is None:
            raise LightGBMError("Need to call fit beforehand")
        return self._Booster.predict(X, pred_leaf=True,
                                     num_iteration=num_iteration)

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found. Need to call fit beforehand.")
        return self._Booster

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance()

    @property
    def evals_result_(self) -> Dict:
        return self.evals_result

    # deprecated accessors kept for drop-in compatibility
    # (reference sklearn.py:480-487 keeps both spellings)
    def booster(self) -> Booster:
        import warnings
        warnings.warn("Use attribute booster_ instead.",
                      DeprecationWarning)
        return self.booster_

    def feature_importance(self) -> np.ndarray:
        import warnings
        warnings.warn("Use attribute feature_importances_ instead.",
                      DeprecationWarning)
        return self.feature_importances_


class LGBMRegressor(_SkRegressorMixin, LGBMModel):
    def __init__(self, objective: str = "regression", **kwargs):
        super().__init__(objective=objective, **kwargs)


class LGBMClassifier(_SkClassifierMixin, LGBMModel):
    def __init__(self, objective: str = "binary", **kwargs):
        super().__init__(objective=objective, **kwargs)

    def fit(self, X, y, **kwargs):  # noqa: D102
        y = np.asarray(y)
        self.classes_, y_enc = np.unique(y, return_inverse=True)
        self._n_classes = len(self.classes_)
        if self._n_classes > 2 and not callable(self.objective):
            self.objective = "multiclass"
        return super().fit(X, y_enc, **kwargs)

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1):
        prob = self.predict_proba(X, raw_score, num_iteration)
        if raw_score:
            return prob
        if prob.ndim > 1:
            return self.classes_[np.argmax(prob, axis=1)]
        return self.classes_[(prob > 0.5).astype(np.int64)]

    def predict_proba(self, X, raw_score: bool = False,
                      num_iteration: int = -1):
        out = self.booster_.predict(X, raw_score=raw_score,
                                    num_iteration=num_iteration)
        if raw_score or out.ndim > 1:
            return out
        return np.vstack([1.0 - out, out]).T

    @property
    def n_classes_(self) -> int:
        return self._n_classes


class LGBMRanker(LGBMModel):
    def __init__(self, objective: str = "lambdarank", **kwargs):
        super().__init__(objective=objective, **kwargs)

    def fit(self, X, y, group=None, **kwargs):  # noqa: D102
        if group is None:
            raise ValueError("Should set group for ranking task")
        if "eval_set" in kwargs and kwargs["eval_set"] is not None:
            if kwargs.get("eval_group") is None:
                raise ValueError("Eval_group cannot be None when eval_set is "
                                 "not None")
        return super().fit(X, y, group=group, **kwargs)
