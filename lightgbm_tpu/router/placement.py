"""Consistent-hash tenant→backend placement (docs/Router.md).

A hash ring with virtual nodes — `VNODES` sha1 points per backend, so
arcs are evenly sized without any RNG or wall clock and the ring is
identical across processes and runs.  ``place(model_id, alive)``
hashes the model id onto the ring and walks clockwise to the first
point owned by an alive backend, which yields both router properties
in one mechanism:

- **stability** — adding or removing ONE backend moves only the
  tenants whose arcs it owned (~1/M of them); every other tenant keeps
  its backend (tests/test_router.py pins this);
- **draining re-placement** — an open-breaker backend simply drops out
  of ``alive``: its tenants land on the next backend clockwise, and
  return home the moment the breaker closes, with no state to migrate
  (backends are model-stateless; each loads from its own model path).
"""
from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Optional, Set, Tuple


def _point(key: str) -> int:
    """64-bit ring position of ``key`` (sha1 — stable across runs,
    unlike hash())."""
    return int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "big")


class HashRing:
    """Static ring over the configured backend fleet; liveness is a
    per-call filter, not ring surgery, so placement under failures and
    placement under reconfiguration are the same walk."""

    VNODES = 64          # points per backend: arc-size variance ~1/sqrt(64)

    def __init__(self, backends: Iterable[str]):
        self.backends: Tuple[str, ...] = tuple(backends)
        pts = sorted((_point(f"{b}#{i}"), b)
                     for b in self.backends for i in range(self.VNODES))
        self._points = [p for p, _ in pts]
        self._owners = [b for _, b in pts]

    def place(self, key: str,
              alive: Optional[Iterable[str]] = None) -> Optional[str]:
        """The backend owning ``key``, restricted to ``alive`` backends
        (None = all configured).  None when no alive backend exists."""
        if not self.backends:
            return None
        alive_set: Set[str] = set(
            self.backends if alive is None else alive)
        if not alive_set:
            return None
        start = bisect.bisect_right(self._points, _point(key))
        n = len(self._points)
        for off in range(n):
            owner = self._owners[(start + off) % n]
            if owner in alive_set:
                return owner
        return None
