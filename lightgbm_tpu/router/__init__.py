"""Router tier: horizontal scale-out across M serving processes.

``task=route`` runs a stdlib-only HTTP router (docs/Router.md) that
spreads /predict traffic over M backend ``task=serve`` processes:
consistent-hash tenant→backend placement (with explicit overrides),
per-backend circuit breakers with count-based half-open probes, and
fleet-aggregated /stats + /metrics.
"""
from .placement import HashRing
from .server import (BackendState, NoHealthyBackendError, RouterServer,
                     route_from_config, router_from_config)

__all__ = [
    "BackendState",
    "HashRing",
    "NoHealthyBackendError",
    "RouterServer",
    "route_from_config",
    "router_from_config",
]
