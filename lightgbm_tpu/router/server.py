"""Stdlib-only HTTP router fronting M backend serving processes.

``task=route`` (application.py) runs this process in front of a fleet
of ``task=serve`` backends (docs/Router.md):

- ``POST /predict`` — the request's model id (``?model=`` query param,
  ``"model"`` object-body field, or ``X-Model-Id`` header — the same
  precedence the backends apply) picks a backend by explicit placement
  override or consistent hash (placement.HashRing), and the request is
  proxied there verbatim — body, query string, trace/model headers in,
  status + payload + ``X-Model-Id`` / ``X-Model-Generation`` /
  ``X-Trace-Id`` headers back out.  A request that names no model
  places under the key ``"default"`` (every backend's unkeyed tenant),
  so unkeyed traffic is sticky too.
- per-backend **circuit breakers** — the serving fleet's replica state
  machine (serving/runtime.py) one level up: `failure_threshold`
  CONSECUTIVE transport failures open a backend's breaker; open
  backends are routed around (their tenants re-place onto the next
  healthy backend clockwise — draining re-placement, in-flight
  requests finish on the old backend); after ``PROBE_AFTER``
  route-arounds ONE live request is dispatched as a half-open probe
  (single-flight, count-based — no wall clock, chaos-deterministic),
  and a success readmits the backend.  A failed dispatch is retried
  ONCE on a different healthy backend with probing disabled — a retry
  is never consumed as a half-open probe (the PR 7 review's bug
  class, at router scope).
- **health loop** — every `route_health_interval_ms` each backend's
  ``/healthz`` is probed; the parsed body (model ids, live + published
  generations, self-reported stale tenants) feeds the fleet /stats
  view, probe successes readmit open breakers, and probe failures
  open them without waiting for live traffic.  0 = no background
  probing; the count-based live-traffic probes still readmit.
- ``GET /stats`` — the fleet view: per-backend breaker health,
  dispatch/inflight counters and last health payload, the placement
  table, per-model staleness across backends, router counters, and
  each healthy backend's own /stats embedded.
- ``GET /metrics`` — Prometheus text exposition merging the router's
  counters with per-backend AND per-model labeled series.

Transport failures (connect/timeout/protocol) are the ROUTER's
failures and drive the breakers; any HTTP response from a backend —
including a 4xx/5xx — is a backend ANSWER and relays to the client
verbatim.  This module deliberately imports none of the serving stack
(no numpy/jax): a router process is plumbing and must start in
milliseconds.
"""
from __future__ import annotations

import json
import re
import socket
import threading
import time
import socketserver
from http.client import HTTPException
from typing import Dict, List, Optional, Tuple

from .. import log, profiling, telemetry
from ..httpd import SeveringHTTPServer
from ..config import MODEL_ID_RE, Config, parse_route_backends
from ..diagnostics import faults, locksan
from ..log import LightGBMError
from .placement import HashRing, _point

# same charset as serving/server.py's ingress validation — duplicated
# (not imported) so the router never pulls the numpy/jax serving stack
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._:-]{1,128}$")

# response headers relayed from backend to client (anything else —
# Date, Server, Connection — is per-hop and re-minted by this server)
_RELAY_HEADERS = ("Content-Type", "X-Model-Id", "X-Model-Generation",
                  "X-Trace-Id", "Retry-After")
_RELAY_HEADERS_LC = {h.lower(): h for h in _RELAY_HEADERS}

# transport-level dispatch failures: the backend did not ANSWER.
# InjectedFault rides along so the chaos suite can open breakers at the
# route.backend seams without real process kills.
_TRANSPORT_ERRORS = (OSError, HTTPException, faults.InjectedFault)


class _BackendConn:
    """One pooled raw-socket backend connection speaking the same
    minimal HTTP/1.1 subset as the ingress handler (see _Handler).

    Not http.client: ``getresponse()`` parses response headers through
    email.parser and builds an HTTPResponse object per round-trip —
    the same few hundred GIL-bound microseconds the ingress rewrite
    removed, paid again on the egress leg.  TCP_NODELAY because the
    proxied request still leaves as header bytes + body bytes and must
    never sit out a delayed-ACK period behind Nagle."""

    __slots__ = ("sock", "rfile", "host", "port")

    def __init__(self, host: str, port: int, timeout: float):
        self.host, self.port = host, port
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.rfile = self.sock.makefile("rb", buffering=64 << 10)

    def close(self) -> None:
        for closer in (self.rfile.close, self.sock.close):
            try:
                closer()
            except OSError:
                pass

    def _read_chunked(self) -> bytes:
        chunks = []
        while True:
            size_line = self.rfile.readline(1024)
            if not size_line:
                raise HTTPException("connection closed mid-chunk")
            size = int(size_line.split(b";", 1)[0], 16)
            if size == 0:
                while self.rfile.readline(65537) not in (b"\r\n", b"\n",
                                                         b""):
                    pass                     # drain trailers
                return b"".join(chunks)
            chunk = self.rfile.read(size + 2)   # chunk + CRLF
            if len(chunk) != size + 2:
                raise HTTPException("truncated chunk")
            chunks.append(chunk[:-2])

    def roundtrip(self, method: str, path: str, body: Optional[bytes],
                  headers: Dict[str, str]):
        """One request/response.  Returns ``(status, lowercase-header
        dict, payload, reusable)``; raises OSError/HTTPException when
        the backend did not answer a complete response."""
        parts = [f"{method} {path} HTTP/1.1\r\n"
                 f"Host: {self.host}:{self.port}\r\n"]
        parts += [f"{k}: {v}\r\n" for k, v in headers.items()]
        if body is not None:
            parts.append(f"Content-Length: {len(body)}\r\n")
        parts.append("\r\n")
        head = "".join(parts).encode("latin-1")
        self.sock.sendall(head + body if body else head)
        line = self.rfile.readline(65537)
        bits = line.split(None, 2)
        if len(bits) < 2 or not bits[1].isdigit():
            raise HTTPException(f"bad status line {line!r}")
        status = int(bits[1])
        hdrs: Dict[str, str] = {}
        while True:
            h = self.rfile.readline(65537)
            if h in (b"\r\n", b"\n"):
                break
            if not h:
                raise HTTPException("connection closed in headers")
            k, sep, v = h.partition(b":")
            if sep:
                hdrs[k.strip().lower().decode("latin-1")] = \
                    v.strip().decode("latin-1")
        reusable = (bits[0] == b"HTTP/1.1" and
                    hdrs.get("connection", "").lower() != "close")
        length = hdrs.get("content-length")
        if length is not None:
            payload = self.rfile.read(int(length))
            if len(payload) != int(length):
                raise HTTPException("truncated response body")
        elif hdrs.get("transfer-encoding", "").lower() == "chunked":
            payload = self._read_chunked()
        else:
            payload = self.rfile.read()      # body runs to EOF
            reusable = False
        return status, hdrs, payload, reusable


class NoHealthyBackendError(RuntimeError):
    """No healthy backend can take this request (all breakers open, or
    the one retry also failed at the transport layer) — HTTP 503 +
    Retry-After at the router."""


class BackendState:
    """Per-backend breaker + health bookkeeping — the serving replica
    state machine (serving/runtime.py `_Replica`) one level up, same
    fields, same count-based transitions."""

    __slots__ = ("index", "addr", "host", "port", "inflight",
                 "dispatches", "failures", "broken", "skips", "probes",
                 "last_health", "req_key", "fail_key")

    def __init__(self, index: int, addr: str):
        self.index = index
        self.addr = addr
        host, _, port = addr.rpartition(":")
        self.host, self.port = host, int(port)
        # labeled registry keys precomputed once: labeled() formats a
        # sorted f-string per call, and these two are per-request
        self.req_key = profiling.labeled(profiling.ROUTER_REQUESTS,
                                         backend=f"b{index}")
        self.fail_key = profiling.labeled(
            profiling.ROUTER_BACKEND_FAILURES, backend=f"b{index}")
        self.inflight = 0       # proxied requests on the wire right now
        self.dispatches = 0     # total proxied requests sent here
        self.failures = 0       # CONSECUTIVE transport failures
        self.broken = False     # breaker open: no traffic except probes
        self.skips = 0          # route-arounds since broken/last probe
        self.probes = 0         # half-open probes dispatched
        self.last_health = None  # parsed /healthz body of the last good probe

    def label(self) -> str:
        """Prometheus label value for this backend (index-shaped —
        ``host:port`` is not label-charset-safe; /stats maps it back)."""
        return f"b{self.index}"


_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            405: "Method Not Allowed", 503: "Service Unavailable"}


class _Handler(socketserver.StreamRequestHandler):
    """Minimal bytes-level HTTP/1.1 ingress.

    Not BaseHTTPRequestHandler: its email.parser header parse and
    many-small-writes response path cost several hundred GIL-bound
    microseconds per request — most of the routing hop's entire <5%
    p99 budget (scripts/bench_router.py).  The router speaks a tiny
    fixed subset (POST /predict plus three GET endpoints), so ingress
    reduces to a request-line split, header partition on b":", a body
    read of Content-Length bytes, and ONE pre-assembled response
    write.  Per-hop headers (Date, Server) are deliberately not
    minted — no client of this tier reads them."""

    rbufsize = 64 << 10   # one buffered read drains typical requests
    wbufsize = 0          # _SocketWriter: each write is one sendall

    def setup(self):
        super().setup()
        # the response leaves in one write, but large payloads still
        # split across send() calls — keep Nagle off regardless
        self.connection.setsockopt(socket.IPPROTO_TCP,
                                   socket.TCP_NODELAY, 1)

    def handle(self):
        try:
            while self._handle_one():
                pass
        except OSError:
            pass    # client hung up, or stop() severed the socket

    def _send(self, code: int, payload: bytes,
              content_type: str = "application/json",
              headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        parts = [f"HTTP/1.1 {code} {_REASONS.get(code, 'OK')}\r\n"
                 f"Content-Type: {content_type}\r\n"
                 f"Content-Length: {len(payload)}\r\n"]
        parts += [f"{k}: {v}\r\n" for k, v in headers]
        if not self._keep:
            parts.append("Connection: close\r\n")
        parts.append("\r\n")
        self.wfile.write("".join(parts).encode("latin-1") + payload)

    def _send_json(self, code: int, obj,
                   headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        self._send(code, (json.dumps(obj) + "\n").encode(),
                   headers=headers)

    def _handle_one(self) -> bool:
        line = self.rfile.readline(65537)
        if not line or line in (b"\r\n", b"\n"):
            return False                 # clean EOF between requests
        self._keep = False               # malformed requests never linger
        try:
            method, target, version = line.split()
        except ValueError:
            self._send(400, b'{"error": "malformed request line"}\n')
            return False
        headers: Dict[str, str] = {}
        while True:
            h = self.rfile.readline(65537)
            if h in (b"\r\n", b"\n", b""):
                break
            if len(headers) >= 128:
                self._send(400, b'{"error": "too many headers"}\n')
                return False
            k, sep, v = h.partition(b":")
            if sep:
                headers[k.strip().lower().decode("latin-1")] = \
                    v.strip().decode("latin-1")
        self._keep = (version == b"HTTP/1.1"
                      and headers.get("connection", "").lower() != "close")
        if headers.get("expect", "").lower() == "100-continue":
            self.wfile.write(b"HTTP/1.1 100 Continue\r\n\r\n")
        # drain the body FIRST: keep-alive would otherwise parse
        # leftover body bytes as the connection's next request line
        # after an early 404/400 (serving/server.py discipline)
        length = headers.get("content-length")
        if length is not None:
            try:
                body = self.rfile.read(int(length))
            except ValueError:
                self._keep = False
                self._send(400, b'{"error": "bad Content-Length"}\n')
                return False
        else:
            body = b""
            if method == b"POST":
                self._keep = False       # unknown body length
        path, _, query = target.decode("latin-1").partition("?")
        rt: "RouterServer" = self.server.router
        if method == b"POST":
            self._do_post(rt, path, query, headers, body)
        elif method == b"GET":
            self._do_get(rt, path)
        else:
            self._send(405, b'{"error": "method not allowed"}\n')
        return self._keep

    def _do_get(self, rt: "RouterServer", path: str) -> None:
        if path == "/healthz":
            healthy = rt.healthy_count()
            self._send_json(200, {
                "status": "ok" if healthy else "degraded",
                "backends": len(rt.ring.backends),
                "healthy": healthy})
        elif path == "/stats":
            self._send_json(200, rt.stats())
        elif path == "/metrics":
            self._send(200, rt.metrics_text().encode(),
                       content_type="text/plain; version=0.0.4; "
                                    "charset=utf-8")
        else:
            self._send_json(404, {"error": f"unknown path {path}"})

    def _do_post(self, rt: "RouterServer", path: str, query: str,
                 headers: Dict[str, str], body: bytes) -> None:
        if path != "/predict":
            self._send_json(404, {"error": f"unknown path {path}"})
            return
        # model id: query param > body object field > header — resolved
        # HERE (not just at the backend) because the id decides which
        # backend sees the request at all.  The body is parsed only
        # when the cheaper sources are absent, it looks like the
        # object form, AND a C-level substring scan says a "model" key
        # can exist at all — a json.loads of every multi-KB row
        # payload would put a GIL-bound parse on the routing hot path
        # (scripts/bench_router.py's <5% p99 budget).
        from urllib.parse import parse_qs
        qs = parse_qs(query)
        raw_mid = qs["model"][0] if "model" in qs else None
        if (raw_mid is None and body[:16].lstrip()[:1] == b"{"
                and b'"model"' in body):
            try:
                mid = json.loads(body).get("model")
                raw_mid = str(mid) if mid else None
            except (ValueError, UnicodeDecodeError):
                raw_mid = None               # backends parse-error it
        if raw_mid is None:
            raw_mid = headers.get("x-model-id")
        if raw_mid is not None and not MODEL_ID_RE.match(raw_mid):
            self._send_json(400, {"error": (
                "malformed model id (must match [A-Za-z0-9._-]{1,64})")})
            return
        # trace ingress mirrors the backends: validate, mint when
        # telemetry is on, forward so the backend's spans join OUR trace
        raw_tid = headers.get("x-trace-id")
        trace_id = (raw_tid if raw_tid is not None
                    and _TRACE_ID_RE.match(raw_tid) else None)
        if trace_id is None and telemetry.enabled():
            trace_id = telemetry.new_trace_id()
        fwd = {"Content-Type": headers.get("content-type",
                                           "application/json")}
        if trace_id:
            fwd["X-Trace-Id"] = trace_id
        if raw_mid:
            fwd["X-Model-Id"] = raw_mid
        try:
            status, rhdrs, payload = rt.proxy(
                raw_mid, body, query, fwd, trace_id=trace_id)
        except NoHealthyBackendError as e:
            profiling.count(profiling.ROUTER_REJECTED)
            self._send_json(503, {"error": str(e)},
                            headers=(("Retry-After", "1"),))
            return
        ctype = rhdrs.pop("Content-Type", "application/json")
        self._send(status, payload, content_type=ctype,
                   headers=tuple(rhdrs.items()))


class RouterServer:
    """HTTP router + backend health loop, with clean teardown (context
    manager) so tests never leak a listener — the `PredictionServer`
    lifecycle shape, one level up."""

    # route-arounds before an open-breaker backend earns ONE in-flight
    # half-open probe (count-based: deterministic under chaos specs,
    # and self-scaling — probes are frequent exactly when traffic is)
    PROBE_AFTER = 8

    def __init__(self, backends, overrides: Optional[Dict[str, str]] = None,
                 *, host: str = "127.0.0.1", port: int = 0,
                 health_interval_ms: float = 1000.0,
                 backend_timeout_ms: float = 30000.0,
                 max_inflight: int = 0, failure_threshold: int = 3,
                 group_spread: int = 1):
        if not backends:
            raise LightGBMError(
                "the router needs at least one backend: set "
                "route_backends=host:port,...")
        self.ring = HashRing(backends)
        self.overrides = dict(overrides or {})
        self.health_interval_s = max(float(health_interval_ms), 0.0) / 1e3
        self.backend_timeout_s = max(float(backend_timeout_ms), 1.0) / 1e3
        self.max_inflight = int(max_inflight)
        self.failure_threshold = max(int(failure_threshold), 1)
        self.group_spread = max(int(group_spread), 1)
        self._lock = locksan.lock("route.server")
        # model id -> co-stack group key, merged from the backends'
        # /healthz "group_keys" payloads (see _placement_key)
        self._group_keys: Dict[str, str] = {}
        self._backends: Dict[str, BackendState] = {
            addr: BackendState(i, addr)
            for i, addr in enumerate(self.ring.backends)}
        self._inflight = 0
        # per-model labeled-counter keys, formatted once per tenant
        self._model_req_keys: Dict[str, str] = {}
        # per-thread backend keep-alive connections (see _dispatch)
        self._conn_pool = threading.local()
        self._httpd = SeveringHTTPServer((host, port), _Handler)
        self._httpd.router = self
        self.host, self.port = self._httpd.server_address[:2]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- placement + breaker -------------------------------------------

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for b in self._backends.values() if not b.broken)

    def _placement_key(self, model_id: Optional[str]) -> str:
        """The key a tenant hashes the ring with: its co-stack group
        key when the health sweeps have reported one (so compatible
        tenants land on the SAME backend and actually co-stack there),
        the model id otherwise.  group_spread > 1 salts the group key
        with the tenant's own hash point modulo the spread, trading
        strict co-location for load spread across that many cohorts —
        tenants in the same cohort still co-stack."""
        key = model_id or "default"
        gk = self._group_keys.get(key)
        if gk is None:
            return key
        if self.group_spread > 1:
            return f"{gk}#{_point(key) % self.group_spread}"
        return gk

    def _place_home(self, model_id: Optional[str]) -> str:
        """The tenant's home backend over the FULL fleet (overrides
        first, ring over the placement key otherwise) — liveness is
        applied by _pick, so a drained tenant returns home on
        readmission."""
        key = model_id or "default"
        home = self.overrides.get(key)
        if home is None:
            home = self.ring.place(self._placement_key(key))
        return home

    def _pick(self, model_id: Optional[str], exclude: Optional[str] = None,
              allow_probe: bool = True) -> BackendState:
        """Choose a backend and charge it one in-flight dispatch.

        Healthy home wins; an open-breaker home counts a skip and may
        be selected as the single-flight half-open probe (never on a
        retry — allow_probe=False there); otherwise the tenant
        re-places clockwise among healthy backends (ROUTER_REHASHES).
        Raises NoHealthyBackendError when nothing can take it."""
        key = model_id or "default"
        with self._lock:
            home = self._backends[self._place_home(model_id)]
            chosen: Optional[BackendState] = None
            if home.addr != exclude and not home.broken:
                chosen = home
            else:
                if home.broken and home.addr != exclude:
                    home.skips += 1
                    if (allow_probe and home.skips >= self.PROBE_AFTER
                            and home.inflight == 0):
                        # half-open: ONE live request probes the broken
                        # backend; its success readmits, its failure
                        # restarts the skip window
                        home.skips = 0
                        home.probes += 1
                        profiling.count(profiling.ROUTER_BACKEND_PROBES)
                        chosen = home
                if chosen is None:
                    alive = [b.addr for b in self._backends.values()
                             if not b.broken and b.addr != exclude]
                    # re-place by the PLACEMENT key: every tenant of a
                    # drained group re-hashes to the same survivor, so
                    # the group re-forms (one compile) instead of
                    # scattering into G solo tenants
                    replaced = self.ring.place(self._placement_key(key),
                                               alive)
                    if replaced is not None:
                        if home.broken:
                            profiling.count(profiling.ROUTER_REHASHES)
                        chosen = self._backends[replaced]
            if chosen is None:
                raise NoHealthyBackendError(
                    f"no healthy backend for model "
                    f"{key!r} ({len(self._backends)} configured, "
                    f"{sum(1 for b in self._backends.values() if not b.broken)}"
                    " healthy)")
            chosen.inflight += 1
            chosen.dispatches += 1
            return chosen

    def _note_success(self, b: BackendState, dispatched: bool = True) -> None:
        with self._lock:
            if dispatched:
                b.inflight -= 1
            b.failures = 0
            if b.broken:
                b.broken = False
                profiling.count(profiling.ROUTER_BACKEND_READMITTED)
                readmitted = True
            else:
                readmitted = False
        if readmitted:
            log.info(f"router: backend {b.addr} readmitted")
            telemetry.event("route.breaker", backend=b.addr,
                            state="closed")

    def _note_failure(self, b: BackendState, error: BaseException,
                      dispatched: bool = True) -> None:
        with self._lock:
            if dispatched:
                b.inflight -= 1
            profiling.count(profiling.ROUTER_BACKEND_FAILURES)
            profiling.count(b.fail_key)
            if b.broken:
                # a failed half-open probe: stay open, earn a fresh
                # PROBE_AFTER window before the next probe
                b.skips = 0
                opened = False
            else:
                b.failures += 1
                opened = b.failures >= self.failure_threshold
                if opened:
                    b.broken = True
                    b.skips = 0
                    profiling.count(profiling.ROUTER_BACKEND_BROKEN)
        if opened:
            log.warning(f"router: backend {b.addr} circuit-broken after "
                        f"{self.failure_threshold} consecutive failures "
                        f"({type(error).__name__}: {error})")
            telemetry.event("route.breaker", backend=b.addr, state="open",
                            error=str(error))

    # -- proxying -------------------------------------------------------

    def _dispatch(self, b: BackendState, method: str, path: str,
                  body: Optional[bytes] = None,
                  headers: Optional[Dict[str, str]] = None):
        """One HTTP round-trip to backend ``b``.  Raises a
        _TRANSPORT_ERRORS member when the backend did not answer; any
        HTTP response (any status) returns ``(status, headers,
        payload)``."""
        faults.check("route.backend")
        faults.check(f"route.backend.{b.label()}")
        # per-thread keep-alive pool: a fresh TCP connection per proxy
        # would make the routing hop pay connect + a new backend
        # handler thread on EVERY request — that alone blows the <5%
        # p99 budget (scripts/bench_router.py).  One cached connection
        # per (handler thread, backend); a request that fails on a
        # CACHED connection retries once on a fresh one below the
        # fault seam, because a stale keep-alive socket (backend
        # restarted, idle close) is not a backend failure — scoring is
        # idempotent, so the re-send is safe.
        pool = self._conn_pool.__dict__.setdefault("conns", {})
        conn = pool.pop(b.addr, None)
        pooled = conn is not None
        for attempt in (0, 1):
            if conn is None:
                conn = _BackendConn(b.host, b.port,
                                    self.backend_timeout_s)
            try:
                status, hdrs, payload, reusable = conn.roundtrip(
                    method, path, body, headers or {})
            except _TRANSPORT_ERRORS:
                conn.close()
                conn = None
                if attempt == 0 and pooled:
                    continue    # stale cached socket, not the backend
                raise
            rhdrs = {_RELAY_HEADERS_LC[k]: v for k, v in hdrs.items()
                     if k in _RELAY_HEADERS_LC}
            if reusable:
                pool[b.addr] = conn
            else:
                conn.close()
            return status, rhdrs, payload

    def proxy(self, model_id: Optional[str], body: bytes, query: str,
              fwd_headers: Dict[str, str],
              trace_id: Optional[str] = None):
        """Route one /predict request: place, dispatch, and on a
        transport failure retry ONCE on a different healthy backend
        with probing disabled.  Returns ``(status, relay-headers,
        payload)``; raises NoHealthyBackendError for the 503 path."""
        profiling.count(profiling.ROUTER_REQUESTS)
        mkey = model_id or "default"
        mk = self._model_req_keys.get(mkey)
        if mk is None:    # benign race: duplicate format, same value
            mk = self._model_req_keys[mkey] = profiling.labeled(
                profiling.ROUTER_REQUESTS, model=mkey)
        profiling.count(mk)
        with self._lock:
            if self.max_inflight and self._inflight >= self.max_inflight:
                # shed load HERE instead of stacking proxy threads on
                # slow backends (the handler adds Retry-After)
                raise NoHealthyBackendError(
                    f"router at max_inflight={self.max_inflight} "
                    "(route_max_inflight); retry with backoff")
            self._inflight += 1
        path = "/predict" + (f"?{query}" if query else "")
        t0 = time.monotonic()
        try:
            with telemetry.span("route.request", trace_id=trace_id,
                                model=model_id or "default") as sp:
                b = self._pick(model_id)
                profiling.count(b.req_key)
                try:
                    status, rhdrs, payload = self._dispatch(
                        b, "POST", path, body=body, headers=fwd_headers)
                except _TRANSPORT_ERRORS as e:
                    self._note_failure(b, e)
                    # ONE retry on a different healthy backend.
                    # allow_probe=False: a retry must never be consumed
                    # as the half-open probe of ANOTHER broken backend
                    # — the client would pay for fleet convalescence.
                    profiling.count(profiling.ROUTER_RETRIES)
                    b2 = self._pick(model_id, exclude=b.addr,
                                    allow_probe=False)
                    try:
                        status, rhdrs, payload = self._dispatch(
                            b2, "POST", path, body=body,
                            headers=fwd_headers)
                    except _TRANSPORT_ERRORS as e2:
                        self._note_failure(b2, e2)
                        raise NoHealthyBackendError(
                            f"backends {b.addr} and {b2.addr} both "
                            f"failed ({type(e2).__name__}: {e2}); "
                            "retry with backoff") from e2
                    self._note_success(b2)
                    sp.set(backend=b2.addr, retried=True, status=status)
                    return status, rhdrs, payload
                self._note_success(b)
                sp.set(backend=b.addr, status=status)
                return status, rhdrs, payload
        finally:
            profiling.observe("router/latency_ms",
                              (time.monotonic() - t0) * 1e3)
            with self._lock:
                self._inflight -= 1

    # -- health loop ----------------------------------------------------

    def probe_backends_once(self) -> None:
        """One health sweep: GET /healthz on every backend (broken ones
        included — readmitting a restarted backend is the point).  The
        deterministic entry the tests call directly; the background
        loop is just this on a timer."""
        for b in list(self._backends.values()):
            try:
                status, _hdrs, payload = self._dispatch(b, "GET", "/healthz")
                if status != 200:
                    raise HTTPException(f"healthz answered {status}")
                health = json.loads(payload or b"{}")
            except (*_TRANSPORT_ERRORS, ValueError) as e:
                self._note_failure(b, e, dispatched=False)
                continue
            with self._lock:
                b.last_health = health
                # merge, don't replace: each backend only knows ITS
                # tenants' group keys; the union is the fleet map that
                # steers placement (stale keys for unpublished tenants
                # are harmless — they just keep steering consistently)
                gk = health.get("group_keys")
                if isinstance(gk, dict):
                    self._group_keys.update(
                        {str(m): str(k) for m, k in gk.items()})
            self._note_success(b, dispatched=False)

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            try:
                self.probe_backends_once()
            except Exception as e:           # never kill the loop
                log.warning(f"router health sweep failed: {e}")

    # -- observability --------------------------------------------------

    def _fleet_models(self) -> Dict[str, dict]:
        """Per-model fleet view from the backends' last /healthz
        payloads: placement, per-backend live + published generations,
        and which backends are stale for the model (self-reported
        pending publish, or a published generation behind the fleet
        max — the partially-swapped-fleet signal the health probes
        exist to catch)."""
        with self._lock:
            snaps = [(b.addr, b.last_health)
                     for b in self._backends.values() if b.last_health]
        models: Dict[str, dict] = {}
        for addr, health in snaps:
            self_stale = set(health.get("stale") or ())
            published = health.get("published") or {}
            for mid, gen in (health.get("models") or {}).items():
                m = models.setdefault(mid, {"placed": None, "live": {},
                                            "published": {},
                                            "stale_backends": []})
                m["live"][addr] = gen
                m["published"][addr] = published.get(mid)
                if mid in self_stale:
                    m["stale_backends"].append(addr)
        for mid, m in models.items():
            m["placed"] = self._place_home(mid)
            known = [g for g in m["published"].values() if g is not None]
            if known:
                newest = max(known)
                for addr, g in m["published"].items():
                    if (g is None or g < newest) \
                            and addr not in m["stale_backends"]:
                        m["stale_backends"].append(addr)
            m["stale_backends"].sort()
        return models

    def stats(self) -> dict:
        """The operator's fleet view, including each healthy backend's
        own /stats embedded (the aggregation a fleet dashboard scrapes
        once instead of M times)."""
        with self._lock:
            backs = {b.addr: {
                "index": b.index,
                "label": b.label(),
                "healthy": not b.broken,
                "inflight": b.inflight,
                "dispatches": b.dispatches,
                "failures": b.failures,
                "skips": b.skips,
                "probes": b.probes,
                "health": b.last_health,
            } for b in self._backends.values()}
            broken = [a for a, s in backs.items() if not s["healthy"]]
        for addr, snap in backs.items():
            if addr in broken:
                continue
            b = self._backends[addr]
            try:
                status, _h, payload = self._dispatch(b, "GET", "/stats")
                if status == 200:
                    snap["stats"] = json.loads(payload)
            except (*_TRANSPORT_ERRORS, ValueError) as e:
                snap["stats_error"] = f"{type(e).__name__}: {e}"
        return {
            "backends": backs,
            "healthy": len(backs) - len(broken),
            "models": self._fleet_models(),
            # per-backend co-stack group counts from the health sweep
            # (serving /healthz "groups"): how many compiled-executable
            # groups each backend's tenants share — the fleet-wide view
            # of cross-model batching (docs/serving.md)
            "groups": {addr: (snap["health"] or {}).get("groups")
                       for addr, snap in backs.items()
                       if snap["health"] is not None},
            # the co-stack placement map the ring hashes with (merged
            # from the health sweeps) and its spread knob — the fleet
            # view of WHY same-group tenants share a home backend
            "group_keys": dict(self._group_keys),
            "group_spread": self.group_spread,
            "overrides": dict(self.overrides),
            "inflight": self._inflight,
            "max_inflight": self.max_inflight,
            "requests": profiling.counter_value(profiling.ROUTER_REQUESTS),
            "retries": profiling.counter_value(profiling.ROUTER_RETRIES),
            "rejected": profiling.counter_value(profiling.ROUTER_REJECTED),
            "rehashes": profiling.counter_value(profiling.ROUTER_REHASHES),
            "backend_failures": profiling.counter_value(
                profiling.ROUTER_BACKEND_FAILURES),
            "backend_broken": profiling.counter_value(
                profiling.ROUTER_BACKEND_BROKEN),
            "backend_readmitted": profiling.counter_value(
                profiling.ROUTER_BACKEND_READMITTED),
            "backend_probes": profiling.counter_value(
                profiling.ROUTER_BACKEND_PROBES),
            "latency_ms": profiling.summary("router/latency_ms"),
            "process": telemetry.process_info(),
        }

    def _gauges(self) -> dict:
        """Live fleet gauges for /metrics: fleet totals, per-backend
        health/inflight series, and per-(backend, model) generation
        series merged from the health payloads — the labeled-series
        contract of PR 11/15 carried one level up."""
        with self._lock:
            backends = list(self._backends.values())
            g = {
                "route.fleet_size": len(backends),
                "route.healthy_backends": sum(
                    1 for b in backends if not b.broken),
                "route.inflight": self._inflight,
                "route.inflight_cap": self.max_inflight,
            }
            for b in backends:
                g[profiling.labeled("route.backend_healthy",
                                    backend=b.label())] = 0 if b.broken else 1
                g[profiling.labeled("route.backend_inflight",
                                    backend=b.label())] = b.inflight
                for mid, gen in ((b.last_health or {}).get("models")
                                 or {}).items():
                    g[profiling.labeled("route.model_generation",
                                        backend=b.label(),
                                        model=mid)] = gen
        return g

    def metrics_text(self) -> str:
        return telemetry.prometheus_text(self._gauges())

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "RouterServer":
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="lgbt-route-http", daemon=True)
        t.start()
        self._threads.append(t)
        if self.health_interval_s > 0:
            h = threading.Thread(target=self._health_loop,
                                 name="lgbt-route-health", daemon=True)
            h.start()
            self._threads.append(h)
        log.info(f"routing on http://{self.host}:{self.port} over "
                 f"{len(self.ring.backends)} backends "
                 f"({', '.join(self.ring.backends)})")
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.close_client_connections()
        self._httpd.server_close()
        for t in self._threads:
            t.join(timeout=10)

    def __enter__(self) -> "RouterServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def router_from_config(cfg: Config) -> RouterServer:
    """Build (not start) a RouterServer from CLI/config parameters."""
    backends, overrides = parse_route_backends(cfg.route_backends)
    if not backends:
        raise LightGBMError("task=route needs a backend fleet: set "
                            "route_backends=host:port,... "
                            "(model_id=host:port entries pin placement)")
    return RouterServer(
        backends, overrides, host=cfg.serve_host, port=cfg.route_port,
        health_interval_ms=cfg.route_health_interval_ms,
        backend_timeout_ms=cfg.route_backend_timeout_ms,
        max_inflight=cfg.route_max_inflight,
        failure_threshold=cfg.replica_failure_threshold,
        group_spread=cfg.route_group_spread)


def route_from_config(cfg: Config) -> None:
    """Blocking ``task=route`` entry: route until SIGINT/SIGTERM."""
    import signal

    router = router_from_config(cfg)
    done = threading.Event()

    def _on_term(_signum, _frame):
        done.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass
    with router:
        try:
            done.wait()
        except KeyboardInterrupt:
            pass
    log.info("routing stopped")
