"""Shared feature quantization against a frozen BinMapper set.

ONE module owns "raw feature rows → integer bin indices", used by all
three consumers so the mapper application can never drift between
training and serving (ROADMAP item: binned int8 inference):

- **Dataset construction** (`dataset.Dataset.__init__` / the two-round
  loader / `from_csc`) and **online ingestion**
  (`Dataset.streaming_from` → `append_rows`) both route their row
  chunks through `bin_rows_into` — the TRAIN policy: float64
  searchsorted against the mapper's float64 bounds, NaN mapped to the
  bin of value 0.0 (the v2.0-era missing convention the histogram
  kernels train on).
- **Serving ingress** (`serving.PredictorRuntime` with
  ``serve_quantize=binned``) quantizes each request chunk with a
  `FeatureQuantizer` — the SERVE policy, engineered to be
  bitwise-equivalent to the RAW f32 traversal kernel on every possible
  input (see below), so binned scores are bit-identical to raw scores.

Serve-policy exactness argument
-------------------------------

Model thresholds ARE bin upper bounds (`Tree.rebin_to_dataset`: saved
thresholds round-trip through `value_to_bin` exactly), and the raw
kernels compare in float32 (``f32(v) <= f32(t)``).  Quantizing with a
float32 searchsorted over the float32-cast upper bounds makes the
integer compare exact for EVERY raw value: ``bin(v) <= bin(t)`` iff
``uppers32[bin(t)] >= f32(v)`` iff ``f32(v) <= f32(t)`` — including
values that straddle a float64 boundary but collapse onto it in f32
(a float64 searchsorted would misroute those against the f32 kernel).
Non-finite handling mirrors the raw kernels' decisions exactly:

- NaN quantizes to the MISSING sentinel — one code past every real
  bin, so it compares greater than any numerical threshold bin and
  equal to no categorical bin: NaN routes RIGHT everywhere, the raw
  kernel's ``v <= t -> False`` / finite-mask behavior.
- +/-inf land on the last/first real bin (the raw compare's outcome).
- A finite category absent from the mapper's table quantizes to the
  sentinel too: the raw categorical compare (int truncation behind a
  finite mask) matches no category either.  Exact for category values
  below 2^24 (the raw kernel's own f32 exactness domain).

The sentinel derivation is the mapper set's missing-bin convention for
serving — it replaces the never-populated ``default_left`` node lane
the raw stacks used to carry.
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

import numpy as np

from .binning import CATEGORICAL, NUMERICAL, BinMapper, pack_bundle_column
from .log import LightGBMError


def file_sha1(path: str) -> str:
    """sha1 of a file's bytes — the refbin integrity fingerprint (the
    online trainer stamps it into the publish ``.meta.json``; the
    serving registry refuses a binned swap on mismatch)."""
    h = hashlib.sha1()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


# ----------------------------------------------------------------------
# TRAIN policy: the store-filling quantization every Dataset build and
# every streaming append runs (moved verbatim from Dataset._bin_rows_into
# so serving could share the module, not re-derived — bitwise identical
# to the pre-refactor binning).
# ----------------------------------------------------------------------

def bin_rows_into(X: np.ndarray, mappers: Sequence[BinMapper],
                  used_features: Sequence[int], plan, store: np.ndarray,
                  row0: int) -> int:
    """Bin raw rows X into ``store[:, row0:row0+len(X)]`` against frozen
    mappers, using the native bulk binner for uint8 numerical columns
    when built.  With a bundle plan, packed features fold into their
    shared column (last writer wins on conflicting rows).  Returns the
    number of realized bundle conflicts observed."""
    dtype = store.dtype
    sl = slice(row0, row0 + len(X))
    conflicts = 0
    num_ks = [k for k, i in enumerate(used_features)
              if mappers[i].bin_type == NUMERICAL
              and (plan is None or not plan.feat_packed[k])]
    done = set()
    if dtype == np.uint8 and num_ks:
        from .native import bin_numerical_native
        cols = [used_features[k] for k in num_ks]
        uppers = [mappers[i].bin_upper_bound for i in cols]
        out = bin_numerical_native(np.ascontiguousarray(X), cols, uppers)
        if out is not None:
            for j, k in enumerate(num_ks):
                c = k if plan is None else int(plan.feat_col[k])
                store[c, sl] = out[j]
            done = set(num_ks)
    for k, i in enumerate(used_features):
        if k in done:
            continue
        b = mappers[i].value_to_bin(X[:, i])
        if plan is None or not plan.feat_packed[k]:
            c = k if plan is None else int(plan.feat_col[k])
            store[c, sl] = b.astype(dtype)
        else:
            conflicts += pack_bundle_column(
                b, int(plan.feat_default[k]), int(plan.feat_offset[k]),
                store[int(plan.feat_col[k]), sl])
    return conflicts


def bin_column_into(k: int, values: np.ndarray,
                    mappers: Sequence[BinMapper],
                    used_features: Sequence[int], plan,
                    store: np.ndarray) -> int:
    """Bin ONE used feature's full raw column into the store (the
    scipy-CSC column-streaming entry).  Returns realized conflicts."""
    c = k if plan is None else int(plan.feat_col[k])
    return bin_feature_column(k, values, mappers, used_features, plan,
                              store[c])


def bin_feature_column(k: int, values: np.ndarray,
                       mappers: Sequence[BinMapper],
                       used_features: Sequence[int], plan,
                       out: np.ndarray) -> int:
    """Bin ONE used feature's raw column into the [N] scratch row `out`
    of its own store column — bin_column_into with the destination row
    supplied by the caller, so the sparse CSR construction can fill a
    per-column scratch without allocating the dense store.  EFB
    last-writer-wins packing semantics are identical to the dense
    route.  Returns realized bundle conflicts."""
    b = mappers[used_features[k]].value_to_bin(values)
    if plan is None or not plan.feat_packed[k]:
        out[:] = b.astype(out.dtype)
        return 0
    return pack_bundle_column(
        b, int(plan.feat_default[k]), int(plan.feat_offset[k]), out)


# ----------------------------------------------------------------------
# SERVE policy: request-path ingress quantization
# ----------------------------------------------------------------------

# grid-accelerated numeric binning: cells are uniform in the float32
# TOTAL-ORDER KEY space (integer arithmetic end to end — no rounding
# anywhere), each cell stores the bin of its smallest key, and at most
# _GRID_ADJUST boundaries may fall inside any cell (checked at build;
# the grid refines until the budget holds or the feature falls back to
# searchsorted).  Lookup = shift + clip + one table gather + _GRID_ADJUST
# compare-increment steps — ~5x the throughput of numpy's per-value
# binary search on the serving ingress path.
_GRID_TARGET_BITS = 13          # ~8192 cells to start
_GRID_MAX_CELLS = 1 << 16
_GRID_ADJUST = 2


def _f32_keys(a32: np.ndarray) -> np.ndarray:
    """Monotone int64 keys of float32 values: a <= b in f32 iff
    key(a) <= key(b) for non-NaN values with -0.0 pre-normalized to
    +0.0 (the caller adds +0.0f, which is the identity everywhere
    else)."""
    u = np.asarray(a32, np.float32).view(np.uint32).astype(np.int64)
    return np.where(u >> 31, 0xFFFFFFFF - u, u + 0x80000000)


class _NumericGrid:
    """Per-feature acceleration index over the f32-cast upper bounds."""

    __slots__ = ("key0", "shift", "cells", "base", "fkeys_padded", "ok")

    def __init__(self, ub32: np.ndarray):
        fin = (ub32[:-1] + np.float32(0.0)).astype(np.float32)
        self.ok = False
        if fin.size == 0 or not np.isfinite(fin).all():
            return                        # 1-bin feature / inf bounds:
                                          # searchsorted fallback (rare)
        fkeys = _f32_keys(fin)
        span = int(fkeys[-1] - fkeys[0])
        shift = max(0, span.bit_length() - _GRID_TARGET_BITS)
        while True:
            cells = (span >> shift) + 1
            if cells > _GRID_MAX_CELLS:
                return                    # budget unreachable: fallback
            edges = fkeys[0] + (np.arange(cells + 1,
                                          dtype=np.int64) << shift)
            base = np.searchsorted(fkeys, edges, side="left")
            if np.diff(base).max(initial=0) <= _GRID_ADJUST:
                break
            if shift == 0:
                return
            shift -= 1
        self.key0 = int(fkeys[0])
        self.shift = shift
        self.cells = cells
        self.base = base.astype(np.int64)
        self.fkeys_padded = np.concatenate(
            [fkeys, np.full(_GRID_ADJUST, np.iinfo(np.int64).max)])
        self.ok = True

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """bin(v32) for the column's keys — exact: the cell's base bin
        is <= the true bin, at most _GRID_ADJUST boundaries sit in any
        cell, and each adjustment step advances iff the current bound's
        key is still below the value's (NaN keys yield garbage bins the
        caller overwrites with the MISSING sentinel)."""
        idx = np.clip((keys - self.key0) >> self.shift, 0, self.cells - 1)
        b = self.base[idx]
        for _ in range(_GRID_ADJUST):
            b = b + (self.fkeys_padded[b] < keys)
        return b

class FeatureQuantizer:
    """Frozen-mapper ingress quantizer for the binned serving path.

    ``quantize(X)`` maps raw ``[rows, num_total_features]`` requests to
    a ``[rows, num_columns]`` uint8 (uint16 past 255 bins) buffer of
    ORIGINAL per-feature bin ids over the used features — 4x (resp. 2x)
    smaller than the f32 buffer the raw kernel ships to the device, and
    bitwise-routing-equivalent to the raw f32 traversal (module
    docstring).  Bundled (EFB) stores need no remap here: trees speak
    original (feature, bin) space and the request buffer is built in
    it, so ``feat_tbl`` stays None on the request path.
    """

    __slots__ = ("used_features", "num_total_features", "num_columns",
                 "dtype", "missing_bin", "_numeric", "_tables",
                 "_num_ks", "_num_raw", "_num_uppers64", "_grids",
                 "_use_native")

    def __init__(self, mappers: Sequence[BinMapper],
                 used_features: Sequence[int]):
        self.used_features = [int(i) for i in used_features]
        self.num_total_features = len(mappers)
        # at least one buffer column so a stump-only model still has a
        # gatherable [rows, 1] buffer
        self.num_columns = max(len(self.used_features), 1)
        max_nb = max((mappers[i].num_bin for i in self.used_features),
                     default=1)
        # the MISSING sentinel needs one free code past every real bin
        if max_nb <= 0xFF:
            self.dtype = np.uint8
            self.missing_bin = 0xFF
        elif max_nb <= 0xFFFF:
            self.dtype = np.uint16
            self.missing_bin = 0xFFFF
        else:
            raise LightGBMError(
                f"cannot quantize serving requests: a mapper has "
                f"{max_nb} bins (> 65535)")
        self._numeric: List[bool] = []
        self._tables: List = []
        for i in self.used_features:
            m = mappers[i]
            if m.bin_type == CATEGORICAL:
                cats = np.asarray(m.bin_2_categorical, np.int64)
                order = np.argsort(cats)
                self._numeric.append(False)
                self._tables.append(
                    (cats[order],
                     np.arange(len(cats), dtype=np.int64)[order]))
            else:
                # f32 bounds: the compare domain of the raw kernels
                self._numeric.append(True)
                self._tables.append(
                    np.asarray(m.bin_upper_bound, np.float64)
                    .astype(np.float32))
        # native bulk-binner plumbing for the numeric block: the f32
        # bounds embedded exactly into f64 (float comparisons agree
        # across the embedding), so the C binary search reproduces the
        # f32 searchsorted bit-for-bit at ~10x the numpy throughput
        self._num_ks = [k for k, isn in enumerate(self._numeric) if isn]
        self._num_raw = [self.used_features[k] for k in self._num_ks]
        self._num_uppers64 = [self._tables[k].astype(np.float64)
                              for k in self._num_ks]
        # probe native availability ONCE: quantize() must not pay the
        # f64 staging copy of every chunk just to learn the library was
        # never built (the common pure-Python install)
        from .native import get_lib
        self._use_native = self.dtype == np.uint8 and get_lib() is not None
        # pure-numpy acceleration when the native library is not built:
        # integer-keyed grid index per numeric feature (exact, with a
        # per-feature searchsorted fallback when its cell budget fails)
        self._grids = [_NumericGrid(self._tables[k])
                       for k in self._num_ks]

    def quantize(self, X: np.ndarray) -> np.ndarray:
        """[rows, num_total_features] raw → [rows, num_columns] bins."""
        X = np.asarray(X)
        n = X.shape[0]
        out = np.zeros((n, self.num_columns), self.dtype)
        miss = self.missing_bin
        # ---- numeric block: one cast, bulk native binning when built ----
        sub32 = None
        if self._num_ks:
            sub32 = X[:, self._num_raw].astype(np.float32)
            nanmask = np.isnan(sub32)
            native_bins = None
            if self._use_native:
                from .native import bin_numerical_native
                sub64 = sub32.astype(np.float64)
                if nanmask.any():
                    # +inf lands in the last real bin — the native
                    # binner's NaN→0.0 convention must not fire; the
                    # sentinel overwrites these positions below
                    sub64[nanmask] = np.inf
                native_bins = bin_numerical_native(
                    np.ascontiguousarray(sub64),
                    list(range(len(self._num_ks))), self._num_uppers64)
            if native_bins is not None:
                for j, k in enumerate(self._num_ks):
                    out[:, k] = native_bins[j]
            else:
                # grid path: one key pass for the whole block (+0.0f
                # normalizes -0.0 so keys agree with f32 compares)
                keys = _f32_keys(sub32 + np.float32(0.0))
                for j, k in enumerate(self._num_ks):
                    g = self._grids[j]
                    if g.ok:
                        out[:, k] = g.lookup(keys[:, j])
                    else:
                        out[:, k] = np.searchsorted(
                            self._tables[k], sub32[:, j], side="left")
            if nanmask.any():
                out[:, self._num_ks] = np.where(
                    nanmask, self.dtype(miss), out[:, self._num_ks])
        # ---- categorical columns --------------------------------------
        for k, i in enumerate(self.used_features):
            if self._numeric[k]:
                continue
            col = X[:, i].astype(np.float32)
            cats, bins = self._tables[k]
            finite = np.isfinite(col)
            # int truncation behind the finite mask — the raw kernels'
            # categorical compare; the clip only silences the f32→int64
            # overflow warning (clipped magnitudes can match no
            # category either way)
            vi = np.clip(np.where(finite, col, np.float32(-1.0)),
                         -9.2e18, 9.2e18).astype(np.int64)
            if cats.size:
                pos = np.clip(np.searchsorted(cats, vi), 0,
                              cats.size - 1)
                hit = finite & (cats[pos] == vi)
                b = np.where(hit, bins[pos], miss)
            else:
                b = np.full(n, miss, np.int64)
            out[:, k] = b
        return out


# ----------------------------------------------------------------------
# refbin sidecar: the frozen-mapper contract between publisher and fleet
# ----------------------------------------------------------------------

def load_refbin(path: str, expected_sha1: Optional[str] = None):
    """Load a ``.refbin`` frozen-mapper sidecar (binary-dataset format:
    the online trainer publishes the window store, offline models write
    a 0-row `Dataset.save_refbin` shell).  The stored max_bin /
    enable_bundle settings are adopted from the file itself — a refbin
    is self-describing, not subject to the serving process's config.
    With ``expected_sha1`` (the publish meta's fingerprint), a
    mismatching file is refused before it is parsed.  The file is read
    and parsed ONCE (an online-published sidecar is a whole window
    store, and this runs on the registry's hot-swap path)."""
    import io

    from .config import Config
    from .dataset import Dataset
    with open(path, "rb") as f:
        blob = f.read()
    if expected_sha1:
        actual = hashlib.sha1(blob).hexdigest()
        if actual != expected_sha1:
            raise LightGBMError(
                f"refbin sidecar {path} sha1 {actual[:12]} does not match "
                f"the publish meta's {str(expected_sha1)[:12]} (torn "
                "write or stale sidecar); refusing the binned mapper set")
    bio = io.BytesIO(blob)
    first = bio.readline().strip().decode(errors="replace")
    if first != Dataset.BINARY_MAGIC:
        raise LightGBMError(
            f"{path} is not a lightgbm_tpu refbin sidecar")
    npz = np.load(bio, allow_pickle=False)
    d = {k: npz[k] for k in npz.files}
    # sparse_store pinned dense: a refbin is a mapper-set contract —
    # serving consumers read its mappers/plan, never histogram it, so
    # re-deriving a CSR store (then densifying on first .bins read)
    # would be pure hot-swap-path churn
    cfg = Config(max_bin=int(d["max_bin"]),
                 enable_bundle=bool(int(d["enable_bundle"])),
                 sparse_store="dense", verbose=-1)
    return Dataset._from_binary_dict(d, cfg, path)


def _check_thresholds_representable(t, k, refbin, sf: np.ndarray) -> None:
    """Every threshold must BE a bin boundary of the refbin's mappers —
    the condition the bitwise argument actually requires: ``bin(v) <=
    bin(t)`` collapses to the raw ``v <= t`` only when
    ``upper[bin(t)] == t`` exactly (and a categorical threshold must be
    IN the mapper's table, else the rebin maps it to bin 0 and the
    binned walk would match the wrong category).  A sidecar frozen from
    OTHER data — e.g. an online daemon's window mappers when the input
    model trained elsewhere — fails here instead of silently misrouting
    the rows that fall between a model threshold and the sidecar's next
    boundary."""
    thr = np.asarray(t.threshold[:k], np.float64)
    tib = np.asarray(t.threshold_in_bin[:k], np.int64)
    for f in np.unique(sf):
        m = refbin.mappers[int(f)]
        sel = sf == f
        tb = tib[sel]
        if m.bin_type == CATEGORICAL:
            cats = np.asarray(m.bin_2_categorical, np.int64)
            ok = ((tb >= 0) & (tb < cats.size)
                  & (cats[np.clip(tb, 0, max(cats.size - 1, 0))]
                     == thr[sel].astype(np.int64)))
        else:
            ub = np.asarray(m.bin_upper_bound, np.float64)
            ok = ((tb >= 0) & (tb < ub.size)
                  & (ub[np.clip(tb, 0, ub.size - 1)] == thr[sel]))
        if not bool(ok.all()):
            raise LightGBMError(
                "refbin mapper set cannot represent the model's "
                "thresholds exactly (a threshold is not a bin boundary "
                "of the sidecar's mappers); binned serving would "
                "misroute — serve raw, or ship the model's own training "
                "mappers as the sidecar (Dataset.save_refbin; the "
                "online daemon adopts input_model's sidecar)")


def rebin_models_for_serving(models, refbin) -> None:
    """Give every tree in-bin thresholds/inner features for the refbin
    mapper set, refusing combinations that cannot route exactly.

    Loaded trees (the registry path) rebin from their real-valued
    thresholds; in-session trees already carry in-bin data for their
    TRAINING mappers, which is verified to agree with the refbin's.
    EVERY tree then passes the threshold-representability check — the
    actual exactness condition (see `_check_thresholds_representable`).
    A model splitting on a feature the refbin filtered as trivial is
    refused outright: the rebin would freeze that node's routing to one
    side while the raw kernel still compares per-row.
    """
    nt = int(refbin.num_total_features)
    inner_map = np.full(nt, -1, np.int64)
    inner_map[np.asarray(refbin.used_features, np.int64)] = np.arange(
        len(refbin.used_features))
    for t in models:
        k = t.num_leaves - 1
        if k <= 0:
            continue
        sf = np.asarray(t.split_feature[:k], np.int64)
        if int(sf.max(initial=-1)) >= nt or bool(np.any(inner_map[sf] < 0)):
            raise LightGBMError(
                "model splits on a feature that is trivial or absent in "
                "the refbin mapper set; binned serving cannot route it "
                "exactly (serve raw instead)")
        if getattr(t, "needs_rebin", False):
            t.rebin_to_dataset(refbin)
        elif not np.array_equal(inner_map[sf],
                                np.asarray(t.split_feature_inner[:k],
                                           np.int64)):
            raise LightGBMError(
                "refbin sidecar does not match the model's training "
                "mappers (used-feature mapping differs)")
        _check_thresholds_representable(t, k, refbin, sf)
