"""Leveled logger + CHECK asserts.

Reference: include/LightGBM/utils/log.h — `Log::{Debug,Info,Warning,
Fatal}` gated by a process-wide verbosity, and `CHECK()`/`CHECK_NOTNULL()`
fatal asserts that raise instead of aborting (log.h:17-38).

Verbosity mapping follows the reference config semantics
(`verbosity`/`verbose`): <0 fatal-only, 0 warnings, 1 info (default),
>=2 debug.  `configure(verbose)` is called by the CLI and the Python
entry points whenever a Config is parsed.
"""
from __future__ import annotations

import sys
from typing import Any, Optional

FATAL = -1
WARNING = 0
INFO = 1
DEBUG = 2

_level = INFO


class LightGBMError(Exception):
    """The package-wide error type (mirrors the reference's thrown
    std::runtime_error from Log::Fatal)."""


def configure(verbose: int) -> None:
    global _level
    _level = int(verbose)


def level() -> int:
    return _level


def debug(msg: str) -> None:
    if _level >= DEBUG:
        print(f"[LightGBM-TPU] [Debug] {msg}", flush=True)


def info(msg: str) -> None:
    if _level >= INFO:
        print(f"[LightGBM-TPU] [Info] {msg}", flush=True)


def warning(msg: str) -> None:
    if _level >= WARNING:
        print(f"[LightGBM-TPU] [Warning] {msg}", file=sys.stderr,
              flush=True)


def fatal(msg: str) -> None:
    """Log and raise (log.h:27-33: Fatal always prints, then throws)."""
    print(f"[LightGBM-TPU] [Fatal] {msg}", file=sys.stderr, flush=True)
    raise LightGBMError(msg)


def check(cond: bool, msg: str = "") -> None:
    """CHECK(condition) (log.h:17-19)."""
    if not cond:
        fatal(f"Check failed: {msg}" if msg else "Check failed")


def check_notnull(value: Optional[Any], name: str = "value") -> Any:
    """CHECK_NOTNULL (log.h:21-23)."""
    if value is None:
        fatal(f"{name} must not be None")
    return value
