"""Out-of-core streamed dataset construction (`Dataset.from_stream`).

The monolithic path materializes the full [N, F] float64 matrix before
binning — the host-memory ceiling ROADMAP #1 names for
millions-of-users datasets (~2.4 GB at HIGGS scale, ~60 GB at Expo).
This module builds the SAME binned Dataset from a re-iterable stream of
row chunks in two passes:

- pass 1 feeds every chunk into mergeable quantile sketches
  (sharded/sketch.py) and, when EFB is on, collects a bounded
  bundle-plan sample — peak memory is one chunk plus O(F / eps)
  summaries (plus the bounded exact buffer in `bin_find=auto` mode, the
  same budget the batch sampler already spends);
- pass 2 bins chunk-by-chunk into the PR 8 capacity-tiered appendable
  store (power-of-two tiers seeded at the known row count, so nothing
  re-allocates and compiled kernel shapes never retrace per chunk).

Peak host RSS therefore scales with `stream_chunk_rows` plus the binned
store (~1 byte/cell), never with the raw float64 matrix — measured in
bench_ingest_measured.json.

Bitwise contract (tests/test_ingest.py): while the data fits the
bin-construction sample budget (`bin_construct_sample_cnt` rows —
`bin_find=auto` keeps the sketches exact up to exactly that budget) and
the bundle-plan sample cap, the streamed store, labels, weights and
BundlePlan are IDENTICAL to batch `Dataset(X, y)` construction,
whatever chunk sizes the stream arrives in.  Beyond the budget the
mappers carry the sketch's documented eps rank guarantee (the batch
path subsamples there too — neither side is "exact" past the budget).

`Dataset.streaming_from` (frozen-mapper appends) and the
`OnlineTrainer`'s first-window freeze route through this module as
well, so online ingestion and offline out-of-core construction share
one chunk-append path.
"""
from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from .sketch import SketchSet


def array_stream(X, y=None, weight=None, chunk_rows: int = 262_144
                 ) -> Callable[[], Iterable[tuple]]:
    """Chunk factory over in-memory arrays: returns a callable yielding
    (X, y, w) row slices of `chunk_rows` — the adapter that lets
    from_stream's two passes walk an array the same way they would walk
    a file."""
    X = np.asarray(X)
    step = max(int(chunk_rows), 1)

    def chunks():
        for r0 in range(0, X.shape[0], step):
            sl = slice(r0, r0 + step)
            yield (X[sl],
                   None if y is None else np.asarray(y)[sl],
                   None if weight is None else np.asarray(weight)[sl])
    return chunks


def _normalize_chunk(chunk) -> Tuple[np.ndarray, Optional[np.ndarray],
                                     Optional[np.ndarray]]:
    """Accept (X,), (X, y), (X, y, w) tuples or a bare X array."""
    if isinstance(chunk, (tuple, list)):
        X = chunk[0]
        y = chunk[1] if len(chunk) > 1 else None
        w = chunk[2] if len(chunk) > 2 else None
    else:
        X, y, w = chunk, None, None
    return np.asarray(X, np.float64), y, w


def _chunk_factory(chunks, cfg: Config) -> Callable[[], Iterable]:
    """Normalize the `chunks` argument to a re-invokable factory.

    - callable: called once per pass (a file reader re-opens the file);
    - (X, y[, w]) array tuple: chunked by cfg.stream_chunk_rows;
    - list/tuple of chunk tuples: iterated per pass.
    A one-shot generator cannot feed two passes — rejected with a clear
    error instead of a silently empty second pass."""
    if callable(chunks):
        return chunks
    if (isinstance(chunks, tuple) and chunks
            and getattr(chunks[0], "ndim", 0) == 2):
        X, y, w = _normalize_chunk(chunks)
        return array_stream(X, y, w, chunk_rows=cfg.stream_chunk_rows)
    if isinstance(chunks, (list, tuple)):
        seq = list(chunks)
        return lambda: iter(seq)
    raise TypeError(
        "from_stream needs a re-iterable chunk source: a callable "
        "returning a fresh iterator, a list of (X, y, w) chunks, or an "
        "(X, y[, w]) array tuple — a one-shot generator cannot feed "
        "the sketch pass AND the binning pass")


def dataset_from_stream(chunks, config: Optional[Config] = None,
                        reference=None,
                        feature_names: Optional[List[str]] = None,
                        categorical_feature: Sequence[int] = (),
                        capacity: int = 0):
    """Build a binned Dataset from a stream of row chunks — see the
    module docstring.  Returns an APPENDABLE capacity-tiered dataset
    (`row_capacity` >= num_data); training learners consume
    `.compacted()`, and further `append_rows` keep streaming into it.

    reference: bin against an existing dataset's FROZEN mappers +
    bundle plan instead of running the sketch pass (the online-window
    path) — single pass, no sketches.
    capacity: seed the store's capacity tier (defaults to the counted
    row total, so the two-pass path never re-allocates)."""
    from ..dataset import (BUNDLE_PLAN_SAMPLE_CNT, Dataset,
                           _plan_bundles_from_sample, _log_bundle_state,
                           row_capacity_tier)

    cfg = config or (reference.config if reference is not None else Config())
    factory = _chunk_factory(chunks, cfg)

    if reference is not None:
        ds = Dataset.streaming_from(reference, cfg,
                                    capacity=max(int(capacity), 1))
        for chunk in factory():
            X, y, w = _normalize_chunk(chunk)
            ds.append_rows(X, y, w)
        ds._check_realized_conflicts()
        return ds

    # ---- pass 1: sketches (+ bounded bundle-plan sample) ---------------
    mode = getattr(cfg, "bin_find", "auto")
    # auto: exact summaries while the data fits the sample budget — the
    # regime where streamed == batch bitwise; sketch=pure eps summaries
    min_cap = int(cfg.bin_construct_sample_cnt) if mode != "sketch" else 0
    ss: Optional[SketchSet] = None
    plan_rows: List[np.ndarray] = []
    plan_count = 0
    n_rows = 0
    for chunk in factory():
        X, _y, _w = _normalize_chunk(chunk)
        if ss is None:
            ss = SketchSet(X.shape[1], cfg.sketch_eps,
                           categorical=categorical_feature,
                           min_capacity_rows=min_cap)
        ss.add_chunk(X)
        n_rows += len(X)
        if cfg.enable_bundle and plan_count < BUNDLE_PLAN_SAMPLE_CNT:
            take = min(BUNDLE_PLAN_SAMPLE_CNT - plan_count, len(X))
            if take:
                plan_rows.append(X[:take].copy())
                plan_count += take
    if ss is None or n_rows == 0:
        raise ValueError("from_stream: the chunk stream yielded no rows")

    mappers = ss.mappers_from_config(cfg)
    used = [i for i, m in enumerate(mappers) if not m.is_trivial]
    plan = None
    if cfg.enable_bundle and plan_rows:
        plan = _plan_bundles_from_sample(
            np.concatenate(plan_rows), mappers, used, cfg)
    _log_bundle_state(plan, len(used), cfg)
    del plan_rows

    # ---- pass 2: bin chunk-by-chunk into the tiered store --------------
    cap = row_capacity_tier(max(int(capacity), n_rows))
    ds = Dataset._empty_from_mappers(cfg, mappers, used, cap,
                                     ss.num_features, feature_names,
                                     plan=plan)
    ds.bins[:] = 0       # streaming slots past num_data hold bin 0
    ds.num_data = 0
    for chunk in factory():
        X, y, w = _normalize_chunk(chunk)
        ds.append_rows(X, y, w)
    if ds.num_data != n_rows:
        raise ValueError(
            f"from_stream: the chunk source yielded {ds.num_data} rows "
            f"on the binning pass but {n_rows} on the sketch pass — "
            "the source must replay identically (is it a one-shot "
            "iterator wrapped in a callable?)")
    if ds.metadata.label.size == 0:
        ds.metadata.label = np.zeros(ds.num_data, np.float32)
    ds._check_realized_conflicts()
    ds._sketch_err_bound = ss.err_bound()
    ds._sketch_exact = ss.exact
    return ds
