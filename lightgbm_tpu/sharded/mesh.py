"""Sharded-primitive layer: every mesh/axis/shard_map decision in ONE
module.

The two mesh learners (learner/rounds.py, learner/fused.py) used to
carry their own copies of the mesh-axis resolution, the shard_map
compatibility shim, the column padding + scatter-divisibility guards,
the psum/psum_scatter selection, and the multi-host row-block assembly
— the exact duplication ROADMAP #1 named as the refactor blocking
multi-host work.  This module is that single layer; learner/common.py
re-exports the names so existing imports keep working.

Axis convention: a learner mesh always names its axes ("data",
"feature").  Rows shard over EVERY axis with size > 1 (the row axes);
under the psum_scatter exchange, the reduced histogram's store-column
axis is scattered over ONE of them — the data axis on a 1-D mesh, the
feature axis on a 2-D (data x feature) mesh, where the exchange
becomes "psum over data, reduce-scatter over feature"
(docs/Distributed-Data.md)."""
from __future__ import annotations

import math
import os
from typing import Dict, Optional, Tuple

import numpy as np

from ..config import Config


def mesh_axes(mesh) -> Dict[str, int]:
    """{axis name: size} of a mesh (empty when mesh is None)."""
    if mesh is None:
        return {}
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def row_shard_axes(dd: int, df: int) -> Optional[Tuple[str, ...]]:
    """The mesh axes a learner's row dimension shards over: every axis
    with more than one device.  None on a single-device mesh."""
    axes = tuple(name for name, size in (("data", dd), ("feature", df))
                 if size > 1)
    return axes or None


class MultiHostRows:
    """Row-block layout + assembly for multi-process data-parallel
    training: the mesh "data" axis spans processes, each process owns one
    contiguous row block (the loader's pre-partition contract,
    dataset.py pre_partition; reference dataset_loader.cpp:554-659).

    Every process pads its block to the same per-process length so the
    global [Np] row axis tiles evenly over the axis devices; global
    arrays are assembled with `jax.make_array_from_process_local_data`
    (the multi-controller analog of the reference's implicit "my rows
    are mine" layout — no data ever crosses hosts, only collectives).
    """

    def __init__(self, mesh, n_local: int):
        import jax
        from jax.experimental import multihost_utils
        axes = mesh_axes(mesh)
        dd = int(axes.get("data", 1))
        self.world = jax.process_count()
        if dd % self.world:
            raise ValueError(
                f"data axis ({dd}) must be divisible by the process count "
                f"({self.world}) for multi-host training")
        if int(axes.get("feature", 1)) > 1:
            raise NotImplementedError(
                "multi-host feature-parallel training is not supported; "
                "use tree_learner=data")
        self.local_dd = dd // self.world
        ns = np.asarray(multihost_utils.process_allgather(
            np.asarray([n_local], np.int64))).reshape(-1)
        self.n_local = int(n_local)
        per = int(ns.max())
        self.per_proc = self.local_dd * int(math.ceil(
            per / self.local_dd)) if per else self.local_dd
        self.np_global = self.per_proc * self.world
        self.n_global = int(ns.sum())
        self.mesh = mesh

    def pad_local(self, x: np.ndarray) -> np.ndarray:
        """Zero-pad the last (row) axis of a LOCAL block to per_proc."""
        pad = self.per_proc - x.shape[-1]
        if pad == 0:
            return x
        widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
        return np.pad(x, widths)

    def put_rows(self, x_local: np.ndarray, spec):
        """Assemble the global row-sharded array from this process's
        padded local block (shape [..., per_proc])."""
        import jax
        from jax.sharding import NamedSharding
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, spec), np.ascontiguousarray(x_local))

    def local_rows(self, arr) -> np.ndarray:
        """Extract this process's rows from a global row-sharded array
        (last axis = rows), trimmed back to the unpadded local length."""
        shards = sorted(
            ((s.index[-1].start or 0, np.asarray(s.data))
             for s in arr.addressable_shards), key=lambda t: t[0])
        return np.concatenate([d for _, d in shards],
                              axis=-1)[..., : self.n_local]


def compat_shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map with a fallback to the pre-graduation API
    (jax<=0.5 ships it as jax.experimental.shard_map.shard_map, with
    the replication-check flag named check_rep instead of check_vma)."""
    import jax
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=check_vma)


def pad_cols_to_ndev(n_cols: int, ndev: int, align: int = 1) -> int:
    """Smallest column count >= `n_cols` that tiles the mesh axis the
    psum_scatter histogram exchange scatters over: a multiple of
    lcm(ndev, align) (`align` carries a kernel layout constraint, e.g.
    the int8 store's 32-sublane grouping; pass ndev = data*feature for
    a 2-D mesh, where the per-feature-shard slice must itself tile the
    data axis).  Raises a clear ValueError on degenerate mesh sizes
    instead of letting lax.psum_scatter fail with a raw XLA tiling
    error downstream."""
    if ndev < 1 or align < 1:
        raise ValueError(
            f"pad_cols_to_ndev: mesh axis size ({ndev}) and alignment "
            f"({align}) must be >= 1; a zero-sized mesh axis cannot be "
            "tiled by any column padding")
    unit = math.lcm(int(ndev), int(align))
    return unit * int(math.ceil(max(int(n_cols), 1) / unit))


def check_scatter_divisible(axis: str, size: int, ndev: int) -> None:
    """Trace-time guard in front of `lax.psum_scatter`: raise a clear
    ValueError naming the axis, its size, and the mesh axis size when
    the scattered axis cannot tile the mesh.  The learners pad their
    stores with pad_cols_to_ndev so this never fires on the built-in
    paths; a caller wiring build_tree* directly without padding used to
    get a bare `assert` (gone under `python -O`, leaving the raw XLA
    shape error at the psum_scatter dispatch)."""
    if ndev > 1 and size % ndev:
        raise ValueError(
            f"psum_scatter needs the scattered axis '{axis}' (size "
            f"{size}) to be a multiple of the mesh axis size "
            f"({ndev}); pad the store columns with "
            f"sharded.mesh.pad_cols_to_ndev "
            f"({pad_cols_to_ndev(size, ndev)} would tile)")


def check_tree_divergence(name: str, arrs, packed=None) -> None:
    """BENCH_SANITIZE divergence gate shared by every mesh learner
    (diagnostics/sanitize.py): the tree a build returned is replicated
    state — every device must hold the bitwise-identical copy, or a
    shard-local value leaked into the growth loop's control flow.
    Fingerprints one pytree shape for all learners (the packed tree
    vector plus leaf counts) so their divergence reports stay
    comparable across tree_growth modes.  No-op (one env read) unless
    the sanitizer is enabled; `packed` is computed only then when the
    caller has not already paid for it."""
    from ..diagnostics import sanitize
    if not sanitize.sanitize_enabled():
        return
    if packed is None:
        from ..learner.fused import pack_tree_arrays
        packed = pack_tree_arrays(arrs)
    sanitize.maybe_check_divergence(name, {"packed_tree": packed,
                                           "leaf_count": arrs.leaf_count})


# `hist_exchange=auto` switches to psum_scatter only when the per-pass
# histogram payload is at least this many bytes: below it the full psum
# is cheaper than reduce-scatter + the per-leaf record allgather
# (mirroring the reference's allgather-vs-Recursive-Halving switch on
# small payloads, network.cpp ReduceScatter dispatch / SURVEY.md §2.8).
# The measured crossover on chip is captured by
# scripts/profile_hotpath.py (hist_exchange_ab_measured.json); the
# validated Config key `hist_exchange_min_bytes` pins it per run, and
# LGBT_HIST_EXCHANGE_MIN_BYTES remains the ad-hoc env override for
# on-chip tuning when the key is unset.
HIST_EXCHANGE_MIN_SCATTER_BYTES = 1 << 20


def _hist_exchange_threshold(cfg: Optional[Config] = None) -> int:
    cfg_v = int(getattr(cfg, "hist_exchange_min_bytes", -1)) \
        if cfg is not None else -1
    if cfg_v >= 0:
        return cfg_v
    raw = os.environ.get("LGBT_HIST_EXCHANGE_MIN_BYTES", "")
    if not raw:
        return HIST_EXCHANGE_MIN_SCATTER_BYTES
    try:
        return max(0, int(raw))
    except ValueError:
        from .. import log
        log.warning(f"ignoring malformed LGBT_HIST_EXCHANGE_MIN_BYTES="
                    f"{raw!r}")
        return HIST_EXCHANGE_MIN_SCATTER_BYTES


def resolve_hist_exchange(cfg: Config, *, ndev: int,
                          payload_bytes: float) -> str:
    """Resolve `hist_exchange` to the collective a data-parallel learner
    runs per histogram pass.  `payload_bytes` is the full reduced
    histogram size of one pass (K * F * 3 * B * 4); with a single device
    there is no exchange and the answer is always "psum" (a no-op).
    `ndev` is the total device count of the mesh's row axes."""
    if ndev <= 1:
        return "psum"
    mode = getattr(cfg, "hist_exchange", "auto")
    if mode == "auto":
        return ("psum_scatter"
                if payload_bytes >= _hist_exchange_threshold(cfg)
                else "psum")
    return mode


def make_mesh(tree_learner: str, num_machines: int = 0):
    """Mesh for a distributed learner type.  `data` shards rows,
    `feature` shards the split search (reference tree_learner types,
    config.h:233; the topology/linker machinery of src/network is
    replaced by the mesh itself)."""
    import jax
    devs = jax.devices()
    if jax.process_count() > 1:
        # num_machines counts HOSTS (reference config.h:246); the mesh
        # always spans every device of the multi-process world
        n = len(devs)
    else:
        n = num_machines if num_machines and num_machines > 1 else len(devs)
        n = min(n, len(devs))
    if n <= 1:
        return None
    devs = np.asarray(devs[:n])
    if tree_learner in ("data", "voting"):
        return jax.sharding.Mesh(devs.reshape(n, 1), ("data", "feature"))
    if tree_learner == "feature":
        return jax.sharding.Mesh(devs.reshape(1, n), ("data", "feature"))
    # hybrid "data2d": balanced 2-D factorization
    df = 1
    for f in range(int(math.isqrt(n)), 0, -1):
        if n % f == 0:
            df = f
            break
    return jax.sharding.Mesh(devs.reshape(n // df, df), ("data", "feature"))
