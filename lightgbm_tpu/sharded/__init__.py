"""Pod-scale data plane (ROADMAP #1): the pieces that let training data
be found, binned, and sharded without any single host ever holding the
whole dataset.

- `sketch`  — mergeable quantile sketches: per-host / per-chunk weighted
  summaries that merge with one small collective, replacing the
  full-sample allgather of distributed bin finding.
- `ingest`  — out-of-core streamed dataset construction:
  `Dataset.from_stream` runs a sketch pass then bins chunk-by-chunk
  into the capacity-tiered store, so peak host memory scales with
  `stream_chunk_rows`, not with the dataset length.
- `mesh`    — the sharded-primitive layer: mesh/axis resolution,
  shard_map compatibility, column padding and scatter-divisibility
  guards, psum/psum_scatter selection, and the multi-host row-block
  assembly shared by every mesh learner (previously duplicated across
  learner/common.py, rounds.py and fused.py).
"""
