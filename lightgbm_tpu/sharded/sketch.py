"""Mergeable quantile sketches for distributed / out-of-core bin finding.

The reference finds distributed bins by sharding FEATURES across
machines and allgathering serialized mappers
(/root/reference/src/io/dataset_loader.cpp:733-833); our PR-era
`find_bin_mappers_distributed` instead allgathers the entire padded row
sample to every process — one [S, F] float64 collective whose payload
grows with the sample budget, and the very thing that stops "millions of
users" datasets from binning out-of-core.  The GPU boosting literature
(arXiv:1706.08359, arXiv:1806.11248) replaces the sample exchange with
MERGEABLE QUANTILE SUMMARIES: each host (or each stream chunk)
summarizes every feature into O(1/eps) weighted entries, the summaries
merge associatively, and bin boundaries come from the merged summary
with a provable rank guarantee.  This module is that summary.

Design (GK-style weighted summary, vectorized in numpy):

- A sketch holds sorted distinct `vals` with per-value `counts`.  While
  it has never compacted, it IS the exact distinct-value summary —
  `find_bin_from_distinct` on it is bitwise the exact mapper (the
  "exact small-N mode").
- When entries exceed `capacity` = O(1/eps), the sketch COMPACTS to
  capacity/2 even-weight buckets.  Each retained entry represents the
  value interval back to its predecessor; compaction preserves the
  cumulative counts AT bucket ends exactly, so the only error source is
  interval RESOLUTION: a later value landing inside a compacted
  interval inherits up to that interval's weight of rank uncertainty.
  `res` tracks the widest multi-entry bucket ever formed; the rank of
  any entry is exact to within `res` (the error is inherited from
  exactly one interval, never stacked across generations — bucket ends
  keep their cumsums through every subsequent compaction).
- MERGING two sketches interleaves their entries.  Each side's
  cumulative counts are then additionally uncertain by the other
  side's resolution at the interleaved positions, so the merge adds
  `max(res_a, res_b)` of attribution fuzz.  `err_bound() = fuzz + res`
  is the total rank uncertainty the sketch self-reports — the
  authoritative per-instance bound (tests assert against it).

Guarantees (documented in docs/Distributed-Data.md):

- single stream of chunks (out-of-core ingestion): fuzz stays 0 and
  `err_bound() = res <= 2 * eps * total` (measured ~eps * total / 2
  typical at capacity 8/eps);
- W-way host merge: `err_bound() <= ~2 * eps * N_global` (each host
  contributes its resolution plus one merge-fuzz term);
- while every sketch stays exact (entries never exceeded capacity),
  `err_bound() == 0` and the derived mappers are BITWISE the exact
  ones.

Serialization is fixed-width float64 (`pack` / `unpack`), so a
`SketchSet` travels through `distributed.allgather_f64` bit-exactly in
ONE small collective of O(F / eps) — no host ever materializes the
global sample.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..binning import (BinMapper, CATEGORICAL, NUMERICAL,
                       find_bin_from_distinct)


def sketch_capacity(eps: float) -> int:
    """Entries per feature summary: compaction prunes to capacity/2
    even-weight buckets of ~ eps * total / 4 rows each; the bucket that
    absorbs a previously-compacted entry can reach twice that, so the
    self-reported resolution stays within ~ eps * total / 2 (measured;
    `err_bound()` is always the authoritative per-instance bound)."""
    return max(64, int(math.ceil(8.0 / float(eps))))


class QuantileSketch:
    """Mergeable weighted quantile summary of ONE feature's non-zero,
    non-NaN sample values (zeros are implied by the row count, exactly
    like binning._distinct_with_zero)."""

    __slots__ = ("eps", "capacity", "vals", "counts", "res", "fuzz")

    def __init__(self, eps: float, capacity: int = 0):
        self.eps = float(eps)
        self.capacity = int(capacity) or sketch_capacity(eps)
        self.vals = np.zeros(0, np.float64)
        self.counts = np.zeros(0, np.float64)
        self.res = 0.0    # value-resolution rank error (widest bucket)
        self.fuzz = 0.0   # cross-sketch attribution error

    @property
    def total(self) -> float:
        return float(self.counts.sum())

    @property
    def exact(self) -> bool:
        """True while the summary still holds every distinct value with
        its exact count — mappers derived from it are bitwise the exact
        ones."""
        return self.res == 0.0 and self.fuzz == 0.0

    def err_bound(self) -> float:
        """Self-reported rank uncertainty: any cumulative count read off
        this sketch is within this many rows of the true rank."""
        return self.res + self.fuzz

    def add(self, values: np.ndarray) -> None:
        """Absorb a batch of raw values (NaN filtered here; zero/total
        bookkeeping is the caller's, matching find_bin's contract)."""
        values = np.asarray(values, np.float64)
        values = values[~np.isnan(values)]
        if values.size == 0:
            return
        nv, nc = np.unique(values, return_counts=True)
        self._absorb(nv, nc.astype(np.float64))

    def merge(self, other: "QuantileSketch") -> None:
        """Merge another sketch (disjoint data) into this one."""
        if other.vals.size == 0:
            return
        if self.vals.size == 0:
            self.vals = other.vals.copy()
            self.counts = other.counts.copy()
            self.res, self.fuzz = other.res, other.fuzz
            return
        # each side's cumulative counts are fuzzy at the OTHER side's
        # entry positions by that side's interval resolution
        self.fuzz = self.fuzz + other.fuzz + max(self.res, other.res)
        self.res = max(self.res, other.res)
        self._absorb(other.vals, other.counts)

    def _absorb(self, v2: np.ndarray, c2: np.ndarray) -> None:
        v = np.concatenate([self.vals, v2])
        c = np.concatenate([self.counts, c2])
        uv, inv = np.unique(v, return_inverse=True)
        uc = np.zeros(uv.size, np.float64)
        np.add.at(uc, inv, c)
        self.vals, self.counts = uv, uc
        if uv.size > self.capacity:
            self._compact()

    def _compact(self) -> None:
        """Prune to capacity/2 even-weight buckets.  Each retained entry
        keeps the bucket's LAST value and the bucket's total weight, so
        cumulative counts at retained entries stay exact; min (entry 0)
        and max (last entry, always a bucket end) are preserved."""
        m = max(self.capacity // 2, 2)
        W = np.cumsum(self.counts)
        total = W[-1]
        targets = total * (np.arange(1, m + 1, dtype=np.float64) / m)
        idx = np.searchsorted(W, targets, side="left")
        idx = np.unique(np.minimum(idx, self.vals.size - 1))
        if idx[0] != 0:
            idx = np.concatenate([[0], idx])
        newc = np.diff(np.concatenate([[0.0], W[idx]]))
        starts = np.concatenate([[-1], idx[:-1]])
        multi = (idx - starts) > 1          # buckets that merged entries
        if multi.any():
            self.res = max(self.res, float(newc[multi].max()))
        self.vals = self.vals[idx]
        self.counts = newc

    # -- fixed-width serialization (allgather transport) ----------------

    WIDTH_SCALARS = 4                       # n_entries, total, res, fuzz

    def pack_width(self) -> int:
        return 2 * self.capacity + self.WIDTH_SCALARS

    def pack(self) -> np.ndarray:
        n = self.vals.size
        if n > self.capacity:               # defensive; _absorb compacts
            self._compact()
            n = self.vals.size
        out = np.zeros(self.pack_width(), np.float64)
        out[0] = float(n)
        out[1] = self.total
        out[2] = self.res
        out[3] = self.fuzz
        s = self.WIDTH_SCALARS
        out[s:s + n] = self.vals
        out[s + self.capacity:s + self.capacity + n] = self.counts
        return out

    @classmethod
    def unpack(cls, arr: np.ndarray, eps: float, capacity: int
               ) -> "QuantileSketch":
        sk = cls(eps, capacity)
        n = int(arr[0])
        sk.res = float(arr[2])
        sk.fuzz = float(arr[3])
        s = cls.WIDTH_SCALARS
        sk.vals = np.asarray(arr[s:s + n], np.float64).copy()
        sk.counts = np.asarray(
            arr[s + capacity:s + capacity + n], np.float64).copy()
        return sk


class CategoricalCounter:
    """Exact per-category counts (categories are small int sets; rank
    compaction makes no sense for them).  When cardinality exceeds the
    capacity, the rarest categories are dropped — consistent with the
    reference's 98%-coverage cut (bin.cpp:188-240), which never keeps
    ultra-rare categories anyway."""

    __slots__ = ("capacity", "vals", "counts", "dropped")

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self.vals = np.zeros(0, np.float64)
        self.counts = np.zeros(0, np.float64)
        self.dropped = 0.0

    @property
    def total(self) -> float:
        return float(self.counts.sum()) + self.dropped

    @property
    def exact(self) -> bool:
        """False once any category was dropped — the derived mapper may
        then differ from the exact one (the bitwise contract requires
        every counter exact, SketchSet.exact)."""
        return self.dropped == 0.0

    def err_bound(self) -> float:
        """Dropped mass is unattributed count — the categorical analog
        of rank uncertainty."""
        return self.dropped

    def add(self, values: np.ndarray) -> None:
        values = np.asarray(values, np.float64)
        values = values[~np.isnan(values)]
        if values.size == 0:
            return
        nv, nc = np.unique(values, return_counts=True)
        self._absorb(nv, nc.astype(np.float64))

    def merge(self, other: "CategoricalCounter") -> None:
        self.dropped += other.dropped
        if other.vals.size:
            self._absorb(other.vals, other.counts)

    def _absorb(self, v2, c2) -> None:
        v = np.concatenate([self.vals, v2])
        c = np.concatenate([self.counts, c2])
        uv, inv = np.unique(v, return_inverse=True)
        uc = np.zeros(uv.size, np.float64)
        np.add.at(uc, inv, c)
        if uv.size > self.capacity:
            order = np.argsort(-uc, kind="stable")
            keep = np.sort(order[: self.capacity])
            self.dropped += float(uc.sum() - uc[keep].sum())
            uv, uc = uv[keep], uc[keep]
        self.vals, self.counts = uv, uc

    # same wire format as QuantileSketch (res slot carries `dropped`)
    def pack_width(self) -> int:
        return 2 * self.capacity + QuantileSketch.WIDTH_SCALARS

    def pack(self) -> np.ndarray:
        out = np.zeros(self.pack_width(), np.float64)
        n = self.vals.size
        out[0] = float(n)
        out[1] = self.total
        out[2] = self.dropped
        s = QuantileSketch.WIDTH_SCALARS
        out[s:s + n] = self.vals
        out[s + self.capacity:s + self.capacity + n] = self.counts
        return out

    @classmethod
    def unpack(cls, arr: np.ndarray, capacity: int) -> "CategoricalCounter":
        cc = cls(capacity)
        n = int(arr[0])
        cc.dropped = float(arr[2])
        s = QuantileSketch.WIDTH_SCALARS
        cc.vals = np.asarray(arr[s:s + n], np.float64).copy()
        cc.counts = np.asarray(
            arr[s + capacity:s + capacity + n], np.float64).copy()
        return cc


class SketchSet:
    """Per-feature sketches + the shared row count: everything needed to
    derive global BinMappers without the raw sample.

    `min_capacity_rows` raises each numerical sketch's capacity so the
    summary stays EXACT while the data fits the bin-construction sample
    budget — the `bin_find=auto` semantics: exact (bitwise the batch
    mappers) up to `bin_construct_sample_cnt` rows, eps-approximate
    beyond."""

    def __init__(self, num_features: int, eps: float,
                 categorical: Sequence[int] = (),
                 min_capacity_rows: int = 0):
        self.eps = float(eps)
        self.num_features = int(num_features)
        cap = max(sketch_capacity(eps), int(min_capacity_rows))
        self.capacity = cap
        cats = set(int(c) for c in categorical)
        self.categorical = sorted(cats)
        self.sketches = [CategoricalCounter(cap) if j in cats
                         else QuantileSketch(eps, cap)
                         for j in range(num_features)]
        self.n_rows = 0

    def add_chunk(self, X: np.ndarray) -> None:
        X = np.asarray(X)
        if X.ndim != 2 or X.shape[1] != self.num_features:
            raise ValueError(
                f"sketch chunk must be [rows, {self.num_features}], "
                f"got {X.shape}")
        self.n_rows += X.shape[0]
        for j in range(self.num_features):
            col = np.asarray(X[:, j], np.float64)
            self.sketches[j].add(col[col != 0.0])

    def merge(self, other: "SketchSet") -> None:
        if other.num_features != self.num_features:
            raise ValueError("cannot merge sketch sets of different width")
        self.n_rows += other.n_rows
        for a, b in zip(self.sketches, other.sketches):
            a.merge(b)

    @property
    def exact(self) -> bool:
        return all(getattr(s, "exact", True) for s in self.sketches)

    def err_bound(self) -> float:
        """Max rank uncertainty across features (rows; a categorical
        counter's dropped mass counts as its uncertainty)."""
        return max((s.err_bound() for s in self.sketches), default=0.0)

    # -- mappers ---------------------------------------------------------

    def mappers(self, max_bin: int, min_data_in_bin: int,
                min_split_data: int, bin_budget: int = 0
                ) -> List[BinMapper]:
        """Derive the BinMappers — the exact find_bin greedy run on the
        (merged) summaries, zero injected from the row count exactly
        like binning._distinct_with_zero.  ``bin_budget > 0`` applies
        the adaptive per-feature allocation (binning.
        allocate_bin_budgets) with distinct/mass counts read off the
        summaries themselves — the sketch-side analog of the exact
        sample path's column stats."""
        budgets = None
        if bin_budget > 0 and self.sketches:
            from ..binning import allocate_bin_budgets
            total0 = int(self.n_rows)
            d = []
            m = []
            for sk in self.sketches:
                nz = int(np.rint(np.asarray(sk.counts)).sum())
                dd = int(np.asarray(sk.vals).size)
                if nz < total0:
                    dd += 1                       # the implied zero
                d.append(max(dd, 1))
                m.append(nz)
            budgets = allocate_bin_budgets(np.asarray(d, np.int64),
                                           np.asarray(m, np.int64),
                                           bin_budget)
        out = []
        total = int(self.n_rows)
        for j, sk in enumerate(self.sketches):
            bt = CATEGORICAL if isinstance(sk, CategoricalCounter) \
                else NUMERICAL
            vals = sk.vals
            # counts are exact integers carried in f64 (< 2^53)
            counts = np.rint(sk.counts).astype(np.int64)
            nonzero = int(counts.sum())
            zero_cnt = max(total - nonzero - int(round(
                getattr(sk, "dropped", 0.0))), 0)
            if vals.size == 0:
                vals = np.array([0.0])
                counts = np.array([max(zero_cnt, 1)], np.int64)
            elif zero_cnt > 0:
                z = np.flatnonzero(vals == 0.0)
                if z.size:
                    counts = counts.copy()
                    counts[z[0]] += zero_cnt
                else:
                    pos = int(np.searchsorted(vals, 0.0))
                    vals = np.insert(vals, pos, 0.0)
                    counts = np.insert(counts, pos, zero_cnt)
            mb = int(budgets[j]) if budgets is not None else max_bin
            out.append(find_bin_from_distinct(
                vals, counts, total, mb, min_data_in_bin,
                min_split_data, bt))
        return out

    def mappers_from_config(self, cfg) -> List[BinMapper]:
        return self.mappers(cfg.max_bin, cfg.min_data_in_bin,
                            cfg.min_data_in_leaf,
                            bin_budget=int(getattr(cfg, "bin_budget", 0)))

    # -- wire format -----------------------------------------------------

    def pack(self) -> np.ndarray:
        """[F + 1, 2 * capacity + 4] float64: row 0 is the header
        (n_rows, capacity, eps, n_features), rows 1..F the per-feature
        summaries.  Fixed width across ranks, so allgather_f64 carries
        it bit-exactly in one collective."""
        w = 2 * self.capacity + QuantileSketch.WIDTH_SCALARS
        out = np.zeros((self.num_features + 1, w), np.float64)
        out[0, 0] = float(self.n_rows)
        out[0, 1] = float(self.capacity)
        out[0, 2] = self.eps
        out[0, 3] = float(self.num_features)
        for j, sk in enumerate(self.sketches):
            out[j + 1] = sk.pack()
        return out

    @classmethod
    def unpack(cls, arr: np.ndarray, categorical: Sequence[int] = ()
               ) -> "SketchSet":
        arr = np.asarray(arr, np.float64)
        n_rows = int(arr[0, 0])
        capacity = int(arr[0, 1])
        eps = float(arr[0, 2])
        F = int(arr[0, 3])
        ss = cls(F, eps, categorical=categorical, min_capacity_rows=capacity)
        ss.n_rows = n_rows
        cats = set(ss.categorical)
        ss.sketches = [
            CategoricalCounter.unpack(arr[j + 1], capacity) if j in cats
            else QuantileSketch.unpack(arr[j + 1], eps, capacity)
            for j in range(F)]
        return ss

    @classmethod
    def merge_packed(cls, stack: np.ndarray, categorical: Sequence[int] = ()
                     ) -> "SketchSet":
        """Merge a [world, F + 1, W] stack of packed sketch sets in rank
        order — deterministic, so every process that holds the identical
        stack derives the identical merged summary (and mappers)."""
        merged = cls.unpack(stack[0], categorical)
        for r in range(1, stack.shape[0]):
            merged.merge(cls.unpack(stack[r], categorical))
        return merged


def sketch_columns(X: np.ndarray, cfg, categorical: Sequence[int] = (),
                   min_capacity_rows: int = 0) -> SketchSet:
    """SketchSet over an in-memory sample, chunked by
    `cfg.stream_chunk_rows` (the same chunk walk the out-of-core path
    takes, so both produce identical summaries)."""
    X = np.asarray(X)
    ss = SketchSet(X.shape[1], cfg.sketch_eps, categorical=categorical,
                   min_capacity_rows=min_capacity_rows)
    step = max(int(cfg.stream_chunk_rows), 1)
    for r0 in range(0, X.shape[0], step):
        ss.add_chunk(X[r0:r0 + step])
    return ss
