"""graftlint — AST static analysis for JAX hot-path hazards.

Four PRs of hot-path work made performance depend on invariants the
Python type system cannot see: jitted tree builders must not retrace
across boosting iterations, no tracer may leak to host mid-loop, and
every per-iteration implicit device→host transfer is a pipeline stall
(the dominant scaling tax of the GPU boosting literature,
arXiv:1706.08359 §5 / arXiv:1806.11248 §4).  This pass codifies those
invariants the way scripts/check_config_coverage.py codifies config
liveness: violations fail in CI, not in the next on-chip bench window.

Rules
-----
- ``host-sync``: device→host synchronization hazards.  ``.item()``
  anywhere; ``float()``/``int()``/``bool()`` or ``np.asarray``/
  ``np.array`` applied to a device value; implicit ``__bool__``
  (``if tracer:`` / ``while tracer:`` / ``assert tracer``) inside
  functions reachable from jit.  Device values are found by a local
  dataflow: names assigned from ``jnp.*``/``lax.*``/``jax.*`` calls or
  from calls into known-jitted package functions (``jax.device_get``
  results are host values and exempt — it is the sanctioned, batchable
  fetch).
- ``retrace-hazard``: per-iteration recompile/upload hazards.  Call
  sites of known-jitted functions passing a ``Config``-derived
  attribute (``cfg.x`` / ``config.x`` / ``self.config.x``) to a
  parameter not in ``static_argnames`` (config scalars are fixed per
  run: bake them static or close over them with ``functools.partial``
  so a changed config is an intentional retrace, not a silent per-call
  upload); ``print``/``log.*`` calls and f-strings formatting device
  values inside traced bodies (trace-time host effects).
- ``dtype-drift``: float64 leaking into traced code with x64 disabled.
  ``np.float64``/``jnp.float64`` casts, ``dtype="float64"``,
  ``astype(float64)``, and float literals outside float32 range (they
  silently become ``0``/``inf`` when the tracer downcasts).
- ``nondeterminism``: ``time.*`` clocks and ``random``/``np.random``
  draws inside traced bodies — they execute at trace time, bake one
  arbitrary value into the compiled program, and make retraces
  unreproducible.

Traced-region discovery: jit roots are ``@jax.jit`` /
``functools.partial(jax.jit, static_argnames=...)`` decorators,
``jax.jit(f)`` / ``jax.jit(functools.partial(f, ...))`` /
``jax.jit(compat_shard_map(f, ...))`` call sites, and bodies handed to
``lax.{fori_loop,while_loop,scan,cond,switch}`` / ``jax.vmap`` /
``shard_map`` (lax control flow traces its body even outside jit).
Reachability then propagates through same-package calls (local names,
``self.method``, and ``from ..x import y`` imports).

Suppressions
------------
Inline, on the finding line or the line above, with a REQUIRED reason::

    x = float(total)  # graftlint: allow(host-sync) — chosen sync point

or a reviewed allowlist entry in scripts/lint_allowlist.txt
(``path::rule::qualname — reason``), mirroring the config-coverage
allowlist: adding one is a conscious review decision.

Run: ``python scripts/run_lint.py`` (nonzero exit on findings); tier-1
runs it from tests/test_lint_clean.py.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULES = ("host-sync", "retrace-hazard", "dtype-drift", "nondeterminism")

# float32 finite range; literals outside it (except 0) drift under jit
_F32_MAX = 3.4028235e38
_F32_TINY = 1.1754944e-38

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*allow\(\s*([a-z-]+(?:\s*,\s*[a-z-]+)*)\s*\)"
    r"\s*(?:[-—–:]+\s*)?(.*)")

_DEVICE_MODULES = {"jnp", "lax"}          # jnp.x(...) / lax.x(...)
_DEVICE_JAX_SUBMODULES = {"lax", "nn", "numpy", "random", "scipy"}
# fetch APIs whose results are HOST values (the sanctioned sync points)
_HOST_FETCHES = {("jax", "device_get")}
_TRACE_WRAPPER_FN_ARGS = {
    # callee suffix -> 0-based positions of traced-function arguments
    "fori_loop": (2,),
    "while_loop": (0, 1),
    "scan": (0,),
    "cond": (1, 2),
    "switch": (1,),
    "vmap": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "shard_map": (0,),
    "compat_shard_map": (0,),
}


@dataclass
class Finding:
    path: str            # repo-relative
    line: int
    rule: str
    message: str
    qualname: str        # enclosing function ('<module>' at top level)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule}: {self.message} "
                f"[in {self.qualname}]")


@dataclass(eq=False)          # identity hash: one node, one entry
class FuncInfo:
    module: str
    qualname: str
    node: ast.AST                      # FunctionDef / AsyncFunctionDef
    params: Tuple[str, ...]
    statics: Set[str] = field(default_factory=set)
    tracer_params: Set[str] = field(default_factory=set)
    traced: bool = False
    is_jit_root: bool = False          # has its own jit cache + statics


@dataclass
class ModuleInfo:
    name: str
    path: str                          # repo-relative
    tree: ast.Module
    lines: List[str]
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)
    # local name -> (module, name) for from-imports; name -> module for
    # module imports/aliases
    imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    mod_aliases: Dict[str, str] = field(default_factory=dict)
    # attribute names assigned from device expressions anywhere in the
    # module (`self.score = jnp.asarray(...)`) — lets the dataflow see
    # `float(self.score)` through object state, not just local names —
    # and attrs assigned HOST values (`self.label = np.asarray(...)`):
    # a name that appears in both is ambiguous across classes and is
    # excluded from the package-wide registry
    device_attrs: Set[str] = field(default_factory=set)
    host_attrs: Set[str] = field(default_factory=set)


def _devicey_chain(chain: Optional[Tuple[str, ...]]) -> bool:
    """True when a call through this attribute chain returns a device
    value (jnp.*/lax.*/jax.* constructors and transforms); False for the
    host-returning introspection and fetch APIs."""
    if not chain:
        return False
    if chain[:2] == ("jax", "device_get"):
        return False                           # the sanctioned fetch
    if chain[0] in _DEVICE_MODULES:
        return chain[-1] not in ("dtype", "result_type", "issubdtype",
                                 "ndim", "shape", "size")
    if chain[0] == "jax" and (len(chain) == 2
                              or chain[1] in _DEVICE_JAX_SUBMODULES):
        return chain[-1] not in ("device_get", "process_count",
                                 "process_index", "devices",
                                 "local_devices", "default_backend")
    return False


def _attr_chain(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """('jax','lax','fori_loop') for jax.lax.fori_loop; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _static_argnames_from_call(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            out: Set[str] = set()
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        out.add(e.value)
            return out
    return set()


def _is_jit_expr(node: ast.AST) -> Optional[Set[str]]:
    """Static-argname set when `node` evaluates to a jit transform
    (jax.jit / jit / functools.partial(jax.jit, ...)), else None."""
    chain = _attr_chain(node)
    if chain and chain[-1] == "jit" and (len(chain) == 1 or chain[0] == "jax"):
        return set()
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        if chain and chain[-1] == "partial":
            if node.args and _is_jit_expr(node.args[0]) is not None:
                return _static_argnames_from_call(node)
        if chain and chain[-1] == "jit" and (len(chain) == 1
                                             or chain[0] == "jax"):
            return _static_argnames_from_call(node)
    return None


def _module_name_for(path: str, root: str) -> str:
    rel = os.path.relpath(path, root)
    mod = rel[:-3].replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _resolve_relative(module: str, node: ast.ImportFrom) -> Optional[str]:
    if node.level == 0:
        return node.module
    parts = module.split(".")
    if node.level > len(parts):
        return None
    base = parts[: len(parts) - node.level]
    if node.module:
        base = base + node.module.split(".")
    return ".".join(base) if base else None


class _ModuleIndexer(ast.NodeVisitor):
    """Pass 1: function defs, imports, direct jit roots, local aliases."""

    def __init__(self, mi: ModuleInfo):
        self.mi = mi
        self.stack: List[str] = []
        # function-local aliases: name -> (funcname, partial_statics|None)
        self.aliases: Dict[str, Tuple[str, Optional[Set[str]]]] = {}

    # -- imports --------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.mi.mod_aliases[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        src = _resolve_relative(self.mi.name, node)
        if src is None:
            return
        for a in node.names:
            if a.name == "*":
                continue
            local = a.asname or a.name
            self.mi.imports[local] = (src, a.name)

    # -- functions ------------------------------------------------------
    def _visit_func(self, node) -> None:
        qual = ".".join(self.stack + [node.name])
        params = tuple(
            a.arg for a in (node.args.posonlyargs + node.args.args
                            + node.args.kwonlyargs))
        fi = FuncInfo(self.mi.name, qual, node, params)
        for dec in node.decorator_list:
            statics = _is_jit_expr(dec)
            if statics is not None:
                fi.traced = fi.is_jit_root = True
                fi.statics = statics
                fi.tracer_params = set(params) - statics
        self.mi.funcs[qual] = fi
        # bare-name index for intra-module resolution (last def wins;
        # nested helpers are usually unique per module in this codebase)
        self.mi.funcs.setdefault(node.name, fi)
        if self.mi.funcs[node.name].qualname != qual and "." not in qual:
            self.mi.funcs[node.name] = fi     # top level shadows nested
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        # track `f = some_func` / `f = functools.partial(some_func, ...)`
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            ref = _callable_ref(node.value)
            if ref is not None:
                self.aliases[tgt] = ref
        # track `self.x = jnp.asarray(...)`-style device-attribute state
        # vs `self.x = np.asarray(...)`-style host state
        if isinstance(node.value, ast.Call):
            chain = _attr_chain(node.value.func)
            if _devicey_chain(chain):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        self.mi.device_attrs.add(t.attr)
            elif chain and (chain[0] in ("np", "numpy")
                            or chain[:2] == ("jax", "device_get")):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        self.mi.host_attrs.add(t.attr)
        self.generic_visit(node)


def _callable_ref(expr: ast.AST) -> Optional[Tuple[str, Optional[Set[str]]]]:
    """(function-name, bound-statics) when `expr` is a bare function
    reference or functools.partial(fn, ...).  bound-statics is a set of
    parameter names bound by the partial (empty for a bare reference) or
    None when the bindings cannot be determined (a ``**kw`` splat) — in
    that case callers must NOT assume the remaining parameters are
    tracers."""
    if isinstance(expr, ast.Name):
        return (expr.id, set())
    if isinstance(expr, ast.Call):
        chain = _attr_chain(expr.func)
        if chain and chain[-1] == "partial" and expr.args:
            inner = expr.args[0]
            if isinstance(inner, ast.Name):
                bound: Optional[Set[str]] = set()
                for kw in expr.keywords:
                    if kw.arg is None:       # **kw splat: bindings unknown
                        bound = None
                        break
                    bound.add(kw.arg)
                return (inner.id, bound)
    return None


class Package:
    """Parsed package + traced-region call graph."""

    def __init__(self, root: str):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}
        self._alias_maps: Dict[str, Dict[str, Tuple[str, Optional[Set[str]]]]] = {}

    # -- loading --------------------------------------------------------
    def add_file(self, path: str) -> None:
        with open(path) as fh:
            src = fh.read()
        mod = _module_name_for(path, self.root)
        mi = ModuleInfo(mod, os.path.relpath(path, self.root),
                        ast.parse(src, filename=path), src.splitlines())
        ix = _ModuleIndexer(mi)
        ix.visit(mi.tree)
        self.modules[mod] = mi
        self._alias_maps[mod] = ix.aliases

    def add_tree(self, pkg_dir: str) -> None:
        for dirpath, _dirs, files in os.walk(pkg_dir):
            for f in sorted(files):
                if f.endswith(".py"):
                    self.add_file(os.path.join(dirpath, f))

    def device_attrs(self) -> Set[str]:
        """Package-wide attribute names assigned ONLY from device
        expressions: an attr any class also assigns a host value
        ('label': device in objectives, numpy in metrics) is ambiguous
        across objects and excluded (built once after loading)."""
        if not hasattr(self, "_device_attrs"):
            dev: Set[str] = set()
            host: Set[str] = set()
            for mi in self.modules.values():
                dev |= mi.device_attrs
                host |= mi.host_attrs
            self._device_attrs = dev - host
        return self._device_attrs

    # -- resolution -----------------------------------------------------
    def resolve(self, module: str, name: str) -> Optional[FuncInfo]:
        mi = self.modules.get(module)
        if mi is None:
            return None
        if name in mi.funcs:
            return mi.funcs[name]
        if name in mi.imports:
            src_mod, src_name = mi.imports[name]
            if src_mod != module:
                return self.resolve(src_mod, src_name)
        alias = self._alias_maps.get(module, {}).get(name)
        if alias is not None:
            return self.resolve(module, alias[0])
        return None

    def resolve_callee(self, mi: ModuleInfo, qual: str,
                       func: ast.AST) -> Optional[FuncInfo]:
        """Resolve a Call callee to a package FuncInfo: bare name,
        self.method, or imported-module attribute."""
        if isinstance(func, ast.Name):
            return self.resolve(mi.name, func.id)
        chain = _attr_chain(func)
        if not chain or len(chain) != 2:
            return None
        base, attr = chain
        if base == "self":
            # method in the same class: replace the last qualname part
            parts = qual.split(".")
            for cut in range(len(parts) - 1, 0, -1):
                cand = ".".join(parts[:cut] + [attr])
                if cand in mi.funcs:
                    return mi.funcs[cand]
            return None
        if base in mi.mod_aliases:
            return self.resolve(mi.mod_aliases[base], attr)
        if base in mi.imports:          # `from .ops import eval as deval`
            src_mod, src_name = mi.imports[base]
            return self.resolve(f"{src_mod}.{src_name}", attr)
        return None

    # -- traced-region discovery ---------------------------------------
    def mark_traced(self) -> None:
        work: List[FuncInfo] = [fi for mi in self.modules.values()
                                for fi in set(mi.funcs.values()) if fi.traced]

        def mark(fi: Optional[FuncInfo], tracer_params: bool = False,
                 statics: Optional[Set[str]] = None) -> None:
            if fi is None:
                return
            new_statics = statics or set()
            if not fi.traced:
                fi.traced = True
                if tracer_params:
                    fi.tracer_params = set(fi.params) - new_statics
                fi.statics |= new_statics
                work.append(fi)
            elif tracer_params and not fi.tracer_params and not fi.is_jit_root:
                fi.tracer_params = set(fi.params) - new_statics
                fi.statics |= new_statics

        # seed: jit()/shard_map()/lax-control-flow call sites anywhere.
        # A partial() with a **splat hides which parameters are bound
        # (extra is None): the body is traced but parameters must not be
        # assumed tracers, or every static config branch would flag.
        for mi in self.modules.values():
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                statics = _is_jit_expr(node.func)
                if statics is not None and node.args:
                    for fn, extra in self._fn_refs(mi, node.args[0]):
                        mark(fn, tracer_params=extra is not None,
                             statics=statics | (extra or set()))
                    continue
                chain = _attr_chain(node.func)
                if chain and chain[-1] in _TRACE_WRAPPER_FN_ARGS:
                    for pos in _TRACE_WRAPPER_FN_ARGS[chain[-1]]:
                        if pos < len(node.args):
                            for fn, extra in self._fn_refs(mi,
                                                           node.args[pos]):
                                mark(fn, tracer_params=extra is not None,
                                     statics=extra or set())

        # propagate through same-package calls from traced bodies
        seen: Set[Tuple[str, str]] = set()
        while work:
            fi = work.pop()
            key = (fi.module, fi.qualname)
            if key in seen:
                continue
            seen.add(key)
            mi = self.modules[fi.module]
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    target = self.resolve_callee(mi, fi.qualname, node.func)
                    if target is not None and not target.traced:
                        mark(target)
                    # functools.partial(fn, ...) inside traced bodies:
                    # fn will be called traced (lax.cond branch tables)
                    ref = _callable_ref(node)
                    if ref is not None and isinstance(node, ast.Call) \
                            and ref[0] != getattr(node.func, "id", None):
                        mark(self.resolve(mi.name, ref[0]),
                             tracer_params=False)

    def _fn_refs(self, mi: ModuleInfo, expr: ast.AST
                 ) -> Iterable[Tuple[Optional[FuncInfo], Optional[Set[str]]]]:
        """FuncInfos referenced by a jit/shard_map/lax-wrapper argument:
        a name, functools.partial(name, ...), a [list] of names (switch),
        or a nested shard_map/partial call."""
        if isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                yield from self._fn_refs(mi, e)
            return
        ref = _callable_ref(expr)
        if ref is not None:
            name, bound = ref
            # chase local `fn = functools.partial(f, **kw)` aliases,
            # merging binding knowledge: an unknown (**splat) binding
            # anywhere in the chain means parameters must not be
            # assumed tracers
            amap = self._alias_maps.get(mi.name, {})
            hops: Set[str] = set()
            while name in amap and name not in hops:
                hops.add(name)
                aname, abound = amap[name]
                bound = (None if bound is None or abound is None
                         else bound | abound)
                name = aname
            yield self.resolve(mi.name, name), bound
            return
        if isinstance(expr, ast.Call):     # jit(compat_shard_map(fn, ...))
            chain = _attr_chain(expr.func)
            if chain and chain[-1] in _TRACE_WRAPPER_FN_ARGS and expr.args:
                yield from self._fn_refs(mi, expr.args[0])


# ---------------------------------------------------------------------------
# rule checks
# ---------------------------------------------------------------------------


class _Dataflow:
    """Per-function device-value tracking (names only, straight-line
    approximation: later assignments overwrite earlier ones)."""

    def __init__(self, pkg: Package, mi: ModuleInfo, fi: FuncInfo):
        self.pkg = pkg
        self.mi = mi
        self.fi = fi
        self.devicey_names: Set[str] = set(fi.tracer_params)

    def is_devicey(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.devicey_names
        if isinstance(expr, ast.Call):
            chain = _attr_chain(expr.func)
            if chain:
                if chain[:2] == ("jax", "device_get"):
                    return False                       # sanctioned fetch
                if _devicey_chain(chain):
                    return True
                if chain[0] in _DEVICE_MODULES or chain[0] == "jax":
                    return False                       # host-returning API
            # only jit ROOTS reliably return device arrays; a merely
            # reachable-from-jit helper called with host args at trace
            # time returns host values (gather_scratch_capacity etc.)
            target = self.pkg.resolve_callee(self.mi, self.fi.qualname,
                                             expr.func)
            if target is not None and target.is_jit_root:
                return True
            # method call on a devicey value: x.sum(), x.reshape(...)
            if isinstance(expr.func, ast.Attribute):
                return self.is_devicey(expr.func.value)
            return False
        if isinstance(expr, ast.BinOp):
            return self.is_devicey(expr.left) or self.is_devicey(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.is_devicey(expr.operand)
        if isinstance(expr, ast.Compare):
            # identity/containment tests (`x is None`) never call the
            # tracer's __bool__ and return a host bool — not a hazard
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in expr.ops):
                return False
            return self.is_devicey(expr.left) or any(
                self.is_devicey(c) for c in expr.comparators)
        if isinstance(expr, ast.BoolOp):
            return any(self.is_devicey(v) for v in expr.values)
        if isinstance(expr, ast.Subscript):
            return self.is_devicey(expr.value)
        if isinstance(expr, ast.Attribute):
            if expr.attr in ("shape", "ndim", "dtype", "size", "itemsize",
                             "nbytes", "at"):
                return expr.attr == "at" and self.is_devicey(expr.value)
            # object state: an attribute assigned from a device
            # expression (self.score = jnp.asarray(...)) is a device
            # value wherever it is read — float(self.score) is the same
            # stall as float(score).  Scoping controls collisions: a
            # direct `self.x` read matches only attrs registered in the
            # SAME module (objectives' device self.label must not taint
            # metrics' host self.label); a multi-hop read through
            # another object (`self.train_score.score`) is cross-class
            # by construction and consults the package-wide registry.
            b, levels = expr.value, 1
            while isinstance(b, ast.Attribute):
                b, levels = b.value, levels + 1
            if isinstance(b, ast.Name) and b.id == "self":
                if levels == 1 and expr.attr in (self.mi.device_attrs
                                                 - self.mi.host_attrs):
                    return True
                if levels >= 2 and expr.attr in self.pkg.device_attrs():
                    return True
            return self.is_devicey(expr.value)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.is_devicey(e) for e in expr.elts)
        if isinstance(expr, ast.IfExp):
            return self.is_devicey(expr.body) or self.is_devicey(expr.orelse)
        return False

    def note_assign(self, node: ast.AST) -> None:
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            return
        dev = self.is_devicey(value)
        for t in targets:
            if isinstance(t, ast.Name):
                if dev:
                    self.devicey_names.add(t.id)
                else:
                    self.devicey_names.discard(t.id)


def _has_float64(expr: ast.AST) -> Optional[ast.AST]:
    for n in ast.walk(expr):
        chain = _attr_chain(n)
        if chain and chain[-1] in ("float64", "double") and chain[0] in (
                "np", "numpy", "jnp"):
            return n
        if isinstance(n, ast.Constant) and n.value in ("float64", "double"):
            return n
    return None


def _config_attr(expr: ast.AST) -> Optional[str]:
    """Name of a Config field read inside `expr` (cfg.x / config.x /
    self.config.x / anything.config.x), or None."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute):
            base = n.value
            if isinstance(base, ast.Name) and base.id in ("cfg", "config"):
                return n.attr
            if isinstance(base, ast.Attribute) and base.attr == "config":
                return n.attr
    return None


class _Checker(ast.NodeVisitor):
    """Rule checks over one function body (or module top level)."""

    def __init__(self, pkg: Package, mi: ModuleInfo, fi: Optional[FuncInfo],
                 findings: List[Finding]):
        self.pkg = pkg
        self.mi = mi
        self.fi = fi
        self.traced = fi is not None and fi.traced
        self.qual = fi.qualname if fi is not None else "<module>"
        self.flow = _Dataflow(pkg, mi, fi) if fi is not None else None
        self.findings = findings

    # -- helpers --------------------------------------------------------
    def _emit(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(Finding(self.mi.path, node.lineno, rule, msg,
                                     self.qual))

    def _devicey(self, expr: ast.AST) -> bool:
        return self.flow is not None and self.flow.is_devicey(expr)

    # -- assignments feed the dataflow ---------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        if self.flow is not None:
            self.flow.note_assign(node)

    visit_AugAssign = visit_Assign
    visit_AnnAssign = visit_Assign

    # -- host-sync ------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        chain = _attr_chain(func)
        # .item(): a one-element device→host sync wherever it appears
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not node.args:
            self._emit(node, "host-sync",
                       ".item() forces a blocking device→host sync; "
                       "batch scalar fetches with jax.device_get at the "
                       "loop boundary")
        # float()/int()/bool() of a device value
        if isinstance(func, ast.Name) and func.id in ("float", "int", "bool") \
                and len(node.args) == 1 and self._devicey(node.args[0]):
            self._emit(node, "host-sync",
                       f"{func.id}() on a device value blocks on a "
                       "device→host transfer; keep it on device or fetch "
                       "explicitly (batched) with jax.device_get")
        # np.asarray / np.array of a device value
        if chain and chain[0] in ("np", "numpy", "onp") \
                and chain[-1] in ("asarray", "array", "ascontiguousarray") \
                and node.args and self._devicey(node.args[0]):
            self._emit(node, "host-sync",
                       f"{'.'.join(chain)} of a device value is an "
                       "implicit device→host transfer; use jax.device_get "
                       "(explicit, transfer-guard-clean, batchable)")
        if self.traced:
            self._check_traced_call(node, chain)
        self._check_config_static(node)
        self.generic_visit(node)

    def _check_traced_call(self, node: ast.Call,
                           chain: Optional[Tuple[str, ...]]) -> None:
        func = node.func
        # print / logging inside traced code
        if isinstance(func, ast.Name) and func.id == "print":
            self._emit(node, "retrace-hazard",
                       "print() inside traced code runs at trace time "
                       "only (or forces a callback); use "
                       "jax.debug.print or hoist out of the jit region")
        if chain and len(chain) >= 2 and chain[0] in ("log", "logging",
                                                      "logger", "Log"):
            self._emit(node, "retrace-hazard",
                       f"{'.'.join(chain)}() inside traced code is a "
                       "trace-time host effect; hoist logging out of the "
                       "jit region")
        # nondeterminism
        if chain:
            if chain[0] == "time" and chain[-1] in (
                    "time", "perf_counter", "monotonic", "time_ns",
                    "process_time"):
                self._emit(node, "nondeterminism",
                           f"{'.'.join(chain)}() in traced code executes "
                           "once at trace time and bakes a stale constant "
                           "into the compiled program")
            if chain[0] == "random" or chain[:2] in (("np", "random"),
                                                     ("numpy", "random")):
                self._emit(node, "nondeterminism",
                           f"{'.'.join(chain)}() in traced code draws at "
                           "trace time (one arbitrary constant per "
                           "compile); thread a jax.random key instead")
        # dtype-drift: astype(float64)
        if isinstance(func, ast.Attribute) and func.attr == "astype" \
                and node.args and _has_float64(node.args[0]) is not None:
            self._emit(node, "dtype-drift",
                       "astype(float64) inside traced code silently "
                       "downcasts to f32 with x64 disabled; pin the "
                       "intended dtype explicitly")

    def _check_config_static(self, node: ast.Call) -> None:
        """Config-derived Python value passed to a jitted function's
        traced (non-static) parameter."""
        if self.fi is None:
            target = None
        else:
            target = self.pkg.resolve_callee(self.mi, self.qual, node.func)
        if target is None or not target.is_jit_root:
            return
        params = list(target.params)
        for i, arg in enumerate(node.args):
            fieldname = _config_attr(arg)
            if fieldname is None:
                continue
            pname = params[i] if i < len(params) else f"arg{i}"
            if pname not in target.statics:
                self._emit(
                    arg, "retrace-hazard",
                    f"Config field '{fieldname}' flows into jitted "
                    f"'{target.qualname}' parameter '{pname}' which is "
                    "not in static_argnames: a per-call scalar upload, "
                    "and a silent retrace hazard if it reaches shape or "
                    "branch logic; declare it static or bind it with "
                    "functools.partial")
        for kw in node.keywords:
            if kw.arg is None:
                continue
            fieldname = _config_attr(kw.value)
            if fieldname is not None and kw.arg not in target.statics:
                self._emit(
                    kw.value, "retrace-hazard",
                    f"Config field '{fieldname}' flows into jitted "
                    f"'{target.qualname}' parameter '{kw.arg}' which is "
                    "not in static_argnames; declare it static or bind "
                    "it with functools.partial")

    # -- implicit __bool__ on tracers ----------------------------------
    def _check_test(self, test: ast.AST, kind: str) -> None:
        if self.traced and self._devicey(test):
            self._emit(test, "host-sync",
                       f"`{kind}` on a traced value calls __bool__ on a "
                       "tracer (TracerBoolConversionError under jit, a "
                       "blocking sync when eager); use lax.cond/jnp.where")

    def visit_If(self, node: ast.If) -> None:
        self._check_test(node.test, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_test(node.test, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_test(node.test, "ternary if")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_test(node.test, "assert")
        self.generic_visit(node)

    # -- f-strings formatting device values -----------------------------
    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if self.traced:
            for v in node.values:
                if isinstance(v, ast.FormattedValue) and self._devicey(v.value):
                    self._emit(node, "retrace-hazard",
                               "f-string formats a traced value: renders "
                               "the tracer repr at trace time (and forces "
                               "a sync when eager); use jax.debug.print")
                    break
        self.generic_visit(node)

    # -- dtype drift on literals / dtype kwargs -------------------------
    def visit_Constant(self, node: ast.Constant) -> None:
        if self.traced and isinstance(node.value, float) and node.value != 0.0:
            a = abs(node.value)
            if a > _F32_MAX or a < _F32_TINY:
                self._emit(node, "dtype-drift",
                           f"float literal {node.value!r} is outside "
                           "float32 range and becomes 0/inf when the "
                           "tracer downcasts with x64 disabled")

    def visit_keyword(self, node: ast.keyword) -> None:
        if self.traced and node.arg == "dtype" \
                and _has_float64(node.value) is not None:
            self._emit(node.value, "dtype-drift",
                       "dtype=float64 inside traced code is quietly f32 "
                       "with x64 disabled; pin float32 (or int32) "
                       "explicitly")
        self.generic_visit(node)

    # keep nested defs inside their own _Checker run
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if self.fi is not None and node is not self.fi.node:
            return                      # separate FuncInfo covers it
        for d in node.decorator_list:
            self.visit(d)
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)


# np.float64(...) calls in traced code (checker-level, needs chain only)
def _np_float64_calls(fi: FuncInfo, mi: ModuleInfo,
                      findings: List[Finding]) -> None:
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[-1] in ("float64", "double") \
                    and chain[0] in ("np", "numpy", "jnp"):
                findings.append(Finding(
                    mi.path, node.lineno, "dtype-drift",
                    "np.float64 cast inside traced code silently becomes "
                    "f32 with x64 disabled; pin float32 or hoist to host",
                    fi.qualname))


# ---------------------------------------------------------------------------
# suppression handling
# ---------------------------------------------------------------------------


def _suppressions_for(lines: Sequence[str], lineno: int
                      ) -> Optional[Tuple[Set[str], str]]:
    """(rules, reason) from a graftlint comment on `lineno` or the line
    above (1-indexed); None when no suppression applies."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _SUPPRESS_RE.search(lines[ln - 1])
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                return rules, m.group(2).strip()
    return None


def load_allowlist(path: str) -> Dict[Tuple[str, str, str], str]:
    """path::rule::qualname -> reason entries from the reviewed file."""
    out: Dict[Tuple[str, str, str], str] = {}
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, _, reason = line.partition("—")
            if not reason:
                body, _, reason = line.partition(" - ")
            parts = [p.strip() for p in body.strip().split("::")]
            if len(parts) == 3:
                out[(parts[0], parts[1], parts[2])] = reason.strip()
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def lint_paths(paths: Sequence[str], root: str,
               allowlist: Optional[Dict[Tuple[str, str, str], str]] = None
               ) -> List[Finding]:
    """Run every rule over `paths` (files or directories).  Returns
    unsuppressed findings; suppressions without a reason are findings
    themselves (`suppression` rule)."""
    pkg = Package(root)
    for p in paths:
        if os.path.isdir(p):
            pkg.add_tree(p)
        else:
            pkg.add_file(p)
    pkg.mark_traced()
    allowlist = allowlist or {}

    raw: List[Finding] = []
    for mi in pkg.modules.values():
        funcs = {id(fi.node): fi for fi in mi.funcs.values()}
        for fi in set(funcs.values()):
            _Checker(pkg, mi, fi, raw).visit(fi.node)
            if fi.traced:
                _np_float64_calls(fi, mi, raw)
        # module top level (rare, but .item() at import time counts)
        top = _Checker(pkg, mi, None, raw)
        for stmt in mi.tree.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                top.visit(stmt)

    # dedupe (nested defs can be visited from two scopes)
    seen: Set[Tuple[str, int, str, str]] = set()
    findings: List[Finding] = []
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        key = (f.path, f.line, f.rule, f.message)
        if key in seen:
            continue
        seen.add(key)
        mi = next(m for m in pkg.modules.values() if m.path == f.path)
        sup = _suppressions_for(mi.lines, f.line)
        if sup is not None and f.rule in sup[0]:
            if not sup[1]:
                findings.append(Finding(
                    f.path, f.line, "suppression",
                    f"graftlint: allow({f.rule}) has no reason; "
                    "suppressions must say why (\"# graftlint: "
                    "allow(rule) — reason\")", f.qualname))
            continue
        wl = allowlist.get((f.path, f.rule, f.qualname))
        if wl is not None:
            if wl:
                continue
            findings.append(Finding(
                f.path, f.line, "suppression",
                "allowlist entry has no reason", f.qualname))
            continue
        findings.append(f)
    return findings
