"""graftlint — AST static analysis for JAX hot-path hazards.

Four PRs of hot-path work made performance depend on invariants the
Python type system cannot see: jitted tree builders must not retrace
across boosting iterations, no tracer may leak to host mid-loop, and
every per-iteration implicit device→host transfer is a pipeline stall
(the dominant scaling tax of the GPU boosting literature,
arXiv:1706.08359 §5 / arXiv:1806.11248 §4).  This pass codifies those
invariants the way scripts/check_config_coverage.py codifies config
liveness: violations fail in CI, not in the next on-chip bench window.

Rules
-----
- ``host-sync``: device→host synchronization hazards.  ``.item()``
  anywhere; ``float()``/``int()``/``bool()`` or ``np.asarray``/
  ``np.array`` applied to a device value; implicit ``__bool__``
  (``if tracer:`` / ``while tracer:`` / ``assert tracer``) inside
  functions reachable from jit.  Device values are found by a local
  dataflow: names assigned from ``jnp.*``/``lax.*``/``jax.*`` calls or
  from calls into known-jitted package functions (``jax.device_get``
  results are host values and exempt — it is the sanctioned, batchable
  fetch).
- ``retrace-hazard``: per-iteration recompile/upload hazards.  Call
  sites of known-jitted functions passing a ``Config``-derived
  attribute (``cfg.x`` / ``config.x`` / ``self.config.x``) to a
  parameter not in ``static_argnames`` (config scalars are fixed per
  run: bake them static or close over them with ``functools.partial``
  so a changed config is an intentional retrace, not a silent per-call
  upload); ``print``/``log.*`` calls and f-strings formatting device
  values inside traced bodies (trace-time host effects).
- ``dtype-drift``: float64 leaking into traced code with x64 disabled.
  ``np.float64``/``jnp.float64`` casts, ``dtype="float64"``,
  ``astype(float64)``, and float literals outside float32 range (they
  silently become ``0``/``inf`` when the tracer downcasts).
- ``nondeterminism``: ``time.*`` clocks and ``random``/``np.random``
  draws inside traced bodies — they execute at trace time, bake one
  arbitrary value into the compiled program, and make retraces
  unreproducible.

shardlint rules (SPMD collective correctness)
---------------------------------------------
The data-parallel learners' correctness rests on collective invariants:
a mismatched ``axis_name`` is an unbound-axis trace error (or, worse, a
reduction over the wrong mesh axis), a collective skipped by one shard
is a pod-wide deadlock on real hardware, and a shard-local value
steering replicated control flow silently grows different trees per
device.  These rules lean on the same traced-region call graph:

- ``collective-mismatch``: a collective (``psum``/``psum_scatter``/
  ``all_gather``/``pmean``/``all_to_all``/…/``axis_index``) whose axis
  name is not an axis of any mesh constructed in the linted tree
  (string-literal axes at the call site, axis-parameter bindings like
  ``data_axis="rows"`` at any call site, and ``PartitionSpec``
  literals are all checked); and a literal-axis collective in traced
  code NOT reachable from any ``shard_map`` body — nothing binds the
  axis, so the trace fails (or the collective silently no-ops under a
  vmapped alias).
- ``divergent-collective``: a ``lax.cond``/``lax.switch`` in traced
  SPMD code where one branch performs a collective (directly or
  through the call graph) and another does not, unless the predicate
  is provably replicated (derived from ``psum``-family results or
  ``combine_sharded_records``); or any branch collective gated by a
  provably shard-local predicate.  Shards disagreeing on the predicate
  enter different branches and the collective deadlocks cross-host.
- ``scatter-divisibility``: a ``psum_scatter`` call whose enclosing
  function (or a lexically enclosing ancestor) carries no static
  divisibility guarantee for the scattered axis — an
  ``assert … % … == 0``, an ``if … % …: raise`` guard, pad-to-multiple
  arithmetic (``nd * ((x + nd - 1) // nd)``), or a call to the
  ``pad_cols_to_ndev`` helper (learner/common.py).  Without one, a
  non-tiling axis surfaces as a raw XLA shape error at trace time.
- ``replication-leak``: a provably shard-local value (derived from
  ``axis_index``/``psum_scatter``/``all_to_all``/``ppermute`` without
  an intervening replicating collective) flowing into a
  ``lax.cond``/``lax.switch`` predicate or a ``lax.fori_loop`` bound —
  control flow the growth loops require to be bitwise-replicated
  across shards (PRs 3-4).  The runtime half of this contract is
  ``diagnostics/sanitize.DivergenceSanitizer``.

Traced-region discovery: jit roots are ``@jax.jit`` /
``functools.partial(jax.jit, static_argnames=...)`` decorators,
``jax.jit(f)`` / ``jax.jit(functools.partial(f, ...))`` /
``jax.jit(compat_shard_map(f, ...))`` call sites, and bodies handed to
``lax.{fori_loop,while_loop,scan,cond,switch}`` / ``jax.vmap`` /
``shard_map`` (lax control flow traces its body even outside jit).
Reachability then propagates through same-package calls (local names,
``self.method``, and ``from ..x import y`` imports).

Suppressions
------------
Inline, on the finding line or the line above, with a REQUIRED reason::

    x = float(total)  # graftlint: allow(host-sync) — chosen sync point

or a reviewed allowlist entry in scripts/lint_allowlist.txt
(``path::rule::qualname — reason``), mirroring the config-coverage
allowlist: adding one is a conscious review decision.

Run: ``python scripts/run_lint.py`` (nonzero exit on findings); tier-1
runs it from tests/test_lint_clean.py.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULES = ("host-sync", "retrace-hazard", "dtype-drift", "nondeterminism",
         "collective-mismatch", "divergent-collective",
         "scatter-divisibility", "replication-leak")

# float32 finite range; literals outside it (except 0) drift under jit
_F32_MAX = 3.4028235e38
_F32_TINY = 1.1754944e-38

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*allow\(\s*([a-z-]+(?:\s*,\s*[a-z-]+)*)\s*\)"
    r"\s*(?:[-—–:]+\s*)?(.*)")

_DEVICE_MODULES = {"jnp", "lax"}          # jnp.x(...) / lax.x(...)
_DEVICE_JAX_SUBMODULES = {"lax", "nn", "numpy", "random", "scipy"}
# fetch APIs whose results are HOST values (the sanctioned sync points)
_HOST_FETCHES = {("jax", "device_get")}
# SPMD collectives (blocking cross-shard comms).  A shard that skips
# one while its peers enter it deadlocks the mesh on real hardware —
# the hazard class behind divergent-collective.
_COMM_COLLECTIVES = {"psum", "psum_scatter", "pmean", "pmax", "pmin",
                     "all_gather", "all_to_all", "ppermute", "pshuffle"}
# collectives whose RESULT is bitwise-replicated across the axis
# (clears the shard-local taint)…
_REPLICATED_RESULT = {"psum", "pmean", "pmax", "pmin", "all_gather"}
# …and primitives whose result is shard-VARYING by construction
# (sets the taint)
_SHARD_LOCAL_RESULT = {"psum_scatter", "all_to_all", "ppermute",
                       "pshuffle", "axis_index"}
# package helpers whose documented contract is a replicated result
# (ops/split.combine_sharded_records: all_gather + identical argmax on
# every shard) — the taint lattice treats them like psum
_REPLICATING_HELPERS = {"combine_sharded_records"}
# 0-based position of the axis-name argument
_COLLECTIVE_AXIS_POS = {"axis_index": 0}
# keyword names that carry mesh-axis bindings at call sites
# (functools.partial(build_tree, data_axis="data") and friends)
_AXIS_KWARG = re.compile(r"(^axis_name$)|(_axis$)")
# divisibility-guard helpers recognized by scatter-divisibility
# (learner/common.py: padding and the trace-time ValueError guard)
_PAD_HELPERS = {"pad_cols_to_ndev", "check_scatter_divisible"}

_TRACE_WRAPPER_FN_ARGS = {
    # callee suffix -> 0-based positions of traced-function arguments
    "fori_loop": (2,),
    "while_loop": (0, 1),
    "scan": (0,),
    "cond": (1, 2),
    "switch": (1,),
    "vmap": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "shard_map": (0,),
    "compat_shard_map": (0,),
}


@dataclass
class Finding:
    path: str            # repo-relative
    line: int
    rule: str
    message: str
    qualname: str        # enclosing function ('<module>' at top level)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule}: {self.message} "
                f"[in {self.qualname}]")


@dataclass(eq=False)          # identity hash: one node, one entry
class FuncInfo:
    module: str
    qualname: str
    node: ast.AST                      # FunctionDef / AsyncFunctionDef
    params: Tuple[str, ...]
    statics: Set[str] = field(default_factory=set)
    tracer_params: Set[str] = field(default_factory=set)
    traced: bool = False
    is_jit_root: bool = False          # has its own jit cache + statics
    smap: bool = False                 # reachable from a shard_map body


@dataclass
class ModuleInfo:
    name: str
    path: str                          # repo-relative
    tree: ast.Module
    lines: List[str]
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)
    # local name -> (module, name) for from-imports; name -> module for
    # module imports/aliases
    imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    mod_aliases: Dict[str, str] = field(default_factory=dict)
    # attribute names assigned from device expressions anywhere in the
    # module (`self.score = jnp.asarray(...)`) — lets the dataflow see
    # `float(self.score)` through object state, not just local names —
    # and attrs assigned HOST values (`self.label = np.asarray(...)`):
    # a name that appears in both is ambiguous across classes and is
    # excluded from the package-wide registry
    device_attrs: Set[str] = field(default_factory=set)
    host_attrs: Set[str] = field(default_factory=set)


def _devicey_chain(chain: Optional[Tuple[str, ...]]) -> bool:
    """True when a call through this attribute chain returns a device
    value (jnp.*/lax.*/jax.* constructors and transforms); False for the
    host-returning introspection and fetch APIs."""
    if not chain:
        return False
    if chain[:2] == ("jax", "device_get"):
        return False                           # the sanctioned fetch
    if chain[0] in _DEVICE_MODULES:
        return chain[-1] not in ("dtype", "result_type", "issubdtype",
                                 "ndim", "shape", "size")
    if chain[0] == "jax" and (len(chain) == 2
                              or chain[1] in _DEVICE_JAX_SUBMODULES):
        return chain[-1] not in ("device_get", "process_count",
                                 "process_index", "devices",
                                 "local_devices", "default_backend")
    return False


def _collective_name(node: ast.AST) -> Optional[str]:
    """'psum' / 'all_gather' / … / 'axis_index' when `node` is the
    callee of an SPMD collective (jax.lax.psum, lax.psum, or a bare
    from-import name); None otherwise."""
    chain = _attr_chain(node)
    if not chain:
        return None
    name = chain[-1]
    if name not in _COMM_COLLECTIVES and name != "axis_index":
        return None
    if len(chain) == 1 or chain[0] in ("jax", "lax"):
        return name
    return None


def _collective_axis_arg(call: ast.Call, name: str) -> Optional[ast.AST]:
    """The axis-name argument expression of a collective call."""
    pos = _COLLECTIVE_AXIS_POS.get(name, 1)
    if pos < len(call.args):
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    return None


def _str_constants(expr: ast.AST) -> Set[str]:
    """Every string literal inside `expr` (an axis argument may be a
    name, a tuple of names, or a conditional like
    `"data" if dd > 1 else None`)."""
    out: Set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.add(n.value)
    return out


def _attr_chain(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """('jax','lax','fori_loop') for jax.lax.fori_loop; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _static_argnames_from_call(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            out: Set[str] = set()
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        out.add(e.value)
            return out
    return set()


def _is_jit_expr(node: ast.AST) -> Optional[Set[str]]:
    """Static-argname set when `node` evaluates to a jit transform
    (jax.jit / jit / functools.partial(jax.jit, ...)), else None."""
    chain = _attr_chain(node)
    if chain and chain[-1] == "jit" and (len(chain) == 1 or chain[0] == "jax"):
        return set()
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        if chain and chain[-1] == "partial":
            if node.args and _is_jit_expr(node.args[0]) is not None:
                return _static_argnames_from_call(node)
        if chain and chain[-1] == "jit" and (len(chain) == 1
                                             or chain[0] == "jax"):
            return _static_argnames_from_call(node)
    return None


def _module_name_for(path: str, root: str) -> str:
    rel = os.path.relpath(path, root)
    mod = rel[:-3].replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _resolve_relative(module: str, node: ast.ImportFrom) -> Optional[str]:
    if node.level == 0:
        return node.module
    parts = module.split(".")
    if node.level > len(parts):
        return None
    base = parts[: len(parts) - node.level]
    if node.module:
        base = base + node.module.split(".")
    return ".".join(base) if base else None


class _ModuleIndexer(ast.NodeVisitor):
    """Pass 1: function defs, imports, direct jit roots, local aliases."""

    def __init__(self, mi: ModuleInfo):
        self.mi = mi
        self.stack: List[str] = []
        # function-local aliases: name -> (funcname, partial_statics|None)
        self.aliases: Dict[str, Tuple[str, Optional[Set[str]]]] = {}

    # -- imports --------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.mi.mod_aliases[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        src = _resolve_relative(self.mi.name, node)
        if src is None:
            return
        for a in node.names:
            if a.name == "*":
                continue
            local = a.asname or a.name
            self.mi.imports[local] = (src, a.name)

    # -- functions ------------------------------------------------------
    def _visit_func(self, node) -> None:
        qual = ".".join(self.stack + [node.name])
        params = tuple(
            a.arg for a in (node.args.posonlyargs + node.args.args
                            + node.args.kwonlyargs))
        fi = FuncInfo(self.mi.name, qual, node, params)
        for dec in node.decorator_list:
            statics = _is_jit_expr(dec)
            if statics is not None:
                fi.traced = fi.is_jit_root = True
                fi.statics = statics
                fi.tracer_params = set(params) - statics
        self.mi.funcs[qual] = fi
        # bare-name index for intra-module resolution (last def wins;
        # nested helpers are usually unique per module in this codebase)
        self.mi.funcs.setdefault(node.name, fi)
        if self.mi.funcs[node.name].qualname != qual and "." not in qual:
            self.mi.funcs[node.name] = fi     # top level shadows nested
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        # track `f = some_func` / `f = functools.partial(some_func, ...)`
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            ref = _callable_ref(node.value)
            if ref is not None:
                self.aliases[tgt] = ref
        # track `self.x = jnp.asarray(...)`-style device-attribute state
        # vs `self.x = np.asarray(...)`-style host state
        if isinstance(node.value, ast.Call):
            chain = _attr_chain(node.value.func)
            if _devicey_chain(chain):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        self.mi.device_attrs.add(t.attr)
            elif chain and (chain[0] in ("np", "numpy")
                            or chain[:2] == ("jax", "device_get")):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        self.mi.host_attrs.add(t.attr)
        self.generic_visit(node)


def _callable_ref(expr: ast.AST) -> Optional[Tuple[str, Optional[Set[str]]]]:
    """(function-name, bound-statics) when `expr` is a bare function
    reference or functools.partial(fn, ...).  bound-statics is a set of
    parameter names bound by the partial (empty for a bare reference) or
    None when the bindings cannot be determined (a ``**kw`` splat) — in
    that case callers must NOT assume the remaining parameters are
    tracers."""
    if isinstance(expr, ast.Name):
        return (expr.id, set())
    if isinstance(expr, ast.Call):
        chain = _attr_chain(expr.func)
        if chain and chain[-1] == "partial" and expr.args:
            inner = expr.args[0]
            if isinstance(inner, ast.Name):
                bound: Optional[Set[str]] = set()
                for kw in expr.keywords:
                    if kw.arg is None:       # **kw splat: bindings unknown
                        bound = None
                        break
                    bound.add(kw.arg)
                return (inner.id, bound)
    return None


class Package:
    """Parsed package + traced-region call graph."""

    def __init__(self, root: str):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}
        self._alias_maps: Dict[str, Dict[str, Tuple[str, Optional[Set[str]]]]] = {}

    # -- loading --------------------------------------------------------
    def add_file(self, path: str) -> None:
        with open(path) as fh:
            src = fh.read()
        mod = _module_name_for(path, self.root)
        mi = ModuleInfo(mod, os.path.relpath(path, self.root),
                        ast.parse(src, filename=path), src.splitlines())
        ix = _ModuleIndexer(mi)
        ix.visit(mi.tree)
        self.modules[mod] = mi
        self._alias_maps[mod] = ix.aliases

    def add_tree(self, pkg_dir: str) -> None:
        for dirpath, _dirs, files in os.walk(pkg_dir):
            for f in sorted(files):
                if f.endswith(".py"):
                    self.add_file(os.path.join(dirpath, f))

    def device_attrs(self) -> Set[str]:
        """Package-wide attribute names assigned ONLY from device
        expressions: an attr any class also assigns a host value
        ('label': device in objectives, numpy in metrics) is ambiguous
        across objects and excluded (built once after loading)."""
        if not hasattr(self, "_device_attrs"):
            dev: Set[str] = set()
            host: Set[str] = set()
            for mi in self.modules.values():
                dev |= mi.device_attrs
                host |= mi.host_attrs
            self._device_attrs = dev - host
        return self._device_attrs

    def mesh_axes(self) -> Set[str]:
        """Union of mesh axis names constructed anywhere in the linted
        tree: string literals in the axis-names argument of ``Mesh(…)``
        / ``make_mesh(…)`` calls and in ``axis_names=`` keywords.  Empty
        when no mesh is built here (partial-tree lint runs) — the
        axis-name checks then stand down rather than flag everything."""
        if not hasattr(self, "_mesh_axes"):
            axes: Set[str] = set()
            for mi in self.modules.values():
                for node in ast.walk(mi.tree):
                    if not isinstance(node, ast.Call):
                        continue
                    chain = _attr_chain(node.func)
                    # Mesh(devices, axis_names) and the modern
                    # jax.make_mesh(axis_shapes, axis_names) both carry
                    # the names at position 1
                    if chain and chain[-1] in ("Mesh", "make_mesh") \
                            and len(node.args) >= 2:
                        axes |= _str_constants(node.args[1])
                    for kw in node.keywords:
                        if kw.arg == "axis_names":
                            axes |= _str_constants(kw.value)
            self._mesh_axes = axes
        return self._mesh_axes

    def func_has_collective(self, fi: Optional[FuncInfo],
                            _seen: Optional[Set[int]] = None) -> bool:
        """Does `fi` perform a blocking SPMD collective, directly or
        through same-package calls?  (axis_index is not a comm op and
        does not count.)"""
        if fi is None:
            return False
        if not hasattr(self, "_coll_memo"):
            self._coll_memo: Dict[int, bool] = {}
        memo = self._coll_memo
        if id(fi) in memo:
            return memo[id(fi)]
        seen = _seen if _seen is not None else set()
        if id(fi) in seen:
            return False                       # cycle: no new evidence
        seen.add(id(fi))
        mi = self.modules[fi.module]
        result = False
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            cname = _collective_name(node.func)
            if cname is not None and cname in _COMM_COLLECTIVES:
                result = True
                break
            target = self.resolve_callee(mi, fi.qualname, node.func)
            if target is not None and target is not fi \
                    and self.func_has_collective(target, seen):
                result = True
                break
        if _seen is None or result:
            memo[id(fi)] = result
        return result

    def branch_has_collective(self, mi: ModuleInfo, qual: str,
                              expr: ast.AST) -> Optional[bool]:
        """Whether a lax.cond/lax.switch branch argument performs a
        collective: True/False when determinable, None when the branch
        reference cannot be resolved (no false positives on unknowns)."""
        if isinstance(expr, ast.Lambda):
            for n in ast.walk(expr.body):
                if isinstance(n, ast.Call):
                    cname = _collective_name(n.func)
                    if cname is not None and cname in _COMM_COLLECTIVES:
                        return True
                    target = self.resolve_callee(mi, qual, n.func)
                    if target is not None \
                            and self.func_has_collective(target):
                        return True
            return False
        refs = [fn for fn, _extra in self._fn_refs(mi, expr)
                if fn is not None]
        if not refs:
            return None
        return any(self.func_has_collective(fn) for fn in refs)

    # -- resolution -----------------------------------------------------
    def resolve(self, module: str, name: str) -> Optional[FuncInfo]:
        mi = self.modules.get(module)
        if mi is None:
            return None
        if name in mi.funcs:
            return mi.funcs[name]
        if name in mi.imports:
            src_mod, src_name = mi.imports[name]
            if src_mod != module:
                return self.resolve(src_mod, src_name)
        alias = self._alias_maps.get(module, {}).get(name)
        if alias is not None:
            return self.resolve(module, alias[0])
        return None

    def resolve_callee(self, mi: ModuleInfo, qual: str,
                       func: ast.AST) -> Optional[FuncInfo]:
        """Resolve a Call callee to a package FuncInfo: bare name,
        self.method, or imported-module attribute."""
        if isinstance(func, ast.Name):
            return self.resolve(mi.name, func.id)
        chain = _attr_chain(func)
        if not chain or len(chain) != 2:
            return None
        base, attr = chain
        if base == "self":
            # method in the same class: replace the last qualname part
            parts = qual.split(".")
            for cut in range(len(parts) - 1, 0, -1):
                cand = ".".join(parts[:cut] + [attr])
                if cand in mi.funcs:
                    return mi.funcs[cand]
            return None
        if base in mi.mod_aliases:
            return self.resolve(mi.mod_aliases[base], attr)
        if base in mi.imports:          # `from .ops import eval as deval`
            src_mod, src_name = mi.imports[base]
            return self.resolve(f"{src_mod}.{src_name}", attr)
        return None

    # -- traced-region discovery ---------------------------------------
    def mark_traced(self) -> None:
        work: List[FuncInfo] = [fi for mi in self.modules.values()
                                for fi in set(mi.funcs.values()) if fi.traced]

        def mark(fi: Optional[FuncInfo], tracer_params: bool = False,
                 statics: Optional[Set[str]] = None) -> None:
            if fi is None:
                return
            new_statics = statics or set()
            if not fi.traced:
                fi.traced = True
                if tracer_params:
                    fi.tracer_params = set(fi.params) - new_statics
                fi.statics |= new_statics
                work.append(fi)
            elif tracer_params and not fi.tracer_params and not fi.is_jit_root:
                fi.tracer_params = set(fi.params) - new_statics
                fi.statics |= new_statics

        # seed: jit()/shard_map()/lax-control-flow call sites anywhere.
        # A partial() with a **splat hides which parameters are bound
        # (extra is None): the body is traced but parameters must not be
        # assumed tracers, or every static config branch would flag.
        for mi in self.modules.values():
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                statics = _is_jit_expr(node.func)
                if statics is not None and node.args:
                    for fn, extra in self._fn_refs(mi, node.args[0]):
                        mark(fn, tracer_params=extra is not None,
                             statics=statics | (extra or set()))
                    continue
                chain = _attr_chain(node.func)
                if chain and chain[-1] in _TRACE_WRAPPER_FN_ARGS:
                    for pos in _TRACE_WRAPPER_FN_ARGS[chain[-1]]:
                        if pos < len(node.args):
                            for fn, extra in self._fn_refs(mi,
                                                           node.args[pos]):
                                mark(fn, tracer_params=extra is not None,
                                     statics=extra or set())

        # propagate through same-package calls from traced bodies
        seen: Set[Tuple[str, str]] = set()
        while work:
            fi = work.pop()
            key = (fi.module, fi.qualname)
            if key in seen:
                continue
            seen.add(key)
            mi = self.modules[fi.module]
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    target = self.resolve_callee(mi, fi.qualname, node.func)
                    if target is not None and not target.traced:
                        mark(target)
                    # functools.partial(fn, ...) inside traced bodies:
                    # fn will be called traced (lax.cond branch tables)
                    ref = _callable_ref(node)
                    if ref is not None and isinstance(node, ast.Call) \
                            and ref[0] != getattr(node.func, "id", None):
                        mark(self.resolve(mi.name, ref[0]),
                             tracer_params=False)

        # shard_map reachability (shardlint): the bodies handed to
        # shard_map / compat_shard_map, then everything they call
        # (including lax control-flow bodies and partial aliases) — the
        # region where mesh axes are bound and collectives are legal
        smap_work: List[FuncInfo] = []

        def mark_smap(fn: Optional[FuncInfo]) -> None:
            if fn is not None and not fn.smap:
                fn.smap = True
                smap_work.append(fn)

        for mi in self.modules.values():
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                if chain and chain[-1] in ("shard_map", "compat_shard_map") \
                        and node.args:
                    for fn, _extra in self._fn_refs(mi, node.args[0]):
                        mark_smap(fn)
        seen_s: Set[Tuple[str, str]] = set()
        while smap_work:
            fi = smap_work.pop()
            key = (fi.module, fi.qualname)
            if key in seen_s:
                continue
            seen_s.add(key)
            mi = self.modules[fi.module]
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                mark_smap(self.resolve_callee(mi, fi.qualname, node.func))
                ref = _callable_ref(node)
                if ref is not None:
                    mark_smap(self.resolve(mi.name, ref[0]))
                chain = _attr_chain(node.func)
                if chain and chain[-1] in _TRACE_WRAPPER_FN_ARGS:
                    for pos in _TRACE_WRAPPER_FN_ARGS[chain[-1]]:
                        if pos < len(node.args):
                            for fn, _extra in self._fn_refs(mi,
                                                            node.args[pos]):
                                mark_smap(fn)

    def _fn_refs(self, mi: ModuleInfo, expr: ast.AST
                 ) -> Iterable[Tuple[Optional[FuncInfo], Optional[Set[str]]]]:
        """FuncInfos referenced by a jit/shard_map/lax-wrapper argument:
        a name, functools.partial(name, ...), a [list] of names (switch),
        or a nested shard_map/partial call."""
        if isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                yield from self._fn_refs(mi, e)
            return
        ref = _callable_ref(expr)
        if ref is not None:
            name, bound = ref
            # chase local `fn = functools.partial(f, **kw)` aliases,
            # merging binding knowledge: an unknown (**splat) binding
            # anywhere in the chain means parameters must not be
            # assumed tracers
            amap = self._alias_maps.get(mi.name, {})
            hops: Set[str] = set()
            while name in amap and name not in hops:
                hops.add(name)
                aname, abound = amap[name]
                bound = (None if bound is None or abound is None
                         else bound | abound)
                name = aname
            yield self.resolve(mi.name, name), bound
            return
        if isinstance(expr, ast.Call):     # jit(compat_shard_map(fn, ...))
            chain = _attr_chain(expr.func)
            if chain and chain[-1] in _TRACE_WRAPPER_FN_ARGS and expr.args:
                yield from self._fn_refs(mi, expr.args[0])


# ---------------------------------------------------------------------------
# rule checks
# ---------------------------------------------------------------------------


class _Dataflow:
    """Per-function device-value tracking (names only, straight-line
    approximation: later assignments overwrite earlier ones)."""

    def __init__(self, pkg: Package, mi: ModuleInfo, fi: FuncInfo):
        self.pkg = pkg
        self.mi = mi
        self.fi = fi
        self.devicey_names: Set[str] = set(fi.tracer_params)
        # shardlint taint lattice: names KNOWN shard-local (derived from
        # axis_index / psum_scatter / all_to_all / ppermute with no
        # intervening replicating collective) vs names KNOWN replicated
        # (derived from psum-family results / combine_sharded_records).
        # Everything else — parameters included — is unknown and fires
        # no rule: the runtime DivergenceSanitizer owns that remainder.
        self.shard_local_names: Set[str] = set()
        self.replicated_names: Set[str] = set()

    def is_devicey(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.devicey_names
        if isinstance(expr, ast.Call):
            chain = _attr_chain(expr.func)
            if chain:
                if chain[:2] == ("jax", "device_get"):
                    return False                       # sanctioned fetch
                if _devicey_chain(chain):
                    return True
                if chain[0] in _DEVICE_MODULES or chain[0] == "jax":
                    return False                       # host-returning API
            # only jit ROOTS reliably return device arrays; a merely
            # reachable-from-jit helper called with host args at trace
            # time returns host values (gather_scratch_capacity etc.)
            target = self.pkg.resolve_callee(self.mi, self.fi.qualname,
                                             expr.func)
            if target is not None and target.is_jit_root:
                return True
            # method call on a devicey value: x.sum(), x.reshape(...)
            if isinstance(expr.func, ast.Attribute):
                return self.is_devicey(expr.func.value)
            return False
        if isinstance(expr, ast.BinOp):
            return self.is_devicey(expr.left) or self.is_devicey(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.is_devicey(expr.operand)
        if isinstance(expr, ast.Compare):
            # identity/containment tests (`x is None`) never call the
            # tracer's __bool__ and return a host bool — not a hazard
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in expr.ops):
                return False
            return self.is_devicey(expr.left) or any(
                self.is_devicey(c) for c in expr.comparators)
        if isinstance(expr, ast.BoolOp):
            return any(self.is_devicey(v) for v in expr.values)
        if isinstance(expr, ast.Subscript):
            return self.is_devicey(expr.value)
        if isinstance(expr, ast.Attribute):
            if expr.attr in ("shape", "ndim", "dtype", "size", "itemsize",
                             "nbytes", "at"):
                return expr.attr == "at" and self.is_devicey(expr.value)
            # object state: an attribute assigned from a device
            # expression (self.score = jnp.asarray(...)) is a device
            # value wherever it is read — float(self.score) is the same
            # stall as float(score).  Scoping controls collisions: a
            # direct `self.x` read matches only attrs registered in the
            # SAME module (objectives' device self.label must not taint
            # metrics' host self.label); a multi-hop read through
            # another object (`self.train_score.score`) is cross-class
            # by construction and consults the package-wide registry.
            b, levels = expr.value, 1
            while isinstance(b, ast.Attribute):
                b, levels = b.value, levels + 1
            if isinstance(b, ast.Name) and b.id == "self":
                if levels == 1 and expr.attr in (self.mi.device_attrs
                                                 - self.mi.host_attrs):
                    return True
                if levels >= 2 and expr.attr in self.pkg.device_attrs():
                    return True
            return self.is_devicey(expr.value)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.is_devicey(e) for e in expr.elts)
        if isinstance(expr, ast.IfExp):
            return self.is_devicey(expr.body) or self.is_devicey(expr.orelse)
        return False

    # -- shardlint taint lattice ---------------------------------------
    def is_shard_local(self, expr: ast.AST) -> bool:
        """Provably shard-varying: axis_index / psum_scatter /
        all_to_all / ppermute results and anything derived from them
        (conservative through calls: a tainted argument taints the
        result, except through the replicating collectives/helpers)."""
        if isinstance(expr, ast.Name):
            return expr.id in self.shard_local_names
        if isinstance(expr, ast.Call):
            cname = _collective_name(expr.func)
            if cname is not None:
                if cname in _SHARD_LOCAL_RESULT:
                    return True
                if cname in _REPLICATED_RESULT:
                    return False
            if isinstance(expr.func, ast.Name) \
                    and expr.func.id in _REPLICATING_HELPERS:
                return False
            chain = _attr_chain(expr.func)
            if chain and chain[-1] in _REPLICATING_HELPERS:
                return False
            if any(self.is_shard_local(a) for a in expr.args) or any(
                    self.is_shard_local(kw.value) for kw in expr.keywords):
                return True
            if isinstance(expr.func, ast.Attribute):       # x.sum() etc.
                return self.is_shard_local(expr.func.value)
            return False
        if isinstance(expr, ast.BinOp):
            return (self.is_shard_local(expr.left)
                    or self.is_shard_local(expr.right))
        if isinstance(expr, ast.UnaryOp):
            return self.is_shard_local(expr.operand)
        if isinstance(expr, ast.Compare):
            return self.is_shard_local(expr.left) or any(
                self.is_shard_local(c) for c in expr.comparators)
        if isinstance(expr, ast.BoolOp):
            return any(self.is_shard_local(v) for v in expr.values)
        if isinstance(expr, ast.Subscript):
            return (self.is_shard_local(expr.value)
                    or self.is_shard_local(expr.slice))
        if isinstance(expr, ast.Attribute):
            return self.is_shard_local(expr.value)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.is_shard_local(e) for e in expr.elts)
        if isinstance(expr, ast.IfExp):
            return (self.is_shard_local(expr.body)
                    or self.is_shard_local(expr.orelse))
        return False

    def is_replicated(self, expr: ast.AST) -> bool:
        """Provably replicated across shards: literals, psum-family /
        combine_sharded_records results, and pure elementwise math over
        replicated operands.  Used only to SILENCE divergent-collective
        on predicates the analysis can vouch for — unknowns stay
        findings (suppress with a written reason)."""
        if isinstance(expr, ast.Constant):
            return True
        if isinstance(expr, ast.Name):
            return expr.id in self.replicated_names
        if isinstance(expr, ast.Call):
            cname = _collective_name(expr.func)
            if cname is not None:
                return cname in _REPLICATED_RESULT
            if isinstance(expr.func, ast.Name) \
                    and expr.func.id in _REPLICATING_HELPERS:
                return True
            chain = _attr_chain(expr.func)
            if chain and chain[-1] in _REPLICATING_HELPERS:
                return True
            # device math (jnp.sum(replicated) etc.) preserves
            # replication when every operand is replicated
            if chain and _devicey_chain(chain) and (expr.args
                                                    or expr.keywords):
                return all(self.is_replicated(a) for a in expr.args) \
                    and all(self.is_replicated(kw.value)
                            for kw in expr.keywords)
            return False
        if isinstance(expr, ast.BinOp):
            return (self.is_replicated(expr.left)
                    and self.is_replicated(expr.right))
        if isinstance(expr, ast.UnaryOp):
            return self.is_replicated(expr.operand)
        if isinstance(expr, ast.Compare):
            return self.is_replicated(expr.left) and all(
                self.is_replicated(c) for c in expr.comparators)
        if isinstance(expr, ast.BoolOp):
            return all(self.is_replicated(v) for v in expr.values)
        if isinstance(expr, ast.Subscript):
            return self.is_replicated(expr.value)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return all(self.is_replicated(e) for e in expr.elts)
        if isinstance(expr, ast.IfExp):
            return (self.is_replicated(expr.body)
                    and self.is_replicated(expr.orelse))
        return False

    def note_assign(self, node: ast.AST) -> None:
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            return
        dev = self.is_devicey(value)
        sl = self.is_shard_local(value)
        rep = self.is_replicated(value)
        for t in targets:
            if isinstance(t, ast.Name):
                if dev:
                    self.devicey_names.add(t.id)
                else:
                    self.devicey_names.discard(t.id)
                if sl:
                    self.shard_local_names.add(t.id)
                else:
                    self.shard_local_names.discard(t.id)
                if rep:
                    self.replicated_names.add(t.id)
                else:
                    self.replicated_names.discard(t.id)


def _has_float64(expr: ast.AST) -> Optional[ast.AST]:
    for n in ast.walk(expr):
        chain = _attr_chain(n)
        if chain and chain[-1] in ("float64", "double") and chain[0] in (
                "np", "numpy", "jnp"):
            return n
        if isinstance(n, ast.Constant) and n.value in ("float64", "double"):
            return n
    return None


def _config_attr(expr: ast.AST) -> Optional[str]:
    """Name of a Config field read inside `expr` (cfg.x / config.x /
    self.config.x / anything.config.x), or None."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute):
            base = n.value
            if isinstance(base, ast.Name) and base.id in ("cfg", "config"):
                return n.attr
            if isinstance(base, ast.Attribute) and base.attr == "config":
                return n.attr
    return None


class _Checker(ast.NodeVisitor):
    """Rule checks over one function body (or module top level)."""

    def __init__(self, pkg: Package, mi: ModuleInfo, fi: Optional[FuncInfo],
                 findings: List[Finding]):
        self.pkg = pkg
        self.mi = mi
        self.fi = fi
        self.traced = fi is not None and fi.traced
        self.qual = fi.qualname if fi is not None else "<module>"
        self.flow = _Dataflow(pkg, mi, fi) if fi is not None else None
        self.findings = findings

    # -- helpers --------------------------------------------------------
    def _emit(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(Finding(self.mi.path, node.lineno, rule, msg,
                                     self.qual))

    def _devicey(self, expr: ast.AST) -> bool:
        return self.flow is not None and self.flow.is_devicey(expr)

    # -- assignments feed the dataflow ---------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        if self.flow is not None:
            self.flow.note_assign(node)

    visit_AugAssign = visit_Assign
    visit_AnnAssign = visit_Assign

    # -- host-sync ------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        chain = _attr_chain(func)
        # .item(): a one-element device→host sync wherever it appears
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not node.args:
            self._emit(node, "host-sync",
                       ".item() forces a blocking device→host sync; "
                       "batch scalar fetches with jax.device_get at the "
                       "loop boundary")
        # float()/int()/bool() of a device value
        if isinstance(func, ast.Name) and func.id in ("float", "int", "bool") \
                and len(node.args) == 1 and self._devicey(node.args[0]):
            self._emit(node, "host-sync",
                       f"{func.id}() on a device value blocks on a "
                       "device→host transfer; keep it on device or fetch "
                       "explicitly (batched) with jax.device_get")
        # np.asarray / np.array of a device value
        if chain and chain[0] in ("np", "numpy", "onp") \
                and chain[-1] in ("asarray", "array", "ascontiguousarray") \
                and node.args and self._devicey(node.args[0]):
            self._emit(node, "host-sync",
                       f"{'.'.join(chain)} of a device value is an "
                       "implicit device→host transfer; use jax.device_get "
                       "(explicit, transfer-guard-clean, batchable)")
        if self.traced:
            self._check_traced_call(node, chain)
        self._check_config_static(node)
        self._check_shard_rules(node, chain)
        self.generic_visit(node)

    # -- shardlint: SPMD collective correctness -------------------------
    def _check_shard_rules(self, node: ast.Call,
                           chain: Optional[Tuple[str, ...]]) -> None:
        axes = self.pkg.mesh_axes()
        cname = _collective_name(node.func)
        if cname is not None:
            axis = _collective_axis_arg(node, cname)
            consts = _str_constants(axis) if axis is not None else set()
            for c in sorted(consts):
                if axes and c not in axes:
                    self._emit(
                        node, "collective-mismatch",
                        f"{cname} over axis '{c}', which is not an axis "
                        f"of any mesh built here (known axes: "
                        f"{sorted(axes)}); a mismatched axis_name is an "
                        "unbound-axis trace error under shard_map — or a "
                        "reduction over the wrong mesh axis")
            if consts and self.fi is not None and self.fi.traced \
                    and not self.fi.smap:
                self._emit(
                    node, "collective-mismatch",
                    f"{cname} over axis "
                    f"'{'/'.join(sorted(consts))}' in traced code not "
                    "reachable from any shard_map body: nothing binds "
                    "the axis, so the trace fails (wrap the caller in "
                    "shard_map or thread the axis name as a "
                    "None-guarded parameter)")
            if cname == "psum_scatter" and self.traced \
                    and not self._has_divisibility_guard():
                self._emit(
                    node, "scatter-divisibility",
                    "psum_scatter with no static divisibility guarantee "
                    "for the scattered axis in the enclosing function "
                    "chain: a size that does not tile the mesh axis is "
                    "a raw XLA shape error at trace time; pad with "
                    "learner/common.pad_cols_to_ndev (or guard with "
                    "`if size % ndev: raise ValueError(...)`)")
        # axis-parameter bindings at any call site
        # (functools.partial(build_tree, data_axis="rows") …)
        for kw in node.keywords:
            if kw.arg and _AXIS_KWARG.search(kw.arg):
                for c in sorted(_str_constants(kw.value)):
                    if axes and c not in axes:
                        self._emit(
                            kw.value, "collective-mismatch",
                            f"axis binding {kw.arg}='{c}' names no axis "
                            f"of any mesh built here (known axes: "
                            f"{sorted(axes)}); the collective it reaches "
                            "will trace with an unbound axis name")
        # PartitionSpec literals must name real mesh axes too
        is_pspec = (chain and chain[-1] == "PartitionSpec") or (
            isinstance(node.func, ast.Name)
            and self.mi.imports.get(node.func.id, ("", ""))[1]
            == "PartitionSpec")
        if is_pspec:
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                for c in sorted(_str_constants(a)):
                    if axes and c not in axes:
                        self._emit(
                            node, "collective-mismatch",
                            f"PartitionSpec names axis '{c}', which is "
                            f"not an axis of any mesh built here (known "
                            f"axes: {sorted(axes)})")
        # divergent collectives + shard-local control flow
        is_lax = chain and (len(chain) == 1 or chain[0] in ("jax", "lax"))
        if is_lax and chain[-1] in ("cond", "switch") and self.traced \
                and len(node.args) >= 2:
            pred = node.args[0]
            if chain[-1] == "cond":
                branches = list(node.args[1:3])
            else:
                b = node.args[1]
                branches = (list(b.elts)
                            if isinstance(b, (ast.List, ast.Tuple))
                            else [b])
            infos = [self.pkg.branch_has_collective(self.mi, self.qual, b)
                     for b in branches]
            known = [i for i in infos if i is not None]
            any_coll = any(known)
            pred_sl = self._shard_local(pred)
            if any_coll and pred_sl:
                self._emit(
                    node, "divergent-collective",
                    f"collective inside a lax.{chain[-1]} branch gated "
                    "by a shard-local predicate: shards disagree on the "
                    "branch, some skip the collective, and the mesh "
                    "deadlocks cross-host; make the predicate "
                    "replicated (psum the inputs) or hoist the "
                    "collective out of the branch")
            elif any_coll and False in known \
                    and not self._replicated(pred):
                self._emit(
                    node, "divergent-collective",
                    f"collective in only one branch of a "
                    f"lax.{chain[-1]} whose predicate is not provably "
                    "replicated: if any shard ever disagrees on the "
                    "predicate, the shards that skip the branch "
                    "deadlock the collective; prove the predicate "
                    "replicated (derive it from psum/"
                    "combine_sharded_records) or suppress with the "
                    "replication argument written down")
            if pred_sl:
                self._emit(
                    pred, "replication-leak",
                    f"shard-local value steers a lax.{chain[-1]} "
                    "predicate: the growth loops require control flow "
                    "to be bitwise-replicated across shards (PRs 3-4) — "
                    "reduce it with psum/all_gather first")
        if is_lax and chain[-1] == "fori_loop" and self.traced:
            for bound in node.args[:2]:
                if self._shard_local(bound):
                    self._emit(
                        bound, "replication-leak",
                        "shard-local value as a fori_loop bound: shards "
                        "run different trip counts, so any collective "
                        "in the body deadlocks and replicated state "
                        "diverges; psum the bound first")

    def _shard_local(self, expr: ast.AST) -> bool:
        return self.flow is not None and self.flow.is_shard_local(expr)

    def _replicated(self, expr: ast.AST) -> bool:
        return self.flow is not None and self.flow.is_replicated(expr)

    def _has_divisibility_guard(self) -> bool:
        """Static divisibility evidence for psum_scatter in the lexical
        function chain: an assert with a `%` test, an `if … % …: raise`
        guard, pad-to-multiple arithmetic `nd * ((x + nd - 1) // nd)`,
        or a pad_cols_to_ndev call."""
        if self.fi is None:
            return False
        chain_fis = [self.fi]
        parts = self.fi.qualname.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            anc = self.mi.funcs.get(".".join(parts[:cut]))
            if anc is not None and anc not in chain_fis:
                chain_fis.append(anc)

        def has_mod(expr: ast.AST) -> bool:
            return any(isinstance(b, ast.BinOp) and isinstance(b.op, ast.Mod)
                       for b in ast.walk(expr))

        for fi in chain_fis:
            for n in ast.walk(fi.node):
                if isinstance(n, ast.Assert) and has_mod(n.test):
                    return True
                if isinstance(n, ast.If) and has_mod(n.test) and any(
                        isinstance(s, ast.Raise) for s in n.body):
                    return True
                if isinstance(n, ast.Call):
                    cchain = _attr_chain(n.func)
                    if cchain and cchain[-1] in _PAD_HELPERS:
                        return True
                if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mult):
                    for a, b in ((n.left, n.right), (n.right, n.left)):
                        if isinstance(b, ast.BinOp) \
                                and isinstance(b.op, ast.FloorDiv) \
                                and ast.dump(b.right) == ast.dump(a):
                            return True
        return False

    def _check_traced_call(self, node: ast.Call,
                           chain: Optional[Tuple[str, ...]]) -> None:
        func = node.func
        # print / logging inside traced code
        if isinstance(func, ast.Name) and func.id == "print":
            self._emit(node, "retrace-hazard",
                       "print() inside traced code runs at trace time "
                       "only (or forces a callback); use "
                       "jax.debug.print or hoist out of the jit region")
        if chain and len(chain) >= 2 and chain[0] in ("log", "logging",
                                                      "logger", "Log"):
            self._emit(node, "retrace-hazard",
                       f"{'.'.join(chain)}() inside traced code is a "
                       "trace-time host effect; hoist logging out of the "
                       "jit region")
        # nondeterminism
        if chain:
            if chain[0] == "time" and chain[-1] in (
                    "time", "perf_counter", "monotonic", "time_ns",
                    "process_time"):
                self._emit(node, "nondeterminism",
                           f"{'.'.join(chain)}() in traced code executes "
                           "once at trace time and bakes a stale constant "
                           "into the compiled program")
            if chain[0] == "random" or chain[:2] in (("np", "random"),
                                                     ("numpy", "random")):
                self._emit(node, "nondeterminism",
                           f"{'.'.join(chain)}() in traced code draws at "
                           "trace time (one arbitrary constant per "
                           "compile); thread a jax.random key instead")
        # dtype-drift: astype(float64)
        if isinstance(func, ast.Attribute) and func.attr == "astype" \
                and node.args and _has_float64(node.args[0]) is not None:
            self._emit(node, "dtype-drift",
                       "astype(float64) inside traced code silently "
                       "downcasts to f32 with x64 disabled; pin the "
                       "intended dtype explicitly")

    def _check_config_static(self, node: ast.Call) -> None:
        """Config-derived Python value passed to a jitted function's
        traced (non-static) parameter."""
        if self.fi is None:
            target = None
        else:
            target = self.pkg.resolve_callee(self.mi, self.qual, node.func)
        if target is None or not target.is_jit_root:
            return
        params = list(target.params)
        for i, arg in enumerate(node.args):
            fieldname = _config_attr(arg)
            if fieldname is None:
                continue
            pname = params[i] if i < len(params) else f"arg{i}"
            if pname not in target.statics:
                self._emit(
                    arg, "retrace-hazard",
                    f"Config field '{fieldname}' flows into jitted "
                    f"'{target.qualname}' parameter '{pname}' which is "
                    "not in static_argnames: a per-call scalar upload, "
                    "and a silent retrace hazard if it reaches shape or "
                    "branch logic; declare it static or bind it with "
                    "functools.partial")
        for kw in node.keywords:
            if kw.arg is None:
                continue
            fieldname = _config_attr(kw.value)
            if fieldname is not None and kw.arg not in target.statics:
                self._emit(
                    kw.value, "retrace-hazard",
                    f"Config field '{fieldname}' flows into jitted "
                    f"'{target.qualname}' parameter '{kw.arg}' which is "
                    "not in static_argnames; declare it static or bind "
                    "it with functools.partial")

    # -- implicit __bool__ on tracers ----------------------------------
    def _check_test(self, test: ast.AST, kind: str) -> None:
        if self.traced and self._devicey(test):
            self._emit(test, "host-sync",
                       f"`{kind}` on a traced value calls __bool__ on a "
                       "tracer (TracerBoolConversionError under jit, a "
                       "blocking sync when eager); use lax.cond/jnp.where")

    def visit_If(self, node: ast.If) -> None:
        self._check_test(node.test, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_test(node.test, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_test(node.test, "ternary if")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_test(node.test, "assert")
        self.generic_visit(node)

    # -- f-strings formatting device values -----------------------------
    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if self.traced:
            for v in node.values:
                if isinstance(v, ast.FormattedValue) and self._devicey(v.value):
                    self._emit(node, "retrace-hazard",
                               "f-string formats a traced value: renders "
                               "the tracer repr at trace time (and forces "
                               "a sync when eager); use jax.debug.print")
                    break
        self.generic_visit(node)

    # -- dtype drift on literals / dtype kwargs -------------------------
    def visit_Constant(self, node: ast.Constant) -> None:
        if self.traced and isinstance(node.value, float) and node.value != 0.0:
            a = abs(node.value)
            if a > _F32_MAX or a < _F32_TINY:
                self._emit(node, "dtype-drift",
                           f"float literal {node.value!r} is outside "
                           "float32 range and becomes 0/inf when the "
                           "tracer downcasts with x64 disabled")

    def visit_keyword(self, node: ast.keyword) -> None:
        if self.traced and node.arg == "dtype" \
                and _has_float64(node.value) is not None:
            self._emit(node.value, "dtype-drift",
                       "dtype=float64 inside traced code is quietly f32 "
                       "with x64 disabled; pin float32 (or int32) "
                       "explicitly")
        self.generic_visit(node)

    # keep nested defs inside their own _Checker run
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if self.fi is not None and node is not self.fi.node:
            return                      # separate FuncInfo covers it
        for d in node.decorator_list:
            self.visit(d)
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)


# np.float64(...) calls in traced code (checker-level, needs chain only)
def _np_float64_calls(fi: FuncInfo, mi: ModuleInfo,
                      findings: List[Finding]) -> None:
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[-1] in ("float64", "double") \
                    and chain[0] in ("np", "numpy", "jnp"):
                findings.append(Finding(
                    mi.path, node.lineno, "dtype-drift",
                    "np.float64 cast inside traced code silently becomes "
                    "f32 with x64 disabled; pin float32 or hoist to host",
                    fi.qualname))


# ---------------------------------------------------------------------------
# suppression handling
# ---------------------------------------------------------------------------


def _suppressions_for(lines: Sequence[str], lineno: int
                      ) -> Optional[Tuple[Set[str], str]]:
    """(rules, reason) from a graftlint comment on `lineno` or the line
    above (1-indexed); None when no suppression applies."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _SUPPRESS_RE.search(lines[ln - 1])
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                return rules, m.group(2).strip()
    return None


def load_allowlist(path: str) -> Dict[Tuple[str, str, str], str]:
    """path::rule::qualname -> reason entries from the reviewed file."""
    out: Dict[Tuple[str, str, str], str] = {}
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, _, reason = line.partition("—")
            if not reason:
                body, _, reason = line.partition(" - ")
            parts = [p.strip() for p in body.strip().split("::")]
            if len(parts) == 3:
                out[(parts[0], parts[1], parts[2])] = reason.strip()
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def lint_paths(paths: Sequence[str], root: str,
               allowlist: Optional[Dict[Tuple[str, str, str], str]] = None,
               used_allowlist: Optional[Set[Tuple[str, str, str]]] = None
               ) -> List[Finding]:
    """Run every rule over `paths` (files or directories).  Returns
    unsuppressed findings; suppressions without a reason are findings
    themselves (`suppression` rule).  When `used_allowlist` is given it
    is filled with the allowlist keys that actually matched a finding —
    the input of the stale-entry check (stale_allowlist_entries)."""
    findings, _stale = lint_run(paths, root, allowlist,
                                used_allowlist=used_allowlist,
                                check_stale=False)
    return findings


def stale_allowlist_entries(
        allowlist: Dict[Tuple[str, str, str], str],
        used: Set[Tuple[str, str, str]],
        linted_paths: Set[str], root: str) -> List[str]:
    """Allowlist entries that no longer earn their keep: the file was
    linted and the key matched no finding (fix landed, or the qualname
    was renamed), or the file no longer exists.  Entries for files
    outside the linted set are left alone, and CALLERS must only run
    this audit over the whole package — whether an entry still produces
    its finding can depend on cross-file context (traced-reachability,
    mesh axes), so a partial-tree run cannot judge even its own files
    (scripts/run_lint.py gates on full scope).  Mirrors
    check_config_coverage.py's stale-allowlist rule: the list may only
    shrink consciously."""
    out: List[str] = []
    for (path, rule, qual), _reason in sorted(allowlist.items()):
        if (path, rule, qual) in used:
            continue
        if path in linted_paths:
            out.append(f"{path}::{rule}::{qual} — no longer produces a "
                       "finding; remove the entry")
        elif not os.path.exists(os.path.join(root, path)):
            out.append(f"{path}::{rule}::{qual} — file no longer exists; "
                       "remove the entry")
    return out


def lint_run(paths: Sequence[str], root: str,
             allowlist: Optional[Dict[Tuple[str, str, str], str]] = None,
             used_allowlist: Optional[Set[Tuple[str, str, str]]] = None,
             check_stale: bool = True
             ) -> Tuple[List[Finding], List[str]]:
    """lint_paths plus the stale-allowlist audit: returns
    (findings, stale-entry descriptions)."""
    pkg = Package(root)
    for p in paths:
        if os.path.isdir(p):
            pkg.add_tree(p)
        else:
            pkg.add_file(p)
    pkg.mark_traced()
    allowlist = allowlist or {}
    used: Set[Tuple[str, str, str]] = (used_allowlist
                                       if used_allowlist is not None
                                       else set())

    raw: List[Finding] = []
    for mi in pkg.modules.values():
        funcs = {id(fi.node): fi for fi in mi.funcs.values()}
        for fi in set(funcs.values()):
            _Checker(pkg, mi, fi, raw).visit(fi.node)
            if fi.traced:
                _np_float64_calls(fi, mi, raw)
        # module top level (rare, but .item() at import time counts)
        top = _Checker(pkg, mi, None, raw)
        for stmt in mi.tree.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                top.visit(stmt)

    # dedupe (nested defs can be visited from two scopes)
    seen: Set[Tuple[str, int, str, str]] = set()
    findings: List[Finding] = []
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        key = (f.path, f.line, f.rule, f.message)
        if key in seen:
            continue
        seen.add(key)
        mi = next(m for m in pkg.modules.values() if m.path == f.path)
        sup = _suppressions_for(mi.lines, f.line)
        if sup is not None and f.rule in sup[0]:
            if not sup[1]:
                findings.append(Finding(
                    f.path, f.line, "suppression",
                    f"graftlint: allow({f.rule}) has no reason; "
                    "suppressions must say why (\"# graftlint: "
                    "allow(rule) — reason\")", f.qualname))
            continue
        wl = allowlist.get((f.path, f.rule, f.qualname))
        if wl is not None:
            used.add((f.path, f.rule, f.qualname))
            if wl:
                continue
            findings.append(Finding(
                f.path, f.line, "suppression",
                "allowlist entry has no reason", f.qualname))
            continue
        findings.append(f)
    stale: List[str] = []
    if check_stale:
        linted = {m.path for m in pkg.modules.values()}
        stale = stale_allowlist_entries(allowlist, used, linted, root)
    return findings, stale
