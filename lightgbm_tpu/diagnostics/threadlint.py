"""threadlint — concurrency-correctness static analysis (third lint
pillar, beside graftlint's JAX-hazard rules and shardlint's SPMD rules,
both in lint.py).

The reference C++ core gets its thread-safety story from OpenMP
structured parallelism; our serving tier replaced that with free-form
``threading`` — batcher flusher workers, registry writer locks, catalog
LRU scans, router health sweeps, telemetry sinks.  This linter rides
lint.py's package-wide AST call graph (``Package``) and marks the
CONCURRENT REGION the way ``FuncInfo.smap`` marks shard_map
reachability: a *thread root* is every

- ``threading.Thread(target=...)`` construction site (a site inside a
  loop, or a ``ThreadPoolExecutor.submit`` fan-out, is a PLURAL root:
  many threads run the same entry point),
- HTTP handler class (``*RequestHandler`` / ``ThreadingHTTPServer``
  subclasses — one thread per connection, always plural),
- ``signal.signal`` handler (interleaves with everything else), and
- ``Condition`` waiter loop,

and everything reachable from a root through same-package calls is in
the concurrent region.  Four rules fire inside it:

- ``unguarded-shared-state`` — an instance attribute assigned
  (``self.x = ...`` / ``+=``) outside ``__init__`` from at least two
  distinct thread roots (or one plural root) where some write site
  holds no lock.  A write counts as guarded when it is lexically inside
  ``with <lock>:`` or carries a ``# guarded by <lock>`` annotation on
  its line or the line above (the documented convention for guards the
  lexical scan cannot see — a GIL-atomic flag, a caller-held lock).
- ``lock-order-cycle`` — the static lock-acquisition graph (which
  locks can be acquired while another is held, through calls) contains
  a cycle: two threads taking the edges in different orders deadlock.
  ``acquire(blocking=False)`` inserts no edge (a try-lock cannot
  deadlock).
- ``blocking-under-lock`` — socket/file I/O, ``jax.device_get`` /
  ``block_until_ready``, ``Future.result()``, ``time.sleep``,
  ``subprocess``, or a timeout-less ``Condition.wait`` on a DIFFERENT
  lock, reachable with a known lock held: the hidden p99-stall and
  swap-starvation class (every waiter inherits the holder's stall).
- ``condition-misuse`` — ``Condition.wait`` whose nearest enclosing
  loop is not a ``while`` predicate loop (wakeups are spurious), or
  ``notify``/``notify_all`` without the condition held.

Suppressions use the existing reasoned grammar —
``# graftlint: allow(rule) — reason`` on the finding line or the line
above — and the shared reviewed allowlist
(scripts/lint_allowlist.txt, ``path::rule::qualname — reason``).  The
runtime half is diagnostics/locksan.py: an instrumented-lock shim that
checks the SAME order-cycle property on the acquisitions the fleet
actually performs under load.

Known limits (by design, to stay a milliseconds-cheap stdlib gate):
writes through containers (``self.q.append``) and through foreign
objects (``other.registry.flag = ...``) are not tracked; a ``with`` on
an expression the tables cannot resolve counts as *some* guard for
shared-state purposes but never feeds the order graph or the
blocking rule.  Like lint.py, resolution is static and same-package.

Stdlib-only; scripts/run_lint.py loads it by path beside lint.py.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

try:
    from . import lint as _lint
except ImportError:           # loaded by path (scripts/run_lint.py)
    import importlib.util
    import sys
    _lint = sys.modules.get("graftlint")
    if _lint is None:
        _spec = importlib.util.spec_from_file_location(
            "graftlint",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "lint.py"))
        _lint = importlib.util.module_from_spec(_spec)
        sys.modules["graftlint"] = _lint
        _spec.loader.exec_module(_lint)

Finding = _lint.Finding
FuncInfo = _lint.FuncInfo
ModuleInfo = _lint.ModuleInfo
Package = _lint.Package
load_allowlist = _lint.load_allowlist
stale_allowlist_entries = _lint.stale_allowlist_entries
_attr_chain = _lint._attr_chain
_callable_ref = _lint._callable_ref
_suppressions_for = _lint._suppressions_for

RULES = ("unguarded-shared-state", "lock-order-cycle",
         "blocking-under-lock", "condition-misuse")

# the `# guarded by <lock>` annotation convention (docs/Readme.md):
# names the guard the lexical scan cannot see
_GUARDED_RE = re.compile(r"#\s*guarded\s+by\s+(\S+)")

# lock-ish constructors: stdlib threading and the locksan factories
_LOCK_CTORS = {"Lock": "lock", "RLock": "lock", "Semaphore": "lock",
               "BoundedSemaphore": "lock", "Condition": "condition",
               "lock": "lock", "rlock": "lock", "condition": "condition"}
_LOCK_CTOR_BASES = {"threading", "locksan"}

_HANDLER_BASE_RE = re.compile(
    r"(RequestHandler|HTTPServer|ThreadingMixIn)$")

# methods whose names are too generic for the unique-method fallback
# (routinely invoked on stdlib/foreign objects; a package class
# happening to define one must not vacuum up every such call)
_FALLBACK_DENY = {
    "get", "put", "pop", "append", "items", "keys", "values", "update",
    "close", "read", "write", "start", "stop", "run", "join", "send",
    "recv", "flush", "acquire", "release", "wait", "notify",
    "notify_all", "result", "set", "clear", "copy", "add", "remove",
    # str/bytes/os.path methods: `s.split(",")` must not resolve to a
    # package method that happens to share the name (Tree.split)
    "split", "rsplit", "strip", "lstrip", "rstrip", "replace",
    "partition", "rpartition", "format", "encode", "decode", "lower",
    "upper", "startswith", "endswith", "splitlines", "count", "index",
    "find", "search", "match", "group", "sort", "insert", "extend",
}

_SOCKET_OPS = {"connect", "create_connection", "accept", "recv",
               "recv_into", "sendall", "makefile", "getaddrinfo"}


# ---------------------------------------------------------------------------
# per-module tables: classes, locks, conditions
# ---------------------------------------------------------------------------


class _ClassScan(ast.NodeVisitor):
    """Class qualnames, lock/condition attrs (``self.x = Lock()``
    anywhere in the class body), module-level locks, handler classes."""

    def __init__(self, mi: ModuleInfo):
        self.mi = mi
        self.stack: List[str] = []
        self.classes: Set[str] = set()
        # (classqual) -> {attr: "lock"|"condition"}
        self.class_locks: Dict[str, Dict[str, str]] = {}
        self.module_locks: Dict[str, str] = {}
        self.handler_classes: Set[str] = set()

    def _ctor_kind(self, value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        chain = _attr_chain(value.func)
        if not chain or chain[-1] not in _LOCK_CTORS:
            return None
        if len(chain) > 1 and chain[0] not in _LOCK_CTOR_BASES:
            return None
        return _LOCK_CTORS[chain[-1]]

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        qual = ".".join(self.stack + [node.name])
        self.classes.add(qual)
        for base in node.bases:
            chain = _attr_chain(base)
            name = chain[-1] if chain else None
            if name and (_HANDLER_BASE_RE.search(name)
                         or name in self.handler_classes
                         or any(h.endswith("." + name) or h == name
                                for h in self.handler_classes)):
                self.handler_classes.add(qual)
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Assign(self, node: ast.Assign) -> None:
        kind = self._ctor_kind(node.value)
        if kind is not None:
            for t in node.targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    cls = self._enclosing_class()
                    if cls is not None:
                        self.class_locks.setdefault(cls, {})[t.attr] = kind
                elif isinstance(t, ast.Name) and not self.stack:
                    self.module_locks[t.id] = kind
        self.generic_visit(node)

    def _enclosing_class(self) -> Optional[str]:
        # longest prefix of the current stack that names a known class
        for cut in range(len(self.stack), 0, -1):
            cand = ".".join(self.stack[:cut])
            if cand in self.classes:
                return cand
        return None


class _Tables:
    """Package-wide lock/condition/class tables + method index."""

    def __init__(self, pkg: Package):
        self.pkg = pkg
        self.scans: Dict[str, _ClassScan] = {}
        for mi in pkg.modules.values():
            sc = _ClassScan(mi)
            sc.visit(mi.tree)
            # second pass so handler subclasses declared before their
            # base (or of a same-module handler) are picked up
            sc.visit(mi.tree)
            self.scans[mi.name] = sc
        # unique package-wide method name -> FuncInfo (fallback
        # resolution for instance calls across modules)
        by_name: Dict[str, List[FuncInfo]] = {}
        for mi in pkg.modules.values():
            for fi in set(mi.funcs.values()):
                if "." in fi.qualname:
                    name = fi.qualname.rsplit(".", 1)[1]
                    if not name.startswith("__"):
                        by_name.setdefault(name, []).append(fi)
        self.unique_methods = {
            n: fs[0] for n, fs in by_name.items()
            if len(fs) == 1 and n not in _FALLBACK_DENY}

    def enclosing_class(self, mi: ModuleInfo, qual: str) -> Optional[str]:
        parts = qual.split(".")
        classes = self.scans[mi.name].classes
        for cut in range(len(parts) - 1, 0, -1):
            cand = ".".join(parts[:cut])
            if cand in classes:
                return cand
        return None

    def lock_id(self, mi: ModuleInfo, cls: Optional[str],
                expr: ast.AST) -> Tuple[Optional[str], bool]:
        """(lock id, is-guard) for a with-item / acquire receiver.
        A known lock/condition yields its id; an unresolvable bare
        Name/Attribute still counts as *a* guard (True) without an id."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None:
            kinds = self.scans[mi.name].class_locks.get(cls, {})
            if expr.attr in kinds:
                return f"{mi.name}:{cls}.{expr.attr}", True
        if isinstance(expr, ast.Name):
            if expr.id in self.scans[mi.name].module_locks:
                return f"{mi.name}:{expr.id}", True
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return None, True          # some context manager: a guard,
        return None, False             # but not a known lock

    def condition_attr(self, mi: ModuleInfo, cls: Optional[str],
                       expr: ast.AST) -> Optional[str]:
        """Lock id when ``expr`` names a known Condition attr."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None:
            kinds = self.scans[mi.name].class_locks.get(cls, {})
            if kinds.get(expr.attr) == "condition":
                return f"{mi.name}:{cls}.{expr.attr}"
        if isinstance(expr, ast.Name) \
                and self.scans[mi.name].module_locks.get(expr.id) \
                == "condition":
            return f"{mi.name}:{expr.id}"
        return None

    def resolve_call(self, mi: ModuleInfo, qual: str,
                     func: ast.AST) -> Optional[FuncInfo]:
        """lint.py resolution plus the unique-method fallback: an
        attribute call whose method name is defined exactly once in the
        package resolves to it (cross-module instance calls —
        ``self.server._catalog.submit`` — are invisible to the exact
        resolver)."""
        target = self.pkg.resolve_callee(mi, qual, func)
        if target is not None:
            return target
        if isinstance(func, ast.Name):
            # class instantiation runs __init__ (Booster(model_file=...)
            # reads the model file — blocking the ctor does counts)
            fi = mi.funcs.get(f"{func.id}.__init__")
            if fi is not None:
                return fi
            if func.id in mi.imports:
                mod, nm = mi.imports[func.id]
                tmi = self.pkg.modules.get(mod)
                if tmi is not None:
                    return tmi.funcs.get(f"{nm}.__init__")
        if isinstance(func, ast.Attribute):
            return self.unique_methods.get(func.attr)
        return None


# ---------------------------------------------------------------------------
# thread roots + reachability
# ---------------------------------------------------------------------------


def _resolve_ref(tables: _Tables, mi: ModuleInfo, qual: str,
                 expr: ast.AST) -> Iterable[FuncInfo]:
    """FuncInfos a Thread target / submitted callable may name: a bare
    name or partial (lint.py's _fn_refs), a bound ``self.method``, or a
    lambda whose body hands package callables onward
    (``pool.map(lambda a: call_in_context(ctx, self._chunk, ...))``)."""
    for fn, _bound in tables.pkg._fn_refs(mi, expr):
        if fn is not None:
            yield fn
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        parts = qual.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            cand = ".".join(parts[:cut] + [expr.attr])
            if cand in mi.funcs:
                yield mi.funcs[cand]
                return
    if isinstance(expr, ast.Lambda):
        for node in ast.walk(expr.body):
            if isinstance(node, ast.Call):
                target = tables.resolve_call(mi, qual, node.func)
                if target is not None:
                    yield target
                for arg in node.args:
                    if isinstance(arg, (ast.Name, ast.Attribute)):
                        yield from _resolve_ref(tables, mi, qual, arg)


def _collect_roots(tables: _Tables
                   ) -> List[Tuple[str, bool, FuncInfo]]:
    """(root key, plural, entry FuncInfo) for every thread root."""
    pkg = tables.pkg
    roots: List[Tuple[str, bool, FuncInfo]] = []

    def walk(node: ast.AST, fi: FuncInfo, mi: ModuleInfo,
             in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue               # nested defs analyzed separately
            loop = in_loop or isinstance(
                child, (ast.For, ast.While, ast.ListComp, ast.SetComp,
                        ast.GeneratorExp, ast.DictComp))
            if isinstance(child, ast.Call):
                chain = _attr_chain(child.func)
                if chain and chain[-1] == "Thread" \
                        and (len(chain) == 1 or chain[0] == "threading"):
                    for kw in child.keywords:
                        if kw.arg == "target":
                            for fn in _resolve_ref(tables, mi,
                                                   fi.qualname, kw.value):
                                roots.append((
                                    f"thread:{mi.name}.{fi.qualname}"
                                    f"@{child.lineno}", loop, fn))
                elif isinstance(child.func, ast.Attribute) \
                        and child.func.attr in ("submit", "map") \
                        and child.args:
                    for fn in _resolve_ref(tables, mi, fi.qualname,
                                           child.args[0]):
                        roots.append((
                            f"pool:{mi.name}.{fi.qualname}"
                            f"@{child.lineno}", True, fn))
                elif chain and chain[-1] == "signal" \
                        and chain[0] == "signal" and len(child.args) >= 2:
                    for fn in _resolve_ref(tables, mi, fi.qualname,
                                           child.args[1]):
                        roots.append((
                            f"signal:{mi.name}.{fi.qualname}"
                            f"@{child.lineno}", False, fn))
            walk(child, fi, mi, loop)

    for mi in pkg.modules.values():
        sc = tables.scans[mi.name]
        for fi in set(mi.funcs.values()):
            walk(fi.node, fi, mi, in_loop=False)
            # Condition waiter loops are entry points of the concurrent
            # region in their own right (a waiter parks mid-function)
            cls = tables.enclosing_class(mi, fi.qualname)
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "wait" \
                        and tables.condition_attr(
                            mi, cls, node.func.value) is not None:
                    roots.append((f"waiter:{mi.name}.{fi.qualname}",
                                  False, fi))
                    break
        # every method of an HTTP handler class serves on its own
        # connection thread — all of them are plural roots
        for cls in sorted(sc.handler_classes):
            for fi in set(mi.funcs.values()):
                if fi.qualname.startswith(cls + ".") \
                        and "." not in fi.qualname[len(cls) + 1:]:
                    roots.append((f"handler:{mi.name}.{cls}", True, fi))
    return roots


def _call_graphs(tables: _Tables,
                 funcs: Dict[int, Tuple[ModuleInfo, FuncInfo]]
                 ) -> Tuple[Dict[int, List[FuncInfo]],
                            Dict[int, List[FuncInfo]]]:
    """One AST pass per function: (strict call targets, those plus
    callables handed onward — pool submits, callbacks).  The strict
    graph feeds the lock-effect fixpoint; the wide one feeds thread
    reachability."""
    pkg = tables.pkg
    strict: Dict[int, List[FuncInfo]] = {}
    wide: Dict[int, List[FuncInfo]] = {}
    for i, (mi, fi) in funcs.items():
        outs: List[FuncInfo] = []
        extra: List[FuncInfo] = []
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            target = tables.resolve_call(mi, fi.qualname, node.func)
            if target is not None:
                outs.append(target)
            for arg in node.args:
                extra.extend(_resolve_ref(tables, mi, fi.qualname, arg))
            ref = _callable_ref(node)
            if ref is not None:
                fn = pkg.resolve(mi.name, ref[0])
                if fn is not None:
                    extra.append(fn)
        strict[i] = outs
        wide[i] = outs + extra
    return strict, wide


def _reachability(wide: Dict[int, List[FuncInfo]],
                  roots: List[Tuple[str, bool, FuncInfo]]
                  ) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """id(FuncInfo) -> set of root keys reaching it; plural root keys."""
    reach: Dict[int, Set[str]] = {}
    plural: Set[str] = set()
    for key, is_plural, entry in roots:
        if is_plural:
            plural.add(key)
        stack = [entry]
        while stack:
            fi = stack.pop()
            seen = reach.setdefault(id(fi), set())
            if key in seen:
                continue
            seen.add(key)
            stack.extend(wide.get(id(fi), ()))
    return reach, plural


# ---------------------------------------------------------------------------
# lock effects: transitive acquires, transitive blocking
# ---------------------------------------------------------------------------


def _blocking_kind(mi: ModuleInfo, node: ast.Call) -> Optional[str]:
    """Name of the blocking operation this call performs, or None.
    Timeout-less Condition.wait is handled separately (it needs held-
    lock context)."""
    if isinstance(node.func, ast.Name):
        if node.func.id == "open":
            return "file I/O (open)"
        if node.func.id == "sleep" \
                and mi.imports.get("sleep", ("", ""))[0] == "time":
            return "time.sleep"
        return None
    chain = _attr_chain(node.func)
    if not chain:
        return None
    if chain == ("time", "sleep"):
        return "time.sleep"
    if chain[0] == "jax" and chain[-1] == "device_get":
        return "jax.device_get (host sync)"
    if chain[-1] == "block_until_ready":
        return "block_until_ready (host sync)"
    if chain[-1] == "result":
        return "Future.result"
    if chain[-1] in _SOCKET_OPS:
        return f"socket I/O (.{chain[-1]})"
    if chain[0] == "subprocess":
        return f"subprocess.{chain[-1]}"
    if chain[-1] == "urlopen":
        return "urllib urlopen"
    if chain[-1] == "join" and not node.args and not node.keywords:
        return "thread join"
    return None


class _FuncEffects:
    """Per-function lexical walk results."""

    def __init__(self) -> None:
        self.acquires: Set[str] = set()          # direct known locks
        self.blocking: List[Tuple[int, str]] = []  # direct, any context
        # (held-lock, acquired-lock, line) lexical nesting edges
        self.edges: List[Tuple[str, str, int]] = []
        # (line, kind, held-lock) blocking ops under a KNOWN lock
        self.blocked_under: List[Tuple[int, str, str]] = []
        # (line, callee FuncInfo, held-locks tuple) calls under a lock
        self.calls_under: List[Tuple[int, FuncInfo, Tuple[str, ...]]] = []
        # write sites: (attr, line, guarded)
        self.writes: List[Tuple[str, int, bool]] = []
        # condition misuse: (line, message)
        self.cond_misuse: List[Tuple[int, str]] = []


def _scan_function(tables: _Tables, mi: ModuleInfo,
                   fi: FuncInfo) -> _FuncEffects:
    cls = tables.enclosing_class(mi, fi.qualname)
    eff = _FuncEffects()

    def guarded_by_annotation(lineno: int) -> bool:
        for ln in (lineno, lineno - 1):
            if 1 <= ln <= len(mi.lines) \
                    and _GUARDED_RE.search(mi.lines[ln - 1]):
                return True
        return False

    def handle_call(node: ast.Call, held: Tuple[str, ...],
                    any_guard: bool, loops: Tuple[str, ...]) -> None:
        # --- acquisition events (with-less .acquire) ---
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "acquire":
            nonblocking = any(
                kw.arg == "blocking" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in node.keywords) \
                or (node.args and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value is False)
            lock, _ = tables.lock_id(mi, cls, node.func.value)
            if lock is not None and not nonblocking:
                eff.acquires.add(lock)
                for h in held:
                    if h != lock:
                        eff.edges.append((h, lock, node.lineno))
            return
        # --- condition rules ---
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("wait", "notify", "notify_all"):
            cond = tables.condition_attr(mi, cls, node.func.value)
            if cond is not None:
                if node.func.attr == "wait":
                    if not loops or loops[-1] != "while":
                        eff.cond_misuse.append((
                            node.lineno,
                            "Condition.wait not inside a while-predicate "
                            "loop: wakeups are spurious and the predicate "
                            "must be re-checked before proceeding"))
                    timeout_less = not node.args and not node.keywords
                    others = [h for h in held if h != cond]
                    if timeout_less and others:
                        eff.blocked_under.append((
                            node.lineno,
                            "timeout-less Condition.wait", others[-1]))
                else:
                    if cond not in held:
                        eff.cond_misuse.append((
                            node.lineno,
                            f"{node.func.attr}() without holding the "
                            "condition: a waiter checking its predicate "
                            "concurrently can miss the wakeup"))
                return
        # --- blocking ops ---
        kind = _blocking_kind(mi, node)
        if kind is not None:
            eff.blocking.append((node.lineno, kind))
            if held:
                eff.blocked_under.append((node.lineno, kind, held[-1]))
            return
        # --- calls: order edges + blocking through callees ---
        target = tables.resolve_call(mi, fi.qualname, node.func)
        if target is not None and held:
            eff.calls_under.append((node.lineno, target, held))

    def walk(node: ast.AST, held: Tuple[str, ...], any_guard: bool,
             loops: Tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            c_held, c_guard, c_loops = held, any_guard, loops
            if isinstance(child, ast.With):
                for item in child.items:
                    lock, is_guard = tables.lock_id(
                        mi, cls, item.context_expr)
                    if lock is not None:
                        eff.acquires.add(lock)
                        for h in c_held:
                            if h != lock:
                                eff.edges.append((h, lock, child.lineno))
                        c_held = c_held + (lock,)
                        c_guard = True
                    elif is_guard:
                        c_guard = True
            elif isinstance(child, ast.While):
                c_loops = loops + ("while",)
            elif isinstance(child, ast.For):
                c_loops = loops + ("for",)
            elif isinstance(child, ast.Call):
                handle_call(child, c_held, c_guard, c_loops)
            elif isinstance(child, (ast.Assign, ast.AugAssign)):
                targets = (child.targets if isinstance(child, ast.Assign)
                           else [child.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        g = (bool(c_held) or c_guard
                             or guarded_by_annotation(child.lineno))
                        eff.writes.append((t.attr, child.lineno, g))
            walk(child, c_held, c_guard, c_loops)

    walk(fi.node, (), False, ())
    return eff


# ---------------------------------------------------------------------------
# rule evaluation over the whole package
# ---------------------------------------------------------------------------


def _transitive(strict: Dict[int, List[FuncInfo]],
                effects: Dict[int, _FuncEffects],
                funcs: Dict[int, Tuple[ModuleInfo, FuncInfo]]
                ) -> Tuple[Dict[int, Set[str]], Dict[int, Optional[str]]]:
    """(transitive lock-acquire sets, transitive blocking kind) per
    function — fixpoint over the call graph."""
    acq: Dict[int, Set[str]] = {i: set(e.acquires)
                                for i, e in effects.items()}
    blk: Dict[int, Optional[str]] = {
        i: (e.blocking[0][1] if e.blocking else None)
        for i, e in effects.items()}
    callees: Dict[int, List[int]] = {
        i: [id(t) for t in outs if id(t) in effects]
        for i, outs in strict.items()}
    changed = True
    while changed:
        changed = False
        for i, outs in callees.items():
            for j in outs:
                if not acq[j] <= acq[i]:
                    acq[i] |= acq[j]
                    changed = True
                if blk[i] is None and blk[j] is not None:
                    qual = funcs[j][1].qualname
                    blk[i] = f"{blk[j]} via {qual}"
                    changed = True
    return acq, blk


def _run_rules(pkg: Package) -> List[Finding]:
    tables = _Tables(pkg)
    funcs: Dict[int, Tuple[ModuleInfo, FuncInfo]] = {}
    effects: Dict[int, _FuncEffects] = {}
    for mi in pkg.modules.values():
        for fi in set(mi.funcs.values()):
            if id(fi) not in effects:
                funcs[id(fi)] = (mi, fi)
                effects[id(fi)] = _scan_function(tables, mi, fi)
    strict, wide = _call_graphs(tables, funcs)
    roots = _collect_roots(tables)
    reach, plural = _reachability(wide, roots)
    acq, blk = _transitive(strict, effects, funcs)

    findings: List[Finding] = []

    # ---- unguarded-shared-state --------------------------------------
    # group write sites per (module, class, attr)
    writes: Dict[Tuple[str, str, str],
                 List[Tuple[ModuleInfo, FuncInfo, int, bool]]] = {}
    for i, (mi, fi) in funcs.items():
        name = fi.qualname.rsplit(".", 1)[-1]
        if name in ("__init__", "__new__", "__post_init__"):
            continue
        cls = tables.enclosing_class(mi, fi.qualname)
        if cls is None:
            continue
        # handler instances are per-connection (one thread each):
        # attributes on the handler itself are thread-local state
        if cls in tables.scans[mi.name].handler_classes:
            continue
        for attr, line, guarded in effects[i].writes:
            writes.setdefault((mi.name, cls, attr), []).append(
                (mi, fi, line, guarded))
    for (mod, cls, attr), sites in sorted(writes.items()):
        site_roots: Set[str] = set()
        for _mi, fi, _line, _g in sites:
            site_roots |= reach.get(id(fi), set())
        shared = (len(site_roots) >= 2
                  or bool(site_roots & plural))
        if not shared:
            continue
        for mi, fi, line, guarded in sites:
            if guarded or not reach.get(id(fi)):
                continue
            ex = sorted(site_roots)[0]
            findings.append(Finding(
                mi.path, line, "unguarded-shared-state",
                f"'self.{attr}' is written from {len(site_roots)} thread "
                f"root(s) (e.g. {ex}) with no lock held at this write; "
                "take the class lock, or annotate '# guarded by <lock>' "
                "naming the guard the scan cannot see",
                fi.qualname))

    # ---- lock-order-cycle --------------------------------------------
    # graph: lexical nesting edges + (held -> callee's transitive
    # acquires) at every call made with a lock held
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for i, (mi, fi) in funcs.items():
        for a, b, line in effects[i].edges:
            edges.setdefault((a, b), (mi.path, line, fi.qualname))
        for line, target, held in effects[i].calls_under:
            for b in acq.get(id(target), ()):
                for a in held:
                    if a != b:
                        edges.setdefault((a, b),
                                         (mi.path, line, fi.qualname))
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    def path_between(src: str, dst: str) -> Optional[List[str]]:
        seen = {src}
        trail = [[src]]
        while trail:
            cur = trail.pop()
            if cur[-1] == dst:
                return cur
            for nxt in sorted(graph.get(cur[-1], ())):
                if nxt not in seen:
                    seen.add(nxt)
                    trail.append(cur + [nxt])
        return None

    reported: Set[frozenset] = set()
    for (a, b) in sorted(edges):
        back = path_between(b, a)
        if back is None:
            continue
        cyc = frozenset(back)
        if cyc in reported:
            continue
        reported.add(cyc)
        path, line, qual = edges[(a, b)]
        loop = " -> ".join([a, b] + back[1:])
        findings.append(Finding(
            path, line, "lock-order-cycle",
            f"lock acquisition order cycle: {loop}; threads taking "
            "these locks in different orders can deadlock — pick one "
            "global order (document it where the locks are created)",
            qual))

    # ---- blocking-under-lock -----------------------------------------
    for i, (mi, fi) in funcs.items():
        if not reach.get(i):
            continue               # outside the concurrent region
        for line, kind, lock in effects[i].blocked_under:
            findings.append(Finding(
                mi.path, line, "blocking-under-lock",
                f"{kind} while holding {lock}: every thread queued on "
                "that lock inherits this stall (p99/liveness hazard); "
                "move the slow work outside the critical section",
                fi.qualname))
        for line, target, held in effects[i].calls_under:
            tb = blk.get(id(target))
            if tb is None:
                continue
            findings.append(Finding(
                mi.path, line, "blocking-under-lock",
                f"call into {target.qualname} (does {tb}) while holding "
                f"{held[-1]}: every thread queued on that lock inherits "
                "the stall; move the slow work outside the critical "
                "section", fi.qualname))

    # ---- condition-misuse --------------------------------------------
    for i, (mi, fi) in funcs.items():
        if not reach.get(i):
            continue
        for line, msg in effects[i].cond_misuse:
            findings.append(Finding(
                mi.path, line, "condition-misuse", msg, fi.qualname))

    return findings


# ---------------------------------------------------------------------------
# driver (mirrors lint.py's lint_run/lint_paths contract)
# ---------------------------------------------------------------------------


def lint_run(paths: Sequence[str], root: str,
             allowlist: Optional[Dict[Tuple[str, str, str], str]] = None,
             used_allowlist: Optional[Set[Tuple[str, str, str]]] = None,
             check_stale: bool = True
             ) -> Tuple[List[Finding], List[str]]:
    """Run the threadlint rules over `paths`; returns (unsuppressed
    findings, stale allowlist entries).  Suppressions use the shared
    ``# graftlint: allow(rule) — reason`` grammar; reason-less
    suppressions surface as ``suppression`` findings, exactly like
    lint.py.  The stale audit only judges threadlint-rule entries
    (lint.py audits its own) and, like lint.py, is only valid on
    whole-package runs."""
    pkg = Package(root)
    for p in paths:
        if os.path.isdir(p):
            pkg.add_tree(p)
        else:
            pkg.add_file(p)
    allowlist = allowlist or {}
    used: Set[Tuple[str, str, str]] = (used_allowlist
                                      if used_allowlist is not None
                                      else set())
    raw = _run_rules(pkg)

    seen: Set[Tuple[str, int, str, str]] = set()
    findings: List[Finding] = []
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        key = (f.path, f.line, f.rule, f.message)
        if key in seen:
            continue
        seen.add(key)
        mi = next(m for m in pkg.modules.values() if m.path == f.path)
        sup = _suppressions_for(mi.lines, f.line)
        if sup is not None and f.rule in sup[0]:
            if not sup[1]:
                findings.append(Finding(
                    f.path, f.line, "suppression",
                    f"graftlint: allow({f.rule}) has no reason; "
                    "suppressions must say why (\"# graftlint: "
                    "allow(rule) — reason\")", f.qualname))
            continue
        wl = allowlist.get((f.path, f.rule, f.qualname))
        if wl is not None:
            used.add((f.path, f.rule, f.qualname))
            if wl:
                continue
            findings.append(Finding(
                f.path, f.line, "suppression",
                "allowlist entry has no reason", f.qualname))
            continue
        findings.append(f)
    stale: List[str] = []
    if check_stale:
        mine = {k: v for k, v in allowlist.items() if k[1] in RULES}
        linted = {m.path for m in pkg.modules.values()}
        stale = stale_allowlist_entries(mine, used, linted, root)
    return findings, stale


def lint_paths(paths: Sequence[str], root: str,
               allowlist: Optional[Dict[Tuple[str, str, str], str]] = None,
               used_allowlist: Optional[Set[Tuple[str, str, str]]] = None
               ) -> List[Finding]:
    findings, _stale = lint_run(paths, root, allowlist,
                                used_allowlist=used_allowlist,
                                check_stale=False)
    return findings
