"""Deterministic fault injection for the train/serve/online seams.

Robustness claims ("a killed daemon resumes", "the fleet survives a
throwing replica") are only as good as the failures they were tested
against.  This registry lets the chaos suite (tests/test_faults.py) and
``scripts/bench_chaos.py`` inject failures at NAMED SEAMS in the product
code, deterministically:

- every seam is a (site, sequence) pair — the Nth time execution
  reaches ``site`` — with no wall clock and no global RNG involved
  (consistent with graftlint's nondeterminism rule: a chaos run is
  exactly reproducible from its spec string);
- the product code carries one cheap call per seam (``check(site)`` /
  ``fire(site)``); with nothing armed the cost is one dict check and
  the registry never allocates;
- torn-write seams (``torn_write``/``torn_copy``) simulate a process
  dying mid-write: HALF the payload lands at the destination path, then
  ``InjectedFault`` raises — the caller's recovery path, not its happy
  path, meets the file.

Arming:

- environment: ``LIGHTGBM_TPU_FAULTS="site:1,3;other:2-4;every.hit:*"``
  (read once at import; ``arm_from_env()`` re-reads);
- programmatic: ``faults.arm("serve.dispatch.r0:1-3")`` — tests arm and
  ``reset()`` around each scenario.

Spec grammar: ``site:seqs`` groups separated by ``;``; ``seqs`` is a
comma list of 1-based sequence numbers and ``a-b`` ranges, or ``*``
(every hit).  A bare ``site`` means ``site:*``.

Sites wired into the package (docs/Robustness.md has the full table):

- ``train.checkpoint`` — checkpoint file torn mid-write, then crash
- ``train.after_checkpoint`` — crash just after a checkpoint landed
- ``serve.dispatch`` / ``serve.dispatch.r<N>`` — replica dispatch (any /
  replica N) raises before executing
- ``route.backend`` / ``route.backend.b<N>`` — router→backend round-trip
  (any / backend N) raises before connecting: covers proxied requests,
  health probes, and stats fetches
- ``online.before_publish`` — crash after refresh compute, before the
  model/meta renames
- ``online.publish_model`` — published model file torn mid-write, crash
- ``online.between_renames`` — crash after the model rename, before the
  meta rename (resolved by the intent's staged-model sha1)
- ``online.after_publish`` — crash after the renames, before the state
  sidecar flush (the publish-intent recovery case)
- ``online.state_write`` — daemon state sidecar torn mid-write, crash
- ``traffic.append`` — traffic-log record torn mid-append, crash
"""
from __future__ import annotations

import os
import threading
from typing import Dict, FrozenSet, Optional, Union

ENV_VAR = "LIGHTGBM_TPU_FAULTS"


class InjectedFault(Exception):
    """An injected failure (simulated crash/exception at a named seam)."""

    def __init__(self, site: str, seq: int):
        super().__init__(f"injected fault at {site} (hit #{seq})")
        self.site = site
        self.seq = seq


_lock = threading.Lock()
# site -> armed sequence numbers (frozenset), or None meaning EVERY hit
_plan: Dict[str, Optional[FrozenSet[int]]] = {}
_hits: Dict[str, int] = {}
_fired: Dict[str, int] = {}


def parse_spec(spec: str) -> Dict[str, Optional[FrozenSet[int]]]:
    """``"a:1,3-5;b:*;c"`` -> {"a": {1,3,4,5}, "b": None, "c": None}."""
    plan: Dict[str, Optional[FrozenSet[int]]] = {}
    for group in spec.replace("\n", ";").split(";"):
        group = group.strip()
        if not group:
            continue
        site, _, seqs = group.partition(":")
        site = site.strip()
        if not site:
            raise ValueError(f"empty site in fault spec group {group!r}")
        seqs = seqs.strip()
        if not seqs or seqs == "*":
            plan[site] = None
            continue
        nums = set()
        for part in seqs.split(","):
            part = part.strip()
            if not part:
                continue
            a, _, b = part.partition("-")
            lo = int(a)
            hi = int(b) if b else lo
            if lo < 1 or hi < lo:
                raise ValueError(f"bad sequence range {part!r} in fault "
                                 f"spec for site {site!r} (1-based)")
            nums.update(range(lo, hi + 1))
        prev = plan.get(site)
        if site in plan and prev is None:
            continue                       # "*" already covers everything
        plan[site] = frozenset(nums | set(prev or ()))
    return plan


def arm(spec: Union[str, Dict[str, Optional[FrozenSet[int]]]]) -> None:
    """Merge a spec into the active plan (hit counters keep running)."""
    plan = parse_spec(spec) if isinstance(spec, str) else dict(spec)
    with _lock:
        for site, seqs in plan.items():
            existing = _plan.get(site, frozenset())
            if seqs is None or existing is None:
                _plan[site] = None
            else:
                _plan[site] = frozenset(existing | seqs)


def arm_from_env() -> bool:
    """(Re)arm from ``LIGHTGBM_TPU_FAULTS``; True iff a spec was found."""
    spec = os.environ.get(ENV_VAR, "")
    if not spec:
        return False
    arm(spec)
    return True


def disarm() -> None:
    """Drop the plan; hit counters keep their values (reset() clears)."""
    with _lock:
        _plan.clear()


def reset() -> None:
    """Clear plan AND counters — call between chaos scenarios."""
    with _lock:
        _plan.clear()
        _hits.clear()
        _fired.clear()


def armed() -> bool:
    return bool(_plan)


def fire(site: str) -> bool:
    """Record one hit at ``site``; True iff this (site, sequence) is
    armed.  The sequence is 1-based and counted whether or not a plan
    is active, so a spec armed mid-run still addresses hits by absolute
    sequence — use reset() for a fresh numbering."""
    if not _plan:
        return False                       # fast path: nothing armed
    with _lock:
        if site not in _plan:
            return False
        seq = _hits.get(site, 0) + 1
        _hits[site] = seq
        seqs = _plan[site]
        hit = seqs is None or seq in seqs
        if hit:
            _fired[site] = _fired.get(site, 0) + 1
    if hit:
        # a firing is an operator-relevant incident: it lands in the
        # span stream (under the current trace when one is active) so a
        # chaos run's timeline shows WHICH request/refresh met the
        # injected failure.  Emitted outside the lock; import is local
        # because this module must stay importable with zero deps.
        from .. import telemetry
        telemetry.event("fault.fired", site=site, seq=seq)
    return hit


def check(site: str) -> None:
    """Raise InjectedFault when this hit of ``site`` is armed."""
    if fire(site):
        raise InjectedFault(site, _hits.get(site, 0))


def torn_write(site: str, path: str, payload: Union[str, bytes]) -> None:
    """Torn-write seam: when this hit of ``site`` is armed, write HALF
    of ``payload`` to ``path`` (simulating a crash mid-write of the
    destination file) and raise InjectedFault.  No-op otherwise — the
    caller proceeds with its normal (atomic) write."""
    if not fire(site):
        return
    data = payload.encode() if isinstance(payload, str) else payload
    with open(path, "wb") as f:
        f.write(data[: max(1, len(data) // 2)])
    raise InjectedFault(site, _hits.get(site, 0))


def torn_copy(site: str, src: str, dst: str) -> None:
    """Like torn_write, but the payload is the current content of
    ``src`` (for writers that stage through a file, e.g. model saves)."""
    if not fire(site):
        return
    with open(src, "rb") as f:
        data = f.read()
    with open(dst, "wb") as f:
        f.write(data[: max(1, len(data) // 2)])
    raise InjectedFault(site, _hits.get(site, 0))


def hits(site: str) -> int:
    with _lock:
        return _hits.get(site, 0)


def fired(site: str) -> int:
    with _lock:
        return _fired.get(site, 0)


def snapshot() -> Dict[str, Dict[str, int]]:
    """Per-site {hits, fired} — the chaos bench's evidence block."""
    with _lock:
        sites = set(_hits) | set(_fired) | set(_plan)
        return {s: {"hits": _hits.get(s, 0), "fired": _fired.get(s, 0)}
                for s in sorted(sites)}


arm_from_env()
