"""Runtime lock sanitizer for the threaded serving plane.

threadlint (threadlint.py) proves what the AST can see about the
free-form ``threading`` code that replaced the reference core's OpenMP
structure — lock-order cycles, unguarded shared state, blocking calls
under a lock; this module closes over what it cannot: the acquisition
orders and contention the fleet ACTUALLY exhibits under load.  It is to
threadlint what ``DivergenceSanitizer`` is to shardlint and
``HotPathSanitizer`` to graftlint.

The shim follows the faults.py arming model: an opt-in registry whose
cost when disarmed is zero — the ``lock()`` / ``rlock()`` /
``condition()`` factories check one module flag at CREATION time and
hand back the plain stdlib primitive when off, so a disarmed serving
process runs the exact objects it always did (no wrapper, no dict
check per acquire).  Armed (``LIGHTGBM_TPU_LOCKSAN=1`` or
``BENCH_SANITIZE=1``, read at import; ``arm()`` programmatically), each
factory returns an instrumented wrapper that records:

- the per-thread HELD-LOCK STACK and the global acquisition-order
  graph: acquiring B while holding A inserts the edge A→B; an edge
  whose reverse path already exists is a lock-ORDER CYCLE — the latent
  ABBA deadlock — counted in ``sanitize/lock_cycles`` with the witness
  path kept in ``cycles()``.  Detection happens at edge-insert time,
  BEFORE blocking on the inner lock, so a would-deadlock acquire still
  reports its cycle.
- contention: an acquire that finds the lock busy counts one
  ``sanitize/lock_waits`` and lands its wait in the
  ``sanitize/lock_wait_ms`` reservoir (per-lock labeled series ride
  the same base name);
- hold time: outermost release lands in ``sanitize/lock_hold_ms``.

Counters flow through the always-on profiling registry, so
``HotPathSanitizer`` windows them (report()/check()), /stats and
/metrics expose them, and every BENCH_SANITIZE=1 serving bench
(bench_serve.py, bench_serve_mt.py, bench_router.py, bench_chaos.py)
asserts ``lock_cycles == 0`` beside the 0-retrace/0-transfer contract.

Non-blocking acquires (``acquire(blocking=False)``) insert no order
edges — a try-lock cannot deadlock, matching threadlint's exclusion of
them from the static acquisition graph (registry._shadow_verdict).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from .. import profiling
from .sanitize import (LOCK_ACQUIRES, LOCK_CYCLES, LOCK_HOLD_MS,
                       LOCK_WAIT_MS, LOCK_WAITS)

ENV_VAR = "LIGHTGBM_TPU_LOCKSAN"

_armed = False

# sanitizer-internal state; _meta guards the order graph and evidence.
# _meta is ALWAYS innermost (nothing is acquired under it), so the
# sanitizer cannot itself create an ordering hazard.
_meta = threading.Lock()
_edges: Dict[str, Set[str]] = {}           # a -> {b}: b acquired under a
_edge_sites: Dict[Tuple[str, str], str] = {}   # first witness per edge
_cycles: List[dict] = []                   # bounded evidence
_tls = threading.local()                   # .stack: [(name, t_acquire)]


def arm() -> None:
    """Make the factories hand out instrumented locks from now on.
    Locks created while disarmed stay plain — arm before the stack is
    built (the serving entry points read the env at import)."""
    global _armed
    _armed = True


def disarm() -> None:
    global _armed
    _armed = False


def armed() -> bool:
    return _armed


def arm_from_env(env: str = ENV_VAR) -> bool:
    """(Re)arm from ``LIGHTGBM_TPU_LOCKSAN`` (the chip-queue flag) or
    ``BENCH_SANITIZE`` (every sanitized bench window); True iff armed."""
    on = any(os.environ.get(v, "0") not in ("0", "", "false")
             for v in (env, "BENCH_SANITIZE"))
    if on:
        arm()
    return on


def reset() -> None:
    """Clear the order graph and evidence (between test scenarios).
    Per-thread held stacks are left alone — callers must not reset
    while locks are held."""
    with _meta:
        _edges.clear()
        _edge_sites.clear()
        _cycles.clear()


def cycles() -> List[dict]:
    """Witnessed lock-order cycles: {"edge": (a, b), "path": [...],
    "thread": name} — the evidence block serving benches embed."""
    with _meta:
        return list(_cycles)


def order_graph() -> Dict[str, Set[str]]:
    with _meta:
        return {a: set(bs) for a, bs in _edges.items()}


def _stack() -> List[Tuple[str, float]]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _path(src: str, dst: str) -> Optional[List[str]]:
    """A path src→…→dst in the order graph, or None.  Caller holds
    _meta.  Iterative DFS — the graph is a handful of named locks."""
    seen = {src}
    trail = [[src]]
    while trail:
        cur = trail.pop()
        node = cur[-1]
        if node == dst:
            return cur
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                trail.append(cur + [nxt])
    return None


def _note_acquired(name: str) -> None:
    """Held-stack + order-graph bookkeeping for one OUTERMOST acquire
    intent.  Called before blocking so a real deadlock still reports."""
    st = _stack()
    profiling.count(LOCK_ACQUIRES)
    if st:
        with _meta:
            for held, _t0 in st:
                if held == name or name in _edges.get(held, ()):
                    continue
                # new edge held→name: a reverse path name→…→held in the
                # existing graph means some thread acquires in the
                # opposite order — a lock-order cycle
                back = _path(name, held)
                _edges.setdefault(held, set()).add(name)
                if back is not None:
                    profiling.count(LOCK_CYCLES)
                    if len(_cycles) < 32:
                        _cycles.append({
                            "edge": (held, name),
                            "path": back + [name],
                            "thread": threading.current_thread().name})


class _SanLock:
    """Instrumented Lock/RLock wrapper.  Exposes the stdlib lock
    interface plus the ``_release_save`` / ``_acquire_restore`` /
    ``_is_owned`` hooks, so a ``threading.Condition`` built ON a
    sanitized rlock keeps RLock recursion AND routes its wait-time
    release/reacquire through the sanitizer's bookkeeping."""

    def __init__(self, name: str, inner, reentrant: bool):
        self.name = name
        self._inner = inner
        self._reentrant = reentrant

    # -- core interface -------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        depth = getattr(_tls, "depth", None)
        if depth is None:
            depth = _tls.depth = {}
        d = depth.get(self.name, 0)
        if d and self._reentrant:          # re-entry: no new edges
            got = self._inner.acquire(blocking, timeout)
            if got:
                depth[self.name] = d + 1
            return got
        if not blocking:
            # a try-lock cannot deadlock: no order edges, no wait
            got = self._inner.acquire(False)
            if got:
                profiling.count(LOCK_ACQUIRES)
                depth[self.name] = d + 1
                _stack().append((self.name, time.perf_counter()))
            return got
        _note_acquired(self.name)
        t0 = time.perf_counter()
        got = self._inner.acquire(False)
        if not got:
            profiling.count(LOCK_WAITS)
            got = self._inner.acquire(True, timeout)
            wait_ms = (time.perf_counter() - t0) * 1000.0
            profiling.observe(LOCK_WAIT_MS, wait_ms)
            profiling.observe(
                profiling.labeled(LOCK_WAIT_MS, lock=self.name), wait_ms)
        if got:
            depth[self.name] = d + 1
            _stack().append((self.name, time.perf_counter()))
        return got

    def release(self) -> None:
        depth = getattr(_tls, "depth", {})
        d = depth.get(self.name, 0)
        self._inner.release()
        if d > 1:
            depth[self.name] = d - 1
            return
        depth.pop(self.name, None)
        st = _stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == self.name:
                _name, t0 = st.pop(i)
                hold_ms = (time.perf_counter() - t0) * 1000.0
                profiling.observe(LOCK_HOLD_MS, hold_ms)
                profiling.observe(
                    profiling.labeled(LOCK_HOLD_MS, lock=self.name),
                    hold_ms)
                break

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition integration ------------------------------------------
    # threading.Condition lifts these from its lock when present; the
    # inner rlock's versions handle recursion state, the wrapper keeps
    # the held-stack honest across the wait's release/reacquire window.
    def _release_save(self):
        # Condition.wait drops ALL recursion levels at once: clear the
        # wrapper bookkeeping first, then delegate the real release to
        # the inner lock in one shot
        depth = getattr(_tls, "depth", {})
        depth.pop(self.name, None)
        st = _stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == self.name:
                _name, t0 = st.pop(i)
                hold_ms = (time.perf_counter() - t0) * 1000.0
                profiling.observe(LOCK_HOLD_MS, hold_ms)
                profiling.observe(
                    profiling.labeled(LOCK_HOLD_MS, lock=self.name),
                    hold_ms)
                break
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        depth = getattr(_tls, "depth", None)
        if depth is None:
            depth = _tls.depth = {}
        _note_acquired(self.name)
        depth[self.name] = depth.get(self.name, 0) + 1
        _stack().append((self.name, time.perf_counter()))

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def lock(name: str):
    """A named mutex: plain ``threading.Lock`` when disarmed, the
    instrumented shim when armed.  ``name`` keys the order graph and
    the per-lock labeled hold/wait series ("serve.batcher",
    "route.server", …) — keep it identifier-shaped."""
    if not _armed:
        return threading.Lock()
    return _SanLock(name, threading.Lock(), reentrant=False)


def rlock(name: str):
    if not _armed:
        return threading.RLock()
    return _SanLock(name, threading.RLock(), reentrant=True)


def condition(name: str):
    """A named ``threading.Condition``: the stdlib one (over its
    default RLock) when disarmed, one built on an instrumented rlock
    when armed — waiters' release/reacquire flows through the shim via
    the ``_release_save``/``_acquire_restore`` hooks."""
    if not _armed:
        return threading.Condition()
    return threading.Condition(rlock(name))


def check() -> None:
    """Assert NO lock-order cycles were witnessed process-wide.  The
    serving benches call this after printing their JSON (so the
    evidence always lands in the chip-queue log first) — the runtime
    half of the 0-retrace/0-transfer steady-state contract."""
    cyc = cycles()
    assert not cyc, (
        f"LockSanitizer: {len(cyc)} lock-order cycle(s) witnessed "
        f"(latent ABBA deadlock): {cyc[:4]}")


def report() -> dict:
    """JSON-ready evidence block (the serving benches embed this
    beside HotPathSanitizer.report())."""
    with _meta:
        return {
            "armed": _armed,
            "locks": sorted(set(_edges)
                            | {b for bs in _edges.values() for b in bs}),
            "order_edges": sorted((a, b) for a, bs in _edges.items()
                                  for b in bs),
            "cycles": list(_cycles[:8]),
        }


arm_from_env()
