"""Hot-path diagnostics: graftlint static analysis (`lint`) and the
runtime retrace/transfer sanitizer (`sanitize`).

`lint` is stdlib-only (no jax import) so the CI gate stays cheap;
`sanitize` imports jax lazily inside the context manager.
"""
