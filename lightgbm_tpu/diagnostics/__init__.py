"""Hot-path diagnostics: graftlint static analysis (`lint`), the
runtime retrace/transfer sanitizer (`sanitize`), and the deterministic
fault-injection registry (`faults`).

`lint` and `faults` are stdlib-only (no jax import) so the CI gate and
the fault seams stay cheap; `sanitize` imports jax lazily inside the
context manager.
"""
