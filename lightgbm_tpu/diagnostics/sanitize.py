"""Runtime retrace/transfer sanitizer for the training and serving hot
paths.

graftlint (lint.py) catches the hazards the AST can see; this module
catches the ones only the runtime can: a jitted builder silently
retracing across boosting iterations (each retrace is seconds of XLA
compile on the TPU queue), and implicit host↔device transfers sneaking
into the pipelined loop (each one a dispatch stall — the dominant
scaling tax of accelerator tree boosting, arXiv:1706.08359 §5).

Two mechanisms, wrapped in one context manager:

- ``jax.transfer_guard(guard)`` around the loop: with the default
  ``"disallow"``, any IMPLICIT transfer raises at the violating dispatch
  while the explicit APIs (``jax.device_put`` / ``jax.device_get``) the
  fixed hot path uses stay legal.  Violations caught at ``step()``
  granularity increment ``sanitize/implicit_transfers``.
- compilation-event capture via ``jax_log_compiles``: a logging handler
  on the ``jax`` logger counts "Compiling <name>" records per step;
  compiles after the declared warmup increment ``sanitize/retraces``.

Counters land in the always-on profiling registry
(``sanitize/retraces``, ``sanitize/implicit_transfers``,
``sanitize/compiles_total``), so bench.py records them in its JSON line
and the /stats endpoint can expose them.  ``BENCH_SANITIZE=1`` modes in
bench.py / scripts/bench_serve.py / scripts/profile_hotpath.py and the
MULTICHIP dryrun gate assert both are zero after warmup.

Backend caveat: the guard is enforced by the backend's dispatch layer
and is a no-op for some transfer directions on some platforms (e.g.
device→host on the CPU backend is zero-copy and never fires).  Probe
with ``transfer_guard_effective()``; tests that require the guard carry
the ``sanitize`` pytest marker so they can be deselected where it is
inert.
"""
from __future__ import annotations

import contextlib
import logging
from contextlib import contextmanager
from typing import Iterator, Optional

from .. import profiling

RETRACES = "sanitize/retraces"
IMPLICIT_TRANSFERS = "sanitize/implicit_transfers"
COMPILES_TOTAL = "sanitize/compiles_total"

# Retrace signal: "Finished tracing + transforming <name> for pjit" fires
# on every (re)trace, INCLUDING compiles served from the persistent
# compilation cache (which skip the "Compiling <name>" backend message
# entirely — counting only that one under-reports retraces whenever
# .jax_cache is warm).  A steady-state iteration emits neither.
_TRACE_MARKER = "Finished tracing + transforming "
_COMPILE_MARKER = "Compiling "


def sanitize_enabled(env: str = "BENCH_SANITIZE") -> bool:
    """One truthiness rule for the BENCH_SANITIZE gates (bench.py,
    scripts/bench_serve.py, scripts/profile_hotpath.py) so the three
    chip-queue entry points cannot diverge.  bench.py re-states the rule
    inline at module level because importing this package there would
    initialize jax before its backend-liveness probe."""
    import os
    return os.environ.get(env, "0") not in ("0", "", "false")


def _is_transfer_guard_error(e: BaseException) -> bool:
    msg = str(e)
    return "Disallowed" in msg and "transfer" in msg


def transfer_guard_effective() -> bool:
    """True when jax.transfer_guard("disallow") actually raises on an
    implicit host→device transfer on this backend (probe with an eager
    op whose scalar operand must be uploaded)."""
    import jax
    import jax.numpy as jnp
    if not hasattr(jax, "transfer_guard"):
        return False
    x = jnp.zeros(2)            # committed before the guard
    try:
        with jax.transfer_guard("disallow"):
            (x * 2.0).block_until_ready()
    except Exception as e:      # noqa: BLE001 — backend-specific error type
        return _is_transfer_guard_error(e)
    return False


class _CompileCounter(logging.Handler):
    """Counts trace events (the retrace signal — see _TRACE_MARKER) and
    backend compiles separately from the jax_log_compiles record
    stream.  One user-level retrace emits one-or-more trace records
    (inner pjits trace too); the contract asserted is ZERO, so the
    event count being an upper bound is fine and the captured names
    point at the offending program."""

    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.count = 0               # trace events (retrace signal)
        self.compiles = 0            # backend "Compiling" events
        self.names = []

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:       # noqa: BLE001 — never break the hot path
            return
        if msg.startswith(_TRACE_MARKER):
            self.count += 1
            if len(self.names) < 64:     # bounded evidence for reports
                self.names.append(
                    msg[len(_TRACE_MARKER):].split(" for ")[0])
        elif msg.startswith(_COMPILE_MARKER):
            self.compiles += 1


class HotPathSanitizer:
    """Context manager asserting the zero-retrace / zero-implicit-
    transfer contract of a steady-state loop.

    Usage::

        with HotPathSanitizer(warmup=1) as san:
            for _ in range(iters):
                with san.step():
                    bst.update()
        assert san.retraces == 0 and san.implicit_transfers == 0

    ``warmup`` steps may compile freely (first call after a cold cache);
    compiles in any later step count as retraces.  A transfer-guard
    violation inside ``step()`` increments the counter and, with
    ``strict=False`` (default), is swallowed so one run can report the
    total instead of dying at the first violation — note the violating
    iteration's work is aborted mid-dispatch, so non-strict mode is for
    *measuring* breakage, not for training through it.
    """

    def __init__(self, warmup: int = 1, guard: str = "disallow",
                 strict: bool = False, label: str = "hot_path",
                 d2d_guard: str = "allow"):
        self.warmup = int(warmup)
        self.guard = guard
        # device→device resharding (e.g. the replicated gradient
        # scattering into a shard_map mesh) is legitimate SPMD traffic,
        # not the host-sync stall class this sanitizer hunts — allowed
        # by default, tightten via d2d_guard="disallow" to audit it too
        self.d2d_guard = d2d_guard
        self.strict = strict
        self.label = label
        self.steps = 0
        self.retraces = 0
        self.implicit_transfers = 0
        self.compiles_total = 0
        self.trace_events = 0
        self.compile_names = []
        self._handler: Optional[_CompileCounter] = None
        self._prev_log_compiles = None
        self._prev_propagate = None

    # -- lifecycle ------------------------------------------------------
    def __enter__(self) -> "HotPathSanitizer":
        import jax
        self._handler = _CompileCounter()
        lg = logging.getLogger("jax")
        lg.addHandler(self._handler)
        # capture without spraying WARNING-level compile logs to stderr
        self._prev_propagate = lg.propagate
        lg.propagate = False
        self._prev_log_compiles = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        import jax
        jax.config.update("jax_log_compiles", self._prev_log_compiles)
        lg = logging.getLogger("jax")
        lg.removeHandler(self._handler)
        lg.propagate = self._prev_propagate
        self.trace_events = self._handler.count
        self.compiles_total = self._handler.compiles
        self.compile_names = list(self._handler.names)
        profiling.count(RETRACES, self.retraces)
        profiling.count(IMPLICIT_TRANSFERS, self.implicit_transfers)
        profiling.count(COMPILES_TOTAL, self.compiles_total)
        return False

    # -- per-iteration accounting --------------------------------------
    @contextmanager
    def step(self) -> Iterator[None]:
        """One hot-loop iteration.  Warmup steps run UNGUARDED (a cold
        cache may legitimately compile, and compiling transfers
        constants); post-warmup steps run under the transfer guard and
        attribute compile events to retraces."""
        import jax
        before = self._handler.count
        guarded = (self.steps >= self.warmup
                   and hasattr(jax, "transfer_guard"))
        try:
            with contextlib.ExitStack() as stack:
                if guarded:
                    if hasattr(jax, "transfer_guard_host_to_device"):
                        stack.enter_context(
                            jax.transfer_guard_host_to_device(self.guard))
                        stack.enter_context(
                            jax.transfer_guard_device_to_host(self.guard))
                        stack.enter_context(
                            jax.transfer_guard_device_to_device(
                                self.d2d_guard))
                    else:       # older jax: one knob for all directions
                        stack.enter_context(jax.transfer_guard(self.guard))
                yield
        except Exception as e:   # noqa: BLE001 — classify, then re-raise
            if guarded and _is_transfer_guard_error(e):
                self.implicit_transfers += 1
                if self.strict:
                    raise
            else:
                raise
        finally:
            self.steps += 1
            new = self._handler.count - before
            if self.steps > self.warmup and new:
                self.retraces += new

    # -- reporting ------------------------------------------------------
    def report(self) -> dict:
        """JSON-ready summary (bench.py embeds this under "sanitize")."""
        return {
            "label": self.label,
            "guard": self.guard,
            "steps": self.steps,
            "warmup": self.warmup,
            "retraces_after_warmup": self.retraces,
            "implicit_transfers": self.implicit_transfers,
            "trace_events_total": self.trace_events,
            "compiles_total": self.compiles_total,
            # first offending program names — the evidence a regression
            # report needs to find the retracing call site
            "retrace_names": self.compile_names[-8:] if self.retraces else [],
        }

    def check(self) -> None:
        """Raise with a diagnostic when the zero/zero contract is broken."""
        if self.retraces or self.implicit_transfers:
            raise AssertionError(
                f"hot-path sanitizer [{self.label}]: "
                f"{self.retraces} retrace(s) and "
                f"{self.implicit_transfers} implicit transfer(s) after "
                f"{self.warmup} warmup step(s) over {self.steps} steps; "
                f"recent compiles: {self.compile_names[-8:]}")
