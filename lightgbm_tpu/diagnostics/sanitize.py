"""Runtime retrace/transfer sanitizer for the training and serving hot
paths.

graftlint (lint.py) catches the hazards the AST can see; this module
catches the ones only the runtime can: a jitted builder silently
retracing across boosting iterations (each retrace is seconds of XLA
compile on the TPU queue), and implicit host↔device transfers sneaking
into the pipelined loop (each one a dispatch stall — the dominant
scaling tax of accelerator tree boosting, arXiv:1706.08359 §5).

Two mechanisms, wrapped in one context manager:

- ``jax.transfer_guard(guard)`` around the loop: with the default
  ``"disallow"``, any IMPLICIT transfer raises at the violating dispatch
  while the explicit APIs (``jax.device_put`` / ``jax.device_get``) the
  fixed hot path uses stay legal.  Violations caught at ``step()``
  granularity increment ``sanitize/implicit_transfers``.
- compilation-event capture via ``jax_log_compiles``: a logging handler
  on the ``jax`` logger counts "Compiling <name>" records per step;
  compiles after the declared warmup increment ``sanitize/retraces``.

A third mechanism, ``DivergenceSanitizer``, is the runtime half of the
shardlint static rules (lint.py): under ``BENCH_SANITIZE=1`` both mesh
learners fingerprint the replicated growth-loop state (the packed tree
arrays and leaf counts — the materialization of the split records after
``combine_sharded_records``) on every device each iteration and
hard-fail on any cross-shard bitwise mismatch — the failure mode
``shard_map(..., check_vma=False)`` cannot see and a 2-D mesh turns
into a pod-wide deadlock.

Counters land in the always-on profiling registry
(``sanitize/retraces``, ``sanitize/implicit_transfers``,
``sanitize/compiles_total``, ``sanitize/divergence_checks``,
``sanitize/divergences``), so bench.py records them in its JSON line
and the /stats endpoint can expose them.  ``BENCH_SANITIZE=1`` modes in
bench.py / scripts/bench_serve.py / scripts/profile_hotpath.py and the
MULTICHIP dryrun gate assert all of them are zero after warmup (with
``divergence_checks > 0`` proving the divergence probe actually ran on
multi-device meshes).

Backend caveat: the guard is enforced by the backend's dispatch layer
and is a no-op for some transfer directions on some platforms (e.g.
device→host on the CPU backend is zero-copy and never fires).  Probe
with ``transfer_guard_effective()``; tests that require the guard carry
the ``sanitize`` pytest marker so they can be deselected where it is
inert.
"""
from __future__ import annotations

import contextlib
import logging
from contextlib import contextmanager
from typing import Iterator, Optional

from .. import profiling

RETRACES = "sanitize/retraces"
IMPLICIT_TRANSFERS = "sanitize/implicit_transfers"
COMPILES_TOTAL = "sanitize/compiles_total"
DIVERGENCE_CHECKS = "sanitize/divergence_checks"
DIVERGENCES = "sanitize/divergences"

# Lock-sanitizer counters (diagnostics/locksan.py — the runtime half of
# the threadlint static rules, the way DivergenceSanitizer is shardlint's):
#  - LOCK_ACQUIRES: outermost acquisitions seen by the instrumented shim
#    (>0 proves the shim was armed and actually on the benched path);
#  - LOCK_WAITS: acquisitions that found the lock busy and had to block
#    (the contention metric; the wait itself lands in LOCK_WAIT_MS);
#  - LOCK_CYCLES: lock-ORDER cycles detected at acquire time — a thread
#    acquired B-then-A after some thread established A-then-B.  The
#    serving benches assert this stays 0 (a nonzero value is a latent
#    ABBA deadlock that timing has not yet cashed in).
# LOCK_HOLD_MS / LOCK_WAIT_MS are bounded sample reservoirs (per-lock
# labeled series ride the same base names via profiling.labeled).
LOCK_ACQUIRES = "sanitize/lock_acquires"
LOCK_WAITS = "sanitize/lock_waits"
LOCK_CYCLES = "sanitize/lock_cycles"
LOCK_HOLD_MS = "sanitize/lock_hold_ms"
LOCK_WAIT_MS = "sanitize/lock_wait_ms"

# Retrace signal: "Finished tracing + transforming <name> for pjit" fires
# on every (re)trace, INCLUDING compiles served from the persistent
# compilation cache (which skip the "Compiling <name>" backend message
# entirely — counting only that one under-reports retraces whenever
# .jax_cache is warm).  A steady-state iteration emits neither.
_TRACE_MARKER = "Finished tracing + transforming "
_COMPILE_MARKER = "Compiling "


def sanitize_enabled(env: str = "BENCH_SANITIZE") -> bool:
    """One truthiness rule for the BENCH_SANITIZE gates (bench.py,
    scripts/bench_serve.py, scripts/profile_hotpath.py) so the three
    chip-queue entry points cannot diverge.  bench.py re-states the rule
    inline at module level because importing this package there would
    initialize jax before its backend-liveness probe."""
    import os
    return os.environ.get(env, "0") not in ("0", "", "false")


def _is_transfer_guard_error(e: BaseException) -> bool:
    msg = str(e)
    return "Disallowed" in msg and "transfer" in msg


def transfer_guard_effective() -> bool:
    """True when jax.transfer_guard("disallow") actually raises on an
    implicit host→device transfer on this backend (probe with an eager
    op whose scalar operand must be uploaded)."""
    import jax
    import jax.numpy as jnp
    if not hasattr(jax, "transfer_guard"):
        return False
    x = jnp.zeros(2)            # committed before the guard
    try:
        with jax.transfer_guard("disallow"):
            (x * 2.0).block_until_ready()
    except Exception as e:      # noqa: BLE001 — backend-specific error type
        return _is_transfer_guard_error(e)
    return False


def _replica_digests(x) -> list:
    """(device, sha1-digest) per REPLICATED copy of `x`: every
    addressable shard whose buffer covers the whole array.  Fewer than
    two full copies (sharded arrays, single device) → [] — there is
    nothing cross-shard to compare.  Fetches are explicit
    ``jax.device_get`` so the probe stays legal under the transfer
    guard's "disallow"."""
    import hashlib

    import jax
    import numpy as np
    shards = getattr(x, "addressable_shards", None)
    if not shards or len(shards) < 2:
        return []
    out = []
    for s in shards:
        if tuple(s.data.shape) != tuple(x.shape):
            return []                  # genuinely sharded, not replicated
        buf = np.ascontiguousarray(jax.device_get(s.data))
        out.append((s.device, hashlib.sha1(buf.tobytes()).hexdigest()))
    return out


class DivergenceSanitizer:
    """Cross-shard replication checker — the runtime half of shardlint.

    The static rules (diagnostics/lint.py shardlint family) prove what
    the AST can see; this closes over what it cannot: whether the
    REPLICATED growth-loop state (split records post-
    ``combine_sharded_records``, leaf counts, the packed tree arrays)
    is actually bitwise-identical on every device after each iteration.
    The mesh learners run ``shard_map(..., check_vma=False)``, so a
    shard-local value leaking into replicated control flow produces
    per-device buffers that silently disagree — a wrong answer on CPU
    and the prelude to a pod-wide deadlock on real hardware.

    ``check(name, value)`` fingerprints every jax.Array leaf of a
    pytree per device (sha1 over the raw buffer) and compares:
    identical → one ``sanitize/divergence_checks`` tick; any mismatch →
    ``sanitize/divergences`` plus (strict mode, the default) an
    immediate AssertionError naming the leaf and per-device digests.
    Multi-process runs compare this process's addressable devices; the
    cross-host copies are covered by every host running the same check.
    """

    def __init__(self, label: str = "growth-loop", strict: bool = True):
        self.label = label
        self.strict = strict
        self.checks = 0
        self.divergences = 0
        self.evidence = []

    def check(self, name: str, value) -> int:
        """Fingerprint a pytree of (assumed-replicated) device arrays.
        Returns the number of NEW divergences found."""
        import jax
        before = self.divergences
        try:
            items = [(jax.tree_util.keystr(p), leaf) for p, leaf in
                     jax.tree_util.tree_leaves_with_path(value)]
        except AttributeError:         # older jax: positional labels
            items = [(str(i), leaf) for i, leaf in
                     enumerate(jax.tree_util.tree_leaves(value))]
        for key, leaf in items:
            digs = _replica_digests(leaf)
            if len(digs) < 2:
                continue
            self.checks += 1
            profiling.count(DIVERGENCE_CHECKS)
            if len({d for _, d in digs}) > 1:
                self.divergences += 1
                profiling.count(DIVERGENCES)
                ev = (name, key, [(str(dev), d[:12]) for dev, d in digs])
                if len(self.evidence) < 16:
                    self.evidence.append(ev)
                if self.strict:
                    raise AssertionError(
                        f"cross-shard divergence [{self.label}] in "
                        f"'{name}/{key}': a replicated growth-loop value "
                        f"differs across devices {ev[2]} — a shard-local "
                        "value leaked into replicated state (silent "
                        "wrong answer here, deadlock shape on a real "
                        "mesh)")
        return self.divergences - before

    def report(self) -> dict:
        return {"label": self.label,
                "divergence_checks": self.checks,
                "divergences": self.divergences,
                "evidence": self.evidence[:4]}


_divergence: Optional[DivergenceSanitizer] = None


def divergence_sanitizer() -> DivergenceSanitizer:
    """The process-wide strict instance the learner hooks feed."""
    global _divergence
    if _divergence is None:
        _divergence = DivergenceSanitizer(label="hot-path")
    return _divergence


def maybe_check_divergence(name: str, value) -> None:
    """Hot-loop hook (both mesh learners call this after every tree
    build): no-op unless BENCH_SANITIZE is on, else a strict
    cross-shard replication check of `value`."""
    if not sanitize_enabled():
        return
    divergence_sanitizer().check(name, value)


class _CompileCounter(logging.Handler):
    """Counts trace events (the retrace signal — see _TRACE_MARKER) and
    backend compiles separately from the jax_log_compiles record
    stream.  One user-level retrace emits one-or-more trace records
    (inner pjits trace too); the contract asserted is ZERO, so the
    event count being an upper bound is fine and the captured names
    point at the offending program."""

    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.count = 0               # trace events (retrace signal)
        self.compiles = 0            # backend "Compiling" events
        self.names = []

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:       # noqa: BLE001 — never break the hot path
            return
        if msg.startswith(_TRACE_MARKER):
            self.count += 1
            if len(self.names) < 64:     # bounded evidence for reports
                self.names.append(
                    msg[len(_TRACE_MARKER):].split(" for ")[0])
        elif msg.startswith(_COMPILE_MARKER):
            self.compiles += 1


class HotPathSanitizer:
    """Context manager asserting the zero-retrace / zero-implicit-
    transfer contract of a steady-state loop.

    Usage::

        with HotPathSanitizer(warmup=1) as san:
            for _ in range(iters):
                with san.step():
                    bst.update()
        assert san.retraces == 0 and san.implicit_transfers == 0

    ``warmup`` steps may compile freely (first call after a cold cache);
    compiles in any later step count as retraces.  A transfer-guard
    violation inside ``step()`` increments the counter and, with
    ``strict=False`` (default), is swallowed so one run can report the
    total instead of dying at the first violation — note the violating
    iteration's work is aborted mid-dispatch, so non-strict mode is for
    *measuring* breakage, not for training through it.
    """

    def __init__(self, warmup: int = 1, guard: str = "disallow",
                 strict: bool = False, label: str = "hot_path",
                 d2d_guard: str = "allow"):
        self.warmup = int(warmup)
        self.guard = guard
        # device→device resharding (e.g. the replicated gradient
        # scattering into a shard_map mesh) is legitimate SPMD traffic,
        # not the host-sync stall class this sanitizer hunts — allowed
        # by default, tightten via d2d_guard="disallow" to audit it too
        self.d2d_guard = d2d_guard
        self.strict = strict
        self.label = label
        self.steps = 0
        self.retraces = 0
        self.implicit_transfers = 0
        self.compiles_total = 0
        self.trace_events = 0
        self.compile_names = []
        # cross-shard divergence counters over this window (the
        # DivergenceSanitizer feeds the profiling registry; the deltas
        # land in report()/check() beside the retrace counters)
        self.divergence_checks = 0
        self.divergences = 0
        self._div0 = (0.0, 0.0)
        # lock-sanitizer counters over this window (diagnostics/locksan
        # feeds the profiling registry when armed; zero when disarmed)
        self.lock_acquires = 0
        self.lock_waits = 0
        self.lock_cycles = 0
        self._lock0 = (0.0, 0.0, 0.0)
        self._handler: Optional[_CompileCounter] = None
        self._prev_log_compiles = None
        self._prev_propagate = None

    # -- lifecycle ------------------------------------------------------
    def __enter__(self) -> "HotPathSanitizer":
        import jax
        self._handler = _CompileCounter()
        lg = logging.getLogger("jax")
        lg.addHandler(self._handler)
        # capture without spraying WARNING-level compile logs to stderr
        self._prev_propagate = lg.propagate
        lg.propagate = False
        self._prev_log_compiles = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        self._div0 = (profiling.counter_value(DIVERGENCE_CHECKS),
                      profiling.counter_value(DIVERGENCES))
        self._lock0 = (profiling.counter_value(LOCK_ACQUIRES),
                       profiling.counter_value(LOCK_WAITS),
                       profiling.counter_value(LOCK_CYCLES))
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        import jax
        jax.config.update("jax_log_compiles", self._prev_log_compiles)
        lg = logging.getLogger("jax")
        lg.removeHandler(self._handler)
        lg.propagate = self._prev_propagate
        self.trace_events = self._handler.count
        self.compiles_total = self._handler.compiles
        self.compile_names = list(self._handler.names)
        profiling.count(RETRACES, self.retraces)
        profiling.count(IMPLICIT_TRANSFERS, self.implicit_transfers)
        profiling.count(COMPILES_TOTAL, self.compiles_total)
        self.divergence_checks = int(
            profiling.counter_value(DIVERGENCE_CHECKS) - self._div0[0])
        self.divergences = int(
            profiling.counter_value(DIVERGENCES) - self._div0[1])
        self.lock_acquires = int(
            profiling.counter_value(LOCK_ACQUIRES) - self._lock0[0])
        self.lock_waits = int(
            profiling.counter_value(LOCK_WAITS) - self._lock0[1])
        self.lock_cycles = int(
            profiling.counter_value(LOCK_CYCLES) - self._lock0[2])
        return False

    # -- per-iteration accounting --------------------------------------
    @contextmanager
    def step(self) -> Iterator[None]:
        """One hot-loop iteration.  Warmup steps run UNGUARDED (a cold
        cache may legitimately compile, and compiling transfers
        constants); post-warmup steps run under the transfer guard and
        attribute compile events to retraces."""
        import jax
        before = self._handler.count
        guarded = (self.steps >= self.warmup
                   and hasattr(jax, "transfer_guard"))
        try:
            with contextlib.ExitStack() as stack:
                if guarded:
                    if hasattr(jax, "transfer_guard_host_to_device"):
                        stack.enter_context(
                            jax.transfer_guard_host_to_device(self.guard))
                        stack.enter_context(
                            jax.transfer_guard_device_to_host(self.guard))
                        stack.enter_context(
                            jax.transfer_guard_device_to_device(
                                self.d2d_guard))
                    else:       # older jax: one knob for all directions
                        stack.enter_context(jax.transfer_guard(self.guard))
                yield
        except Exception as e:   # noqa: BLE001 — classify, then re-raise
            if guarded and _is_transfer_guard_error(e):
                self.implicit_transfers += 1
                if self.strict:
                    raise
            else:
                raise
        finally:
            self.steps += 1
            new = self._handler.count - before
            if self.steps > self.warmup and new:
                self.retraces += new

    # -- reporting ------------------------------------------------------
    def report(self) -> dict:
        """JSON-ready summary (bench.py embeds this under "sanitize")."""
        return {
            "label": self.label,
            "guard": self.guard,
            "steps": self.steps,
            "warmup": self.warmup,
            "retraces_after_warmup": self.retraces,
            "implicit_transfers": self.implicit_transfers,
            "trace_events_total": self.trace_events,
            "compiles_total": self.compiles_total,
            # cross-shard replication audit over this window (the
            # DivergenceSanitizer; >0 checks only on multi-device
            # meshes with BENCH_SANITIZE on)
            "divergence_checks": self.divergence_checks,
            "divergences": self.divergences,
            # lock-order audit over this window (diagnostics/locksan;
            # acquires > 0 proves the instrumented shim was armed)
            "lock_acquires": self.lock_acquires,
            "lock_waits": self.lock_waits,
            "lock_cycles": self.lock_cycles,
            # first offending program names — the evidence a regression
            # report needs to find the retracing call site
            "retrace_names": self.compile_names[-8:] if self.retraces else [],
        }

    def check(self) -> None:
        """Raise with a diagnostic when the zero/zero/zero contract is
        broken (retraces, implicit transfers, cross-shard divergences,
        lock-order cycles)."""
        if self.retraces or self.implicit_transfers or self.divergences \
                or self.lock_cycles:
            from . import locksan
            raise AssertionError(
                f"hot-path sanitizer [{self.label}]: "
                f"{self.retraces} retrace(s), "
                f"{self.implicit_transfers} implicit transfer(s), "
                f"{self.divergences} cross-shard divergence(s) and "
                f"{self.lock_cycles} lock-order cycle(s) after "
                f"{self.warmup} warmup step(s) over {self.steps} steps; "
                f"recent compiles: {self.compile_names[-8:]}; "
                f"lock cycles: {locksan.cycles()[:4]}")
