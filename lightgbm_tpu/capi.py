"""Python side of the native TRAINING C ABI (src/native/c_api_train.cpp).

The reference exposes its full training workflow through ~50 ``LGBM_*``
C functions (include/LightGBM/c_api.h:37-711) so non-Python callers can
build datasets, boost, evaluate, and predict.  In this framework the
compute path is JAX/XLA — so the native training ABI hosts the Python
runtime (CPython embedding) and this module is the marshaling boundary:
every function takes raw pointer ADDRESSES plus shape/dtype metadata,
wraps them as numpy arrays via ctypes (zero-copy views; copies only
where the data must outlive the call), and delegates to the package's
own Dataset/Booster objects.  The C++ layer stays a thin shell that
never touches array memory itself.

Handles held by C callers are ordinary Python objects (`CApiDataset`,
`CApiBooster`) kept alive by the C layer's reference counts.

The serving-only ABI (src/native/c_api.cpp) remains dependency-free by
design; this module backs the training library `liblgbt_train.so`.
"""
from __future__ import annotations

import ctypes
import json
from typing import List, Optional

import numpy as np

from .basic import Booster as _PyBooster, Dataset as _PyDataset
from .binning import CATEGORICAL, NUMERICAL, find_bin
from .config import apply_aliases, config_from_params
from .dataset import Dataset as _InnerDataset, Metadata

# reference c_api.h:20-28 dtype / predict-type codes
_DTYPE = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}
PREDICT_NORMAL, PREDICT_RAW, PREDICT_LEAF = 0, 1, 2


def _view(addr: int, count: int, type_code: int) -> np.ndarray:
    """Zero-copy numpy view of `count` elements at raw address `addr`."""
    dt = _DTYPE[int(type_code)]
    if count == 0:
        return np.empty(0, dt)
    ct = {np.float32: ctypes.c_float, np.float64: ctypes.c_double,
          np.int32: ctypes.c_int32, np.int64: ctypes.c_int64}[dt]
    buf = (ct * int(count)).from_address(int(addr))
    return np.ctypeslib.as_array(buf)


def _params_from_string(parameters: str) -> dict:
    """Parse the reference's 'key1=value1 key2=value2' parameter format
    (c_api.h LGBM_BoosterCreate doc; application.cpp:46-70 tokens)."""
    out: dict = {}
    for tok in (parameters or "").replace("\n", " ").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def _categorical_from_params(params: dict) -> List[int]:
    res = apply_aliases(dict(params))
    spec = str(res.get("categorical_feature", "") or "")
    cols: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if part.isdigit() or (part.startswith("-") and part[1:].isdigit()):
            cols.append(int(part))
    return cols


class CApiDataset:
    """Dataset handle: either fully constructed, or an empty push-mode
    shell (CreateByReference / CreateFromSampledColumn) that finalizes
    once rows [0, num_total_row) have all been pushed — the reference's
    FinishLoad contract (c_api.h LGBM_DatasetPushRows doc)."""

    def __init__(self, inner: Optional[_InnerDataset], params: dict,
                 reference: Optional["CApiDataset"] = None):
        self.inner = inner
        self.params = dict(params)
        self.reference = reference
        self._pushed = 0
        self._finished = inner is not None
        self._field_cache: dict = {}    # keeps GetField views alive

    # -- push-mode construction ---------------------------------------------

    @classmethod
    def empty_like(cls, reference: "CApiDataset", num_total_row: int
                   ) -> "CApiDataset":
        ref = reference.require_finished()
        cfg = config_from_params(reference.params)
        inner = _InnerDataset._empty_from_mappers(
            cfg, ref.mappers, list(ref.used_features), int(num_total_row),
            ref.num_total_features, list(ref.feature_names),
            plan=ref.bundle_plan)
        ds = cls(None, reference.params, reference)
        ds.inner = inner
        return ds

    @classmethod
    def from_sampled_column(cls, col_addrs, idx_addrs, num_per_col,
                            num_sample_row: int, num_total_row: int,
                            params: dict) -> "CApiDataset":
        """LGBM_DatasetCreateFromSampledColumn: per-column sampled
        non-zero values build the bin mappers (the exact FindBin input,
        bin.cpp:67-240); the store is then filled by PushRows."""
        cfg = config_from_params(params)
        cats = set(_categorical_from_params(params))
        mappers = []
        for j, (addr, cnt) in enumerate(zip(col_addrs, num_per_col)):
            vals = _view(addr, cnt, 1).astype(np.float64, copy=True)
            vals = vals[(vals != 0.0) & ~np.isnan(vals)]
            bt = CATEGORICAL if j in cats else NUMERICAL
            mappers.append(find_bin(vals, int(num_sample_row), cfg.max_bin,
                                    cfg.min_data_in_bin,
                                    cfg.min_data_in_leaf, bt))
        used = [i for i, m in enumerate(mappers) if not m.is_trivial]
        inner = _InnerDataset._empty_from_mappers(
            cfg, mappers, used, int(num_total_row), len(mappers), None)
        ds = cls(None, params)
        ds.inner = inner
        return ds

    def push_rows(self, X: np.ndarray, start_row: int) -> None:
        if self._finished:
            raise RuntimeError("cannot push rows into a finished Dataset")
        self.inner._bin_rows_into(np.ascontiguousarray(X, np.float64),
                                  int(start_row))
        self._pushed += len(X)
        if int(start_row) + len(X) >= self.inner.num_data:
            self._finish_load()

    def _finish_load(self) -> None:
        md = self.inner.metadata
        if md.label.size == 0:
            md.label = np.zeros(self.inner.num_data, np.float32)
        self._finished = True

    def require_finished(self) -> _InnerDataset:
        if not self._finished:
            raise RuntimeError(
                f"Dataset is still loading: {self._pushed} of "
                f"{self.inner.num_data} rows pushed")
        return self.inner

    # -- fields (c_api.h LGBM_DatasetSetField/GetField) ----------------------

    def set_field(self, name: str, addr: int, count: int,
                  type_code: int) -> None:
        data = _view(addr, count, type_code)
        md = self.inner.metadata
        name = name.lower()
        if name == "label":
            md.label = np.asarray(data, np.float32).copy()
        elif name == "weight":
            md.weights = (np.asarray(data, np.float32).copy()
                          if count else None)
        elif name == "init_score":
            md.init_score = (np.asarray(data, np.float64).copy()
                             if count else None)
        elif name in ("group", "query", "group_id", "query_id"):
            sizes = np.asarray(data, np.int64)
            md.set_query_from_sizes(sizes.copy())
        else:
            raise ValueError(f"unknown field name: {name}")
        self._field_cache.pop(name, None)

    def get_field(self, name: str):
        """Returns (addr, len, type_code) of the field's storage; the
        array is cached on the handle so the pointer stays valid until
        the next SetField/Free (the reference hands out internal
        metadata pointers with the same lifetime)."""
        md = self.require_finished().metadata
        name = name.lower()
        if name == "label":
            arr, code = np.asarray(md.label, np.float32), 0
        elif name == "weight":
            if md.weights is None:
                return 0, 0, 0
            arr, code = np.asarray(md.weights, np.float32), 0
        elif name == "init_score":
            if md.init_score is None:
                return 0, 0, 1
            arr, code = np.asarray(md.init_score, np.float64), 1
        elif name in ("group", "query", "group_id", "query_id"):
            qb = md.query_boundaries
            if qb is None:
                return 0, 0, 2
            arr, code = np.asarray(qb, np.int32), 2
        else:
            raise ValueError(f"unknown field name: {name}")
        arr = np.ascontiguousarray(arr)
        self._field_cache[name] = arr
        return arr.ctypes.data, arr.size, code


# -- dataset creation entry points -------------------------------------------

def dataset_from_file(filename: str, parameters: str,
                      reference: Optional[CApiDataset]) -> CApiDataset:
    params = _params_from_string(parameters)
    cfg = config_from_params(params)
    ref_inner = reference.require_finished() if reference else None
    inner = _InnerDataset.from_file(filename, cfg, reference=ref_inner)
    return CApiDataset(inner, params)


def _mat_view(addr: int, type_code: int, nrow: int, ncol: int,
              is_row_major: int) -> np.ndarray:
    flat = _view(addr, int(nrow) * int(ncol), type_code)
    if is_row_major:
        return flat.reshape(int(nrow), int(ncol))
    return flat.reshape(int(ncol), int(nrow)).T


def dataset_from_mat(addr: int, type_code: int, nrow: int, ncol: int,
                     is_row_major: int, parameters: str,
                     reference: Optional[CApiDataset]) -> CApiDataset:
    params = _params_from_string(parameters)
    cfg = config_from_params(params)
    X = _mat_view(addr, type_code, nrow, ncol, is_row_major)
    ref_inner = reference.require_finished() if reference else None
    inner = _InnerDataset(
        np.asarray(X, np.float64), None, cfg, reference=ref_inner,
        categorical_feature=_categorical_from_params(params))
    return CApiDataset(inner, params)


def _dense_from_csr(indptr, indices, data, num_col: int) -> np.ndarray:
    """Densify a whole CSR matrix (dataset-construction entries, whose
    downstream binner wants the full matrix anyway).  The PREDICT paths
    never call this — they densify bounded row chunks via
    `_csr_row_chunks` so a 10^6-row sparse predict peaks at one
    chunk's dense bytes, not the whole matrix."""
    nrow = indptr.size - 1
    X = np.zeros((nrow, int(num_col)), np.float64)
    row = np.repeat(np.arange(nrow), np.diff(indptr).astype(np.int64))
    X[row, indices[: data.size]] = data
    return X


def _predict_densify_chunk(num_col: int = 1) -> int:
    """Row-slab size of the predict-path densify: the device predict
    chunk cap, additionally BYTE-capped by the column count (a
    262144-row float64 slab at 50k features would be ~105 GB — the
    wide-sparse shape this path exists for).  ~256 MB per slab; the
    device loop re-chunks rows internally, so a smaller slab costs
    nothing."""
    from .boosting.gbdt import GBDT
    byte_cap = int(256e6) // max(int(num_col) * 8, 1)
    return max(1024, min(int(GBDT._PREDICT_CHUNK), byte_cap))


def _csr_row_chunks(indptr, indices, data, num_col: int, chunk: int):
    """Yield dense [<=chunk, num_col] float64 row slabs of a CSR
    matrix; peak memory is one slab + the sparse arrays."""
    nrow = indptr.size - 1
    for r0 in range(0, nrow, chunk):
        r1 = min(nrow, r0 + chunk)
        s, e = int(indptr[r0]), int(indptr[r1])
        Xc = np.zeros((r1 - r0, int(num_col)), np.float64)
        rows = np.repeat(np.arange(r0, r1),
                         np.diff(indptr[r0:r1 + 1]).astype(np.int64)) - r0
        Xc[rows, indices[s:e]] = data[s:e]
        yield Xc


def _csc_to_csr_arrays(col_ptr, indices, data, num_row: int):
    """CSC → CSR index arrays (one nnz-sized stable sort, no dense
    matrix) so the CSC predict path can reuse `_csr_row_chunks`."""
    ncol = col_ptr.size - 1
    cols = np.repeat(np.arange(ncol), np.diff(col_ptr).astype(np.int64))
    rows = np.asarray(indices[: data.size])
    order = np.argsort(rows, kind="stable")
    indptr = np.concatenate([[0], np.cumsum(
        np.bincount(rows, minlength=int(num_row)))]).astype(np.int64)
    return indptr, cols[order], np.asarray(data)[order]


def _dense_from_csc(col_ptr, indices, data, num_row: int) -> np.ndarray:
    ncol = col_ptr.size - 1
    X = np.zeros((int(num_row), ncol), np.float64)
    col = np.repeat(np.arange(ncol), np.diff(col_ptr).astype(np.int64))
    X[indices[: data.size], col] = data
    return X


def dataset_from_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                     data_type, nindptr, nelem, num_col, parameters,
                     reference: Optional[CApiDataset]) -> CApiDataset:
    indptr = _view(indptr_addr, nindptr, indptr_type).astype(np.int64)
    indices = _view(indices_addr, nelem, 2)
    data = _view(data_addr, nelem, data_type).astype(np.float64)
    X = _dense_from_csr(indptr, indices, data, num_col)
    params = _params_from_string(parameters)
    ref_inner = reference.require_finished() if reference else None
    inner = _InnerDataset(X, None, config_from_params(params),
                          reference=ref_inner,
                          categorical_feature=_categorical_from_params(params))
    return CApiDataset(inner, params)


def dataset_from_csc(col_ptr_addr, col_ptr_type, indices_addr, data_addr,
                     data_type, ncol_ptr, nelem, num_row, parameters,
                     reference: Optional[CApiDataset]) -> CApiDataset:
    col_ptr = _view(col_ptr_addr, ncol_ptr, col_ptr_type).astype(np.int64)
    indices = _view(indices_addr, nelem, 2)
    data = _view(data_addr, nelem, data_type).astype(np.float64)
    X = _dense_from_csc(col_ptr, indices, data, num_row)
    params = _params_from_string(parameters)
    ref_inner = reference.require_finished() if reference else None
    inner = _InnerDataset(X, None, config_from_params(params),
                          reference=ref_inner,
                          categorical_feature=_categorical_from_params(params))
    return CApiDataset(inner, params)


def dataset_push_rows(ds: CApiDataset, addr: int, type_code: int,
                      nrow: int, ncol: int, start_row: int) -> None:
    X = _mat_view(addr, type_code, nrow, ncol, 1)
    ds.push_rows(X, start_row)


def dataset_push_rows_csr(ds: CApiDataset, indptr_addr, indptr_type,
                          indices_addr, data_addr, data_type, nindptr,
                          nelem, num_col, start_row) -> None:
    indptr = _view(indptr_addr, nindptr, indptr_type).astype(np.int64)
    indices = _view(indices_addr, nelem, 2)
    data = _view(data_addr, nelem, data_type).astype(np.float64)
    ds.push_rows(_dense_from_csr(indptr, indices, data, num_col), start_row)


def dataset_get_subset(ds: CApiDataset, idx_addr: int, num_idx: int,
                       parameters: str) -> CApiDataset:
    inner = ds.require_finished()
    idx = _view(idx_addr, num_idx, 2).astype(np.int64)
    params = dict(ds.params)
    params.update(_params_from_string(parameters))
    cfg = config_from_params(params)
    sub = _InnerDataset._empty_from_mappers(
        cfg, inner.mappers, list(inner.used_features), int(num_idx),
        inner.num_total_features, list(inner.feature_names),
        plan=inner.bundle_plan)
    sub.bins = np.ascontiguousarray(
        inner.dense_bins(site="capi_subset")[:, idx])
    # conflicts of the selected rows are not recoverable from the bundled
    # store; carry a proportional ESTIMATE so realized_conflict_rate()
    # stays in [0, 1] instead of inheriting the full dataset's count
    sub.bundle_conflict_rows = int(round(
        inner.bundle_conflict_rows * num_idx / max(inner.num_data, 1)))
    md = Metadata()
    md.label = np.asarray(inner.metadata.label, np.float32)[idx].copy()
    if inner.metadata.weights is not None:
        md.weights = np.asarray(inner.metadata.weights,
                                np.float32)[idx].copy()
    if inner.metadata.init_score is not None:
        md.init_score = np.asarray(inner.metadata.init_score,
                                   np.float64)[idx].copy()
    if inner.metadata.query_boundaries is not None:
        # carry ranking groups: map rows to query ids, then rebuild
        # boundaries from the subset's id runs.  Like the reference,
        # this assumes the indices keep each query's rows together
        # (CV folds subset whole queries).
        qb = inner.metadata.query_boundaries.astype(np.int64)
        qid = np.repeat(np.arange(len(qb) - 1), np.diff(qb))[idx]
        change = np.flatnonzero(np.diff(qid)) + 1
        sizes = np.diff(np.concatenate([[0], change, [qid.size]]))
        md.set_query_from_sizes(sizes)
    sub.metadata = md
    out = CApiDataset(sub, params)
    return out


# -- booster -----------------------------------------------------------------

class CApiBooster:
    """Booster handle: a thin shell over the package Booster plus the
    eval-result bookkeeping the C contract needs (GetEvalNames order is
    the order GetEval fills results in, c_api.h:465-480)."""

    def __init__(self, booster: _PyBooster,
                 train_ds: Optional[CApiDataset] = None):
        self.booster = booster
        self.train_ds = train_ds
        self.valid: List[CApiDataset] = []
        self._cache: dict = {}          # keeps returned buffers alive

    @classmethod
    def create(cls, train: CApiDataset, parameters: str) -> "CApiBooster":
        params = _params_from_string(parameters)
        shell = _wrap_inner(train.require_finished(), params)
        return cls(_PyBooster(params, shell), train)

    @classmethod
    def from_model_file(cls, filename: str) -> "CApiBooster":
        return cls(_PyBooster(model_file=filename))

    @classmethod
    def from_model_string(cls, model_str: str) -> "CApiBooster":
        return cls(_PyBooster(model_str=model_str))

    # -- training ------------------------------------------------------------

    def add_valid(self, ds: CApiDataset) -> None:
        shell = _wrap_inner(ds.require_finished(), self.booster.params)
        self.booster.add_valid(shell, f"valid_{len(self.valid)}")
        self.valid.append(ds)

    def update(self) -> bool:
        return bool(self.booster.update())

    def update_custom(self, grad_addr: int, hess_addr: int) -> bool:
        """Boost directly from caller gradients.  Booster.update(fobj=..)
        would first materialize the full score array for fobj — a
        device sync + K*N host copy the C caller (who already read
        scores via GetPredict) never looks at."""
        import jax.numpy as jnp
        g = self.booster._gbdt
        n, k = int(g.num_data), int(g.K)
        grad = _view(grad_addr, n * k, 0).reshape(k, n)
        hess = _view(hess_addr, n * k, 0).reshape(k, n)
        return bool(g.train_one_iter(jnp.asarray(grad), jnp.asarray(hess),
                                     False))

    def reset_training_data(self, ds: CApiDataset) -> None:
        shell = _wrap_inner(ds.require_finished(), self.booster.params)
        self.booster._gbdt.reset_training_data(shell._inner)
        self.booster.train_set = shell
        self.train_ds = ds

    def merge(self, other: "CApiBooster") -> None:
        """Append the other booster's trees (reference GBDT::MergeFrom,
        gbdt.h: models are concatenated)."""
        g, og = self.booster._gbdt, other.booster._gbdt
        for t in og.models:
            g.models.append(t)

    def refit(self, leaf_pred_addr: int, nrow: int, ncol: int) -> None:
        """LGBM_BoosterRefit: refit the handle's model IN PLACE on its
        training dataset's labels using caller-provided leaf
        predictions ([nrow, ncol] int32 — one column per model, the
        PredictForMat(PREDICT_LEAF) layout).  Delegates to the online
        refit kernel with the routing step skipped (c_api.h
        LGBM_BoosterRefit semantics; decay/min-rows come from the
        booster's ``refit_decay_rate`` / ``refit_min_rows`` params)."""
        if self.train_ds is None:
            raise RuntimeError("refit needs the training dataset on the "
                               "booster handle")
        from .online.refit import refit_gbdt
        leaf = _view(leaf_pred_addr, int(nrow) * int(ncol), 2).reshape(
            int(nrow), int(ncol)).copy()
        refit_gbdt(self.booster._gbdt, self.train_ds.require_finished(),
                   leaf_idx=leaf)

    # -- eval ----------------------------------------------------------------

    def eval_names(self) -> List[str]:
        """One metric object can yield several results (ndcg@1,3,5);
        Metric.result_names enumerates them without an eval pass —
        GetEvalCounts/GetEvalNames must stay cheap (the reference
        returns stored names, c_api.cpp GetEvalNames)."""
        g = self.booster._gbdt
        metrics = g.train_metrics or (
            g.valid_sets[0][3] if g.valid_sets else [])
        return [n for m in metrics for n in m.result_names()]

    def get_eval(self, data_idx: int) -> List[float]:
        g = self.booster._gbdt
        if data_idx == 0:
            return [v for _, _, v, _ in g.eval_train()]
        # evaluate ONLY the requested set — eval_valid() would run every
        # registered set per call (V sets polled per iteration -> V^2)
        name, _, su, ms = g.valid_sets[data_idx - 1]
        out: List = []
        g._eval_one_set(name, su, ms, out)
        return [v for _, _, v, _ in g._materialize_evals(out)]

    def inner_predict_len(self, data_idx: int) -> int:
        """Length of GetPredict's result WITHOUT materializing it
        (GetNumPredict is a pure size query, c_api.h:487-494)."""
        g = self.booster._gbdt
        n = (int(g.num_data) if data_idx == 0
             else int(g.valid_sets[data_idx - 1][1].num_data))
        return n * int(g.K)

    def inner_predict(self, data_idx: int) -> np.ndarray:
        g = self.booster._gbdt
        if data_idx == 0:
            sc = g.train_score.get()
        else:
            sc = np.asarray(g.valid_sets[data_idx - 1][2].get())
        arr = np.ascontiguousarray(np.asarray(sc, np.float64).reshape(-1))
        self._cache[("inner", data_idx)] = arr
        return arr

    # -- prediction -----------------------------------------------------------

    def _predict(self, X: np.ndarray, predict_type: int,
                 num_iteration: int) -> np.ndarray:
        ni = int(num_iteration) if int(num_iteration) > 0 else -1
        out = self.booster.predict(
            X, num_iteration=ni,
            raw_score=(predict_type == PREDICT_RAW),
            pred_leaf=(predict_type == PREDICT_LEAF), is_reshape=False)
        return np.ascontiguousarray(np.asarray(out, np.float64).reshape(-1))

    def predict_for_mat(self, addr, type_code, nrow, ncol, is_row_major,
                        predict_type, num_iteration, out_addr) -> int:
        X = _mat_view(addr, type_code, nrow, ncol, is_row_major)
        res = self._predict(np.asarray(X, np.float64), predict_type,
                            num_iteration)
        _view(out_addr, res.size, 1)[:] = res
        return int(res.size)

    def _predict_sparse_chunks(self, indptr, indices, data, num_col,
                               predict_type, num_iteration,
                               out_addr) -> int:
        """Chunked dense predict over CSR arrays: each row slab is
        densified, scored, and written at its output offset — the full
        dense matrix never exists."""
        from .basic import _warn_sparse_densify
        nrow = indptr.size - 1
        chunk = _predict_densify_chunk(num_col)
        _warn_sparse_densify((nrow, int(num_col)),
                             chunk_rows=min(chunk, max(nrow, 1)))
        total = 0
        for Xc in _csr_row_chunks(indptr, indices, data, num_col, chunk):
            res = self._predict(Xc, predict_type, num_iteration)
            _view(out_addr, total + res.size, 1)[total:] = res
            total += int(res.size)
        return total

    def predict_for_csr(self, indptr_addr, indptr_type, indices_addr,
                        data_addr, data_type, nindptr, nelem, num_col,
                        predict_type, num_iteration, out_addr) -> int:
        indptr = _view(indptr_addr, nindptr, indptr_type).astype(np.int64)
        indices = _view(indices_addr, nelem, 2)
        data = _view(data_addr, nelem, data_type).astype(np.float64)
        return self._predict_sparse_chunks(indptr, indices, data, num_col,
                                           predict_type, num_iteration,
                                           out_addr)

    def predict_for_csc(self, col_ptr_addr, col_ptr_type, indices_addr,
                        data_addr, data_type, ncol_ptr, nelem, num_row,
                        predict_type, num_iteration, out_addr) -> int:
        col_ptr = _view(col_ptr_addr, ncol_ptr, col_ptr_type).astype(np.int64)
        indices = _view(indices_addr, nelem, 2)
        data = _view(data_addr, nelem, data_type).astype(np.float64)
        num_col = col_ptr.size - 1
        indptr, cols, vals = _csc_to_csr_arrays(col_ptr, indices, data,
                                                num_row)
        return self._predict_sparse_chunks(indptr, cols, vals, num_col,
                                           predict_type, num_iteration,
                                           out_addr)

    def predict_for_file(self, data_filename: str, data_has_header: int,
                         predict_type: int, num_iteration: int,
                         result_filename: str) -> None:
        ni = int(num_iteration) if int(num_iteration) > 0 else -1
        preds = self.booster.predict(
            data_filename, num_iteration=ni,
            raw_score=(predict_type == PREDICT_RAW),
            pred_leaf=(predict_type == PREDICT_LEAF),
            data_has_header=bool(data_has_header), is_reshape=True)
        preds = np.asarray(preds)
        if preds.ndim == 1:
            preds = preds[:, None]
        with open(result_filename, "w") as fh:
            for row in preds:
                fh.write("\t".join(f"{v:g}" for v in row) + "\n")

    def calc_num_predict(self, num_row: int, predict_type: int,
                         num_iteration: int) -> int:
        g = self.booster._gbdt
        if predict_type == PREDICT_LEAF:
            # must agree with predict_leaf_index's model count (which
            # includes the boost_from_average init model) or the caller
            # under-allocates and PredictForMat writes past the buffer
            g._flush_pending()
            ni = int(num_iteration) if int(num_iteration) > 0 else -1
            return int(num_row) * int(g._num_used_models(ni))
        return int(num_row) * int(g.num_class)

    # -- model IO --------------------------------------------------------------

    def save_model(self, num_iteration: int, filename: str) -> None:
        ni = int(num_iteration) if int(num_iteration) > 0 else -1
        self.booster.save_model(filename, num_iteration=ni)

    def model_to_string(self, num_iteration: int) -> str:
        ni = int(num_iteration) if int(num_iteration) > 0 else -1
        return self.booster.model_to_string(num_iteration=ni)

    def dump_model(self, num_iteration: int) -> str:
        ni = int(num_iteration) if int(num_iteration) > 0 else -1
        return json.dumps(self.booster.dump_model(num_iteration=ni))

    def get_leaf_value(self, tree_idx: int, leaf_idx: int) -> float:
        t = self.booster._gbdt.models[int(tree_idx)]
        return float(t.leaf_value[int(leaf_idx)])

    def set_leaf_value(self, tree_idx: int, leaf_idx: int,
                       val: float) -> None:
        t = self.booster._gbdt.models[int(tree_idx)]
        t.leaf_value[int(leaf_idx)] = float(val)
        t._device_cache = None


def _wrap_inner(inner: _InnerDataset, params: dict) -> _PyDataset:
    """Wrap an already-constructed inner dataset in the package-level
    Dataset shell (skips re-binning: _inner is pre-set)."""
    shell = _PyDataset.__new__(_PyDataset)
    shell.params = dict(params)
    shell.data = None
    shell.label = None
    shell.reference = None
    shell.weight = shell.group = shell.init_score = None
    shell.feature_name = "auto"
    shell.categorical_feature = "auto"
    shell.free_raw_data = False
    shell.pandas_categorical = None
    shell._inner = inner
    shell._raw_X = None
    return shell
