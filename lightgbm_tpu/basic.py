"""User-facing Dataset / Booster API.

Mirrors the reference python package (/root/reference/python-package/
lightgbm/basic.py): `Dataset` with lazy construction, reference-alignment
for validation data, pandas & categorical handling (basic.py:536-1159);
`Booster` with update/eval/predict/save (basic.py:1160-1781).  There is no
ctypes/C-API hop: the "engine" underneath is the in-process JAX GBDT.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config, config_from_params
from .dataset import Dataset as _InnerDataset, Metadata
from .boosting.gbdt import GBDT, create_boosting
from .log import LightGBMError  # noqa: F401  (canonical error type)


_sparse_densify_warned = False


def _warn_sparse_densify(shape, chunk_rows: int = 0) -> None:
    """One-time warning when a scipy-sparse matrix is materialized dense
    (training avoids this via Dataset.from_csc; the prediction paths
    densify bounded row chunks).  Reports the estimated dense bytes —
    the whole matrix, and the actual per-chunk peak when the caller
    densifies in row slabs."""
    global _sparse_densify_warned
    if _sparse_densify_warned:
        return
    _sparse_densify_warned = True
    from . import log
    est = int(shape[0]) * int(shape[1]) * 8
    if chunk_rows and chunk_rows < shape[0]:
        peak = int(chunk_rows) * int(shape[1]) * 8
        log.warning(
            f"densifying a scipy sparse matrix of shape {tuple(shape)} "
            f"in {chunk_rows}-row chunks (~{peak / 1e6:.1f} MB peak per "
            f"chunk; {est / 1e6:.1f} MB = {est} bytes if whole, as "
            "float64); pass training data as-is to Dataset so the "
            "binner streams CSC columns instead")
        return
    log.warning(
        f"densifying a scipy sparse matrix of shape {tuple(shape)} "
        f"(~{est / 1e6:.1f} MB = {est} bytes as float64); pass training "
        "data as-is to Dataset so the binner streams CSC columns "
        "instead")


def _is_scipy_sparse(data) -> bool:
    return hasattr(data, "toarray") and hasattr(data, "tocsc")


def _to_numpy(data) -> np.ndarray:
    if hasattr(data, "values"):  # pandas DataFrame/Series
        return np.asarray(data.values, dtype=np.float64)
    if isinstance(data, (list, tuple)):
        return np.asarray(data, dtype=np.float64)
    if _is_scipy_sparse(data):
        _warn_sparse_densify(data.shape)
        return np.asarray(data.toarray(), dtype=np.float64)
    return np.asarray(data, dtype=np.float64)


def _read_last_line(path: str) -> str:
    """The final line of a file, scanning backwards in 1 MB chunks — the
    pandas_categorical trailer is exactly one line and can be arbitrarily
    large (high-cardinality categories), so no fixed tail cap is safe."""
    with open(path, "rb") as f:
        f.seek(0, 2)
        end = f.tell()
        buf = b""
        pos = end
        while pos > 0:
            step = min(1 << 20, pos)
            pos -= step
            f.seek(pos)
            buf = f.read(step) + buf
            stripped = buf.rstrip(b"\n")
            nl = stripped.rfind(b"\n")
            if nl >= 0:
                return stripped[nl + 1:].decode(errors="replace")
        return buf.rstrip(b"\n").decode(errors="replace")


def _load_pandas_categorical(model_tail: str):
    """Read the `pandas_categorical:<json>` trailer the save path appends
    (the reference stores the same trailer, basic.py save_model).
    `model_tail` may be just the end of the model text."""
    import json
    marker = "pandas_categorical:"
    pos = model_tail.rfind("\n" + marker)
    if pos < 0:
        if not model_tail.startswith(marker):
            return None
        pos = -1
    line = model_tail[pos + 1:].splitlines()[0]
    try:
        return json.loads(line[len(marker):])
    except json.JSONDecodeError:
        from . import log
        log.warning("model file has a corrupt pandas_categorical trailer; "
                    "categorical DataFrame prediction will be unavailable")
        return None


def _apply_pandas_categorical(data, pandas_categorical):
    """Map a prediction DataFrame's category columns to the TRAINING
    category codes (reference basic.py predict-time pandas handling):
    category order may differ between frames, so codes are re-derived
    from the stored training category lists; unseen categories map to -1
    like pandas' own missing-code convention."""
    if not (hasattr(data, "dtypes") and hasattr(data, "columns")):
        return data
    cat_cols = [c for c in data.columns
                if str(data[c].dtype) == "category"]
    if not cat_cols:
        return data
    if not pandas_categorical or len(cat_cols) != len(pandas_categorical):
        raise ValueError(
            "prediction data has pandas categorical columns but the "
            "model carries no matching training category lists")
    df = data.copy()
    for col, cats in zip(cat_cols, pandas_categorical):
        df[col] = df[col].cat.set_categories(cats).cat.codes.astype(
            np.float64)
    return df


def _resolve_categorical(data, categorical_feature, feature_name):
    """pandas categorical columns -> codes + column index list
    (reference basic.py:192-260 pandas handling)."""
    cat_cols: List[int] = []
    pandas_categorical = None
    if hasattr(data, "dtypes") and hasattr(data, "columns"):
        import pandas as pd  # type: ignore
        df = data.copy()
        pandas_categorical = []
        for i, col in enumerate(df.columns):
            if str(df[col].dtype) == "category":
                pandas_categorical.append(list(df[col].cat.categories))
                df[col] = df[col].cat.codes.astype(np.float64)
                cat_cols.append(i)
        data = df
    if categorical_feature not in (None, "auto"):
        names = feature_name if feature_name not in (None, "auto") else None
        for c in categorical_feature:
            if isinstance(c, str) and names:
                cat_cols.append(names.index(c))
            elif isinstance(c, int):
                cat_cols.append(c)
    return data, sorted(set(cat_cols)), pandas_categorical


class Dataset:
    """Training/validation dataset with lazy construction."""

    def __init__(self, data, label=None, max_bin=None, reference=None,
                 weight=None, group=None, init_score=None, silent=False,
                 feature_name="auto", categorical_feature="auto", params=None,
                 free_raw_data=False):
        self.params: Dict[str, Any] = dict(params or {})
        if max_bin is not None:
            self.params.setdefault("max_bin", max_bin)
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.free_raw_data = free_raw_data
        self.pandas_categorical = None
        self._inner: Optional[_InnerDataset] = None
        self._raw_X: Optional[np.ndarray] = None

    # -- construction -------------------------------------------------------

    def construct(self, extra_params: Optional[Dict[str, Any]] = None
                  ) -> "Dataset":
        if self._inner is not None:
            return self
        merged = dict(self.params)
        if extra_params:
            for k, v in extra_params.items():
                merged.setdefault(k, v)
        cfg = config_from_params(merged)
        if isinstance(self.data, str):
            ref_inner = (self.reference.construct()._inner
                         if self.reference is not None else None)
            self._inner = _InnerDataset.from_file(self.data, cfg,
                                                  reference=ref_inner)
            self._raw_X = None
        else:
            data, cat_cols, self.pandas_categorical = _resolve_categorical(
                self.data, self.categorical_feature, self.feature_name)
            y = None if self.label is None else _to_numpy(self.label).reshape(-1)
            md = Metadata()
            if self.weight is not None:
                md.weights = _to_numpy(self.weight).reshape(-1).astype(np.float32)
            if self.group is not None:
                md.set_query_from_sizes(_to_numpy(self.group).reshape(-1)
                                        .astype(np.int64))
            if self.init_score is not None:
                md.init_score = _to_numpy(self.init_score).reshape(-1)
            names = None
            if self.feature_name not in (None, "auto"):
                names = list(self.feature_name)
            elif hasattr(self.data, "columns"):
                names = [str(c) for c in self.data.columns]
            ref_inner = (self.reference.construct()._inner
                         if self.reference is not None else None)
            if _is_scipy_sparse(data):
                # stream CSC columns into the binner — the full dense
                # matrix never materializes (one-time warning covers the
                # remaining densifying call sites, e.g. predict)
                self._inner = _InnerDataset.from_csc(
                    data, y, cfg, metadata=md, feature_names=names,
                    categorical_feature=cat_cols, reference=ref_inner)
                self._raw_X = data if not self.free_raw_data else None
                return self
            X = _to_numpy(data)
            if X.ndim == 1:
                X = X.reshape(-1, 1)
            self._inner = _InnerDataset(
                X, y, cfg, reference=ref_inner, metadata=md,
                feature_names=names, categorical_feature=cat_cols)
            self._raw_X = X if not self.free_raw_data else None
        return self

    # -- reference-style helpers -------------------------------------------

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, silent=False, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, silent=silent,
                       params=params or self.params)

    def set_label(self, label) -> None:
        self.label = label
        if self._inner is not None:
            self._inner.metadata.label = _to_numpy(label).astype(np.float32)

    def set_weight(self, weight) -> None:
        self.weight = weight
        if self._inner is not None:
            self._inner.metadata.weights = (
                None if weight is None
                else _to_numpy(weight).reshape(-1).astype(np.float32))

    def set_group(self, group) -> None:
        self.group = group
        if self._inner is not None:
            self._inner.metadata.set_query_from_sizes(
                _to_numpy(group).reshape(-1).astype(np.int64))

    def set_init_score(self, init_score) -> None:
        self.init_score = init_score
        if self._inner is not None:
            self._inner.metadata.init_score = (
                None if init_score is None
                else _to_numpy(init_score).reshape(-1))

    def get_label(self):
        self.construct()
        return np.asarray(self._inner.metadata.label)

    def get_weight(self):
        self.construct()
        return self._inner.metadata.weights

    def get_group(self):
        self.construct()
        qb = self._inner.metadata.query_boundaries
        return None if qb is None else np.diff(qb)

    def get_init_score(self):
        self.construct()
        return self._inner.metadata.init_score

    def save_binary(self, filename: str) -> "Dataset":
        """Serialize the constructed binned dataset (reference
        basic.py save_binary → LGBM_DatasetSaveBinary)."""
        self.construct()
        self._inner.save_binary(filename)
        return self

    def save_refbin(self, filename: str) -> "Dataset":
        """Persist only the frozen bin-mapper set — the serving
        registry's ``.refbin`` sidecar for ``serve_quantize=binned``
        with offline-trained models (docs/serving.md)."""
        self.construct()
        self._inner.save_refbin(filename)
        return self

    def num_data(self) -> int:
        self.construct()
        return self._inner.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._inner.num_total_features

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._inner.feature_names)

    def subset(self, used_indices: Sequence[int], params=None) -> "Dataset":
        """Row-subset dataset (reference Dataset.subset) — used by cv()."""
        self.construct()
        idx = np.asarray(used_indices, np.int64)
        if self._raw_X is None and not isinstance(self.data, str):
            raise LightGBMError("cannot subset when raw data was freed")
        if isinstance(self.data, str):
            raise LightGBMError("subset of file-backed Dataset not supported")
        sub = Dataset(self._raw_X[idx],
                      label=np.asarray(self.get_label())[idx],
                      reference=self, params=params or self.params)
        w = self.get_weight()
        if w is not None:
            sub.weight = np.asarray(w)[idx]
        return sub


class Booster:
    """The boosting model driver (reference basic.py:1160+)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent: bool = False):
        params = dict(params or {})
        self.params = params
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._valid_names: List[str] = []
        self._valid_data: List["Dataset"] = []
        self.pandas_categorical = None
        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("train_set should be Dataset instance")
            train_set.construct(params)
            cfg = config_from_params(params)
            self._gbdt = create_boosting(cfg)
            self._gbdt.reset_training_data(train_set._inner)
            self.train_set = train_set
            self.pandas_categorical = train_set.pandas_categorical
        elif model_file is not None:
            cfg = config_from_params(params)
            self._gbdt = create_boosting(cfg, model_file)  # loads the model
            self.train_set = None
            self.pandas_categorical = _load_pandas_categorical(
                _read_last_line(model_file))
        elif model_str is not None:
            cfg = config_from_params(params)
            self._gbdt = GBDT(cfg)
            self._gbdt.load_model_from_string(model_str)
            self.train_set = None
            self.pandas_categorical = _load_pandas_categorical(model_str)
        else:
            raise TypeError("need at least one of train_set, model_file, model_str")

    # -- training -----------------------------------------------------------

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct(self.params)
        self._gbdt.add_valid(data._inner, name)
        self._valid_names.append(name)
        self._valid_data.append(data)
        return self

    def update(self, train_set: Optional[Dataset] = None,
               fobj: Optional[Callable] = None) -> bool:
        """One boosting iteration; returns True if no further splits."""
        if train_set is not None and train_set is not self.train_set:
            train_set.construct(self.params)
            self._gbdt.reset_training_data(train_set._inner)
            self.train_set = train_set
        if fobj is None:
            return self._gbdt.train_one_iter(None, None, False)
        preds = self.__inner_raw_score()
        grad, hess = fobj(preds, self.train_set)
        return self.__boost(grad, hess)

    def __inner_raw_score(self) -> np.ndarray:
        sc = self._gbdt.train_score.get()
        return sc.reshape(-1)  # class-major flat, like the reference

    def __boost(self, grad, hess) -> bool:
        import jax.numpy as jnp
        K = self._gbdt.K
        n = self._gbdt.num_data
        g = np.asarray(grad, np.float32).reshape(K, n)
        h = np.asarray(hess, np.float32).reshape(K, n)
        return self._gbdt.train_one_iter(jnp.asarray(g), jnp.asarray(h), False)

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        return self._gbdt.current_iteration()

    def num_trees(self) -> int:
        return self._gbdt.num_trees

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        self.params.update(params)
        new_cfg = config_from_params(self.params)
        self._gbdt.config = new_cfg
        self._gbdt.shrinkage_rate = new_cfg.learning_rate
        if self._gbdt.train_set is not None:
            self._gbdt.learner.config = new_cfg
        return self

    # -- evaluation ---------------------------------------------------------

    def eval_train(self, feval=None):
        return self.__eval("training", self._gbdt.eval_train(), feval,
                           is_train=True)

    def eval_valid(self, feval=None):
        return self.__eval(None, self._gbdt.eval_valid(), feval,
                           is_train=False)

    def eval(self, data: Dataset, name: str, feval=None):
        if data is self.train_set:
            return self.eval_train(feval)
        return [r for r in self.eval_valid(feval) if r[0] == name]

    def __eval(self, name, results, feval, is_train):
        out = [(nm, metric, val, hib) for nm, metric, val, hib in results]
        if feval is None:
            return out

        def apply(ds_name, raw, dataset):
            ret = feval(raw, dataset)
            if ret is None:
                return
            if isinstance(ret, tuple):
                ret = [ret]
            for fname, val, hib in ret:
                out.append((ds_name, fname, val, hib))

        if is_train and self.train_set is not None:
            apply("training", self.__inner_raw_score(), self.train_set)
        elif not is_train:
            for vname, vdata, (gname, _, su, _) in zip(
                    self._valid_names, self._valid_data,
                    self._gbdt.valid_sets):
                apply(vname, np.asarray(su.get()).reshape(-1), vdata)
        return out

    # -- refit (upstream Booster.refit parity) ------------------------------

    def refit(self, data, label, decay_rate: float = 0.9, weight=None,
              **kwargs) -> "Booster":
        """Refit the existing model's LEAF VALUES on new data (tree
        structures unchanged) and return the refitted Booster; `self`
        is untouched (upstream ``Booster.refit(data, label,
        decay_rate)`` contract).

        new_leaf = decay_rate * old + (1 - decay_rate) * newton_output
        — the online-learning refit kernel (lightgbm_tpu/online/refit.py):
        one binned ensemble traversal routes every row, one jitted scan
        recomputes every tree's leaves.  kwargs become dataset/refit
        params (e.g. ``refit_min_rows``).
        """
        if label is None:
            raise ValueError("refit needs labels")
        params = dict(self.params)
        params.update(kwargs)
        new = Booster(params=params, model_str=self.model_to_string())
        data = _apply_pandas_categorical(data, self.pandas_categorical)
        X = _to_numpy(data)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        md = Metadata()
        if weight is not None:
            md.weights = _to_numpy(weight).reshape(-1).astype(np.float32)
        inner = _InnerDataset(X, _to_numpy(label).reshape(-1),
                              config_from_params(params), metadata=md)
        from .online.refit import refit_gbdt
        # route on the RAW feature values (upstream refit = pred_leaf
        # then LGBM_BoosterRefit): exact, where the binned router would
        # quantize thresholds falling inside this data's own bins
        leaf = new._gbdt.predict_leaf_index(X)
        refit_gbdt(new._gbdt, inner, decay_rate=decay_rate, leaf_idx=leaf)
        return new

    # -- prediction ---------------------------------------------------------

    def predict(self, data, num_iteration: int = -1, raw_score: bool = False,
                pred_leaf: bool = False, data_has_header: bool = False,
                is_reshape: bool = True) -> np.ndarray:
        if isinstance(data, str):
            from .dataset import parse_text_file
            X, _, _ = parse_text_file(data, data_has_header)
        else:
            data = _apply_pandas_categorical(data, self.pandas_categorical)
            X = _to_numpy(data)
            if X.ndim == 1:
                X = X.reshape(1, -1)
        if pred_leaf:
            return self._gbdt.predict_leaf_index(X, num_iteration)
        if raw_score:
            return self._gbdt.predict_raw(X, num_iteration)
        return self._gbdt.predict(X, num_iteration)

    # -- model io -----------------------------------------------------------

    def _pandas_categorical_trailer(self) -> str:
        import json
        if not self.pandas_categorical:
            return ""
        def _reject(o):
            # stringifying (e.g. Timestamps) would silently break the
            # save/load round trip: the reloaded strings no longer match
            # the frame's category values.  Refuse loudly instead (the
            # reference raises on unserializable categories too).
            raise LightGBMError(
                "categorical column categories must be JSON-native "
                f"(str/int/float/bool) to save the model; got {type(o)}")
        return ("pandas_categorical:"
                + json.dumps(self.pandas_categorical, default=_reject)
                + "\n")

    def save_model(self, filename: str, num_iteration: int = -1) -> "Booster":
        self._gbdt.save_model_to_file(filename, num_iteration)
        trailer = self._pandas_categorical_trailer()
        if trailer:
            with open(filename, "a") as f:
                f.write(trailer)
        return self

    def model_to_string(self, num_iteration: int = -1) -> str:
        return (self._gbdt.save_model_to_string(num_iteration)
                + self._pandas_categorical_trailer())

    def dump_model(self, num_iteration: int = -1) -> Dict:
        return self._gbdt.to_json()

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        if importance_type not in ("split", "gain"):
            raise ValueError(
                f"unknown importance_type {importance_type!r}; "
                "use 'split' or 'gain'")
        imp = self._gbdt.feature_importance(importance_type)
        names = self.feature_name()
        # split importance is int32 in the reference C API (int* out)
        dt = np.float64 if importance_type == "gain" else np.int32
        return np.array([imp.get(n, 0) for n in names], dt)

    def feature_name(self) -> List[str]:
        return list(self._gbdt.feature_names)

    def num_feature(self) -> int:
        return self._gbdt.max_feature_idx + 1

    def free_dataset(self) -> "Booster":
        self.train_set = None
        return self

    def __getstate__(self):
        state = {"params": self.params,
                 "model_str": self.model_to_string(),
                 "best_iteration": self.best_iteration,
                 "best_score": self.best_score}
        return state

    def __setstate__(self, state):
        self.params = state["params"]
        cfg = config_from_params(self.params)
        self._gbdt = GBDT(cfg)
        self._gbdt.load_model_from_string(state["model_str"])
        self.best_iteration = state.get("best_iteration", -1)
        self.best_score = state.get("best_score", {})
        self.train_set = None
        self._valid_names = []
        self._valid_data = []
        # category lists travel inside the model text trailer
        self.pandas_categorical = _load_pandas_categorical(
            state["model_str"])
