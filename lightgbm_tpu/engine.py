"""train() / cv() — the callback-driven training loop.

Mirrors /root/reference/python-package/lightgbm/engine.py: train()
(engine.py:17-203) with init_model continuation, client-side early stopping
via callbacks, evals_result recording; cv() (engine.py:279+) with
(stratified) folds.
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .basic import Booster, Dataset, LightGBMError
from . import callback as callback_mod


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[Union[Dataset, List[Dataset]]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None, feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          feature_name: str = "auto", categorical_feature: str = "auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[Dict] = None, verbose_eval: Union[bool, int] = True,
          learning_rates: Optional[Union[List[float], Callable]] = None,
          callbacks: Optional[List[Callable]] = None) -> Booster:
    params = dict(params or {})
    for alias in ("num_iterations", "num_iteration", "num_trees", "num_tree",
                  "num_rounds", "num_round"):
        if alias in params:
            num_boost_round = int(params.pop(alias))
            break
    if fobj is not None:
        params["objective"] = params.get("objective", "regression")
    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    booster = Booster(params=params, train_set=train_set)
    # continuation from an init model: seed scores with its predictions
    # (reference engine.py:91-98 _InnerPredictor path)
    if init_model is not None:
        if isinstance(init_model, str):
            init_booster = Booster(model_file=init_model, params=params)
        else:
            init_booster = init_model
        train_set.construct(params)
        init_raw = init_booster.predict(train_set._raw_X
                                        if train_set._raw_X is not None
                                        else train_set.data, raw_score=True)
        train_set.set_init_score(np.asarray(init_raw, np.float64).T.reshape(-1))
        booster = Booster(params=params, train_set=train_set)
        booster._init_trees = init_booster  # keep for prediction merge
        booster._gbdt.models = ([t for t in init_booster._gbdt.models]
                                + booster._gbdt.models)
        booster._gbdt.num_init_iteration = init_booster._gbdt.current_iteration()
        booster._gbdt.boost_from_average_used = (
            init_booster._gbdt.boost_from_average_used)

    # checkpoint/resume (docs/Robustness.md): `checkpoint_path` /
    # `checkpoint_interval` params give the Python API the same
    # kill-and-resume story as CLI task=train.  Resume happens BEFORE
    # add_valid so the restored model replays onto valid scores too.
    ckpt = booster._gbdt.config
    start_round = 0
    resumed_early_stop = False
    if ckpt.checkpoint_path:
        from .boosting.gbdt import load_checkpoint
        state = load_checkpoint(ckpt.checkpoint_path)
        if state is not None:
            g = booster._gbdt
            start_round = g.resume_from_checkpoint(state, g.train_set,
                                                   g.objective)
            resumed_early_stop = state.get("finished") == "early_stop"
            if resumed_early_stop:
                # the early-stopped run rolled its best_iteration back;
                # without this the skipped loop would fall through to
                # current_iteration() (the FULL tree count)
                booster.best_iteration = int(state.get("best_iteration", 0))
            elif 0 < start_round < num_boost_round and (
                    early_stopping_rounds or any(
                        getattr(cb, "order", None) == 30
                        for cb in (callbacks or []))):
                from . import log
                log.warning(
                    "checkpoint resume cannot restore the early-stopping "
                    "callback's best-score history; it restarts at the "
                    "resume point, so the stopping round may differ from "
                    "an uninterrupted run")

    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                name = "training"
            elif valid_names is not None and i < len(valid_names):
                name = valid_names[i]
            else:
                name = f"valid_{i}"
            if vs is not train_set:
                if vs.reference is None:
                    vs.reference = train_set
                booster.add_valid(vs, name)

    cbs = set(callbacks or [])
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval:
        cbs.add(callback_mod.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(callback_mod.early_stopping(
            early_stopping_rounds, verbose=bool(verbose_eval)))
    if evals_result is not None:
        cbs.add(callback_mod.record_evaluation(evals_result))
    if learning_rates is not None:
        cbs.add(callback_mod.reset_parameter(learning_rate=learning_rates))
    cbs_before = sorted((cb for cb in cbs
                         if getattr(cb, "before_iteration", False)),
                        key=lambda cb: getattr(cb, "order", 0))
    cbs_after = sorted((cb for cb in cbs
                        if not getattr(cb, "before_iteration", False)),
                       key=lambda cb: getattr(cb, "order", 0))

    has_valid = bool(booster._valid_names)
    train_in_valid = (valid_sets is not None
                      and any(vs is train_set for vs in valid_sets))
    # a checkpointed run that already early-stopped keeps its result; the
    # early-stopping callback's state is not checkpointable, so re-entering
    # the loop would retrain the tail until early stopping fires again
    if resumed_early_stop:
        start_round = num_boost_round
    stopped_early = resumed_early_stop
    for i in range(start_round, num_boost_round):
        env = callback_mod.CallbackEnv(
            model=booster, params=params, iteration=i, begin_iteration=0,
            end_iteration=num_boost_round, evaluation_result_list=None)
        for cb in cbs_before:
            cb(env)
        finished = booster.update(fobj=fobj)
        if (ckpt.checkpoint_path and ckpt.checkpoint_interval > 0
                and (i + 1) % ckpt.checkpoint_interval == 0):
            booster._gbdt.save_checkpoint(ckpt.checkpoint_path)
        evaluation_result_list = []
        if train_in_valid or params.get("is_training_metric"):
            evaluation_result_list.extend(booster.eval_train(feval))
        if has_valid:
            evaluation_result_list.extend(booster.eval_valid(feval))
        env = env._replace(evaluation_result_list=evaluation_result_list)
        try:
            for cb in cbs_after:
                cb(env)
        except callback_mod.EarlyStopException as e:
            booster.best_iteration = e.best_iteration + 1
            stopped_early = True
            break
        if finished:
            break
    if ckpt.checkpoint_path and ckpt.checkpoint_interval > 0:
        # final snapshot (mirrors the CLI): a rerun of this completed
        # call resumes past the loop instead of retraining the tail
        # since the last periodic snapshot
        booster._gbdt.save_checkpoint(ckpt.checkpoint_path, extra={
            "finished": "early_stop" if stopped_early else "complete",
            "best_iteration": int(booster.best_iteration)})
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration()
    return booster


def _make_n_folds(full_data: Dataset, nfold: int, params, seed: int,
                  stratified: bool = False, shuffle: bool = True):
    full_data.construct(params)
    num_data = full_data.num_data()
    rng = np.random.RandomState(seed)
    if stratified:
        label = np.asarray(full_data.get_label())
        if shuffle:
            # random order within each label class, then round-robin:
            # folds stay stratified but membership is randomized
            order = np.lexsort((rng.permutation(num_data), label))
        else:
            order = np.argsort(label, kind="stable")
        folds_idx = [order[i::nfold] for i in range(nfold)]
    else:
        idx = np.arange(num_data)
        if shuffle:
            rng.shuffle(idx)
        folds_idx = np.array_split(idx, nfold)
    for k in range(nfold):
        test_idx = np.sort(np.asarray(folds_idx[k]))
        train_mask = np.ones(num_data, bool)
        train_mask[test_idx] = False
        train_idx = np.flatnonzero(train_mask)
        yield train_idx, test_idx


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 10,
       folds=None, nfold: int = 5, stratified: bool = False,
       shuffle: bool = True, metrics=None, fobj=None, feval=None,
       init_model=None, feature_name="auto", categorical_feature="auto",
       early_stopping_rounds=None, fpreproc=None, verbose_eval=None,
       show_stdv: bool = True, seed: int = 0,
       callbacks=None) -> Dict[str, List[float]]:
    """K-fold cross validation (reference engine.py:279+).

    Returns {metric-name-mean: [...], metric-name-stdv: [...]}.
    """
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    train_set.construct(params)
    if folds is None:
        folds = list(_make_n_folds(train_set, nfold, params, seed, stratified,
                                   shuffle))
    boosters = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(train_idx, params)
        te = train_set.subset(test_idx, params)
        if fpreproc is not None:
            tr, te, params = fpreproc(tr, te, params.copy())
        bst = Booster(params=params, train_set=tr)
        bst.add_valid(te, "valid")
        boosters.append(bst)

    results = collections.defaultdict(list)
    best_score: Dict[str, float] = {}
    best_it: Dict[str, int] = {}
    for i in range(num_boost_round):
        agg = collections.defaultdict(list)
        for bst in boosters:
            bst.update(fobj=fobj)
            for _, name, val, hib in bst.eval_valid(feval):
                agg[(name, hib)].append(val)
        line = {}
        for (name, hib), vals in agg.items():
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results[name + "-mean"].append(mean)
            results[name + "-stdv"].append(std)
            line[(name, hib)] = mean
        if verbose_eval:
            msg = "\t".join(f"cv_agg {n}-mean: {results[n + '-mean'][-1]:g}"
                            for n in set(k[0] for k in agg))
            print(f"[{i + 1}]\t{msg}")
        if early_stopping_rounds:
            # Reference semantics (engine.py:414-418 + callback.py:189-202):
            # per-metric best tracking; the FIRST metric in eval order whose
            # no-improvement window hits the limit stops the run, and every
            # history is truncated at THAT metric's best iteration.
            stop_at = None
            for (name, hib), mean in line.items():
                score = mean if hib else -mean
                if name not in best_score or score > best_score[name]:
                    best_score[name] = score
                    best_it[name] = i
                elif i - best_it[name] >= early_stopping_rounds:
                    stop_at = best_it[name] + 1
                    break
            if stop_at is not None:
                for key in results:
                    del results[key][stop_at:]
                break
    return dict(results)
