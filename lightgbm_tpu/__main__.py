"""`python -m lightgbm_tpu config=train.conf` — the CLI entry point
(reference src/main.cpp:4-22)."""
import sys

from .application import main

sys.exit(main())
