"""Multi-host bootstrap: the reference's Network::Init for the TPU world.

The reference brings up a TCP/MPI mesh from `num_machines` +
`machine_list_file` (+ `local_listen_port`) at training start
(/root/reference/src/application/application.cpp:185-197,
src/network/linkers_socket.cpp:73-110: "ip port" lines, optional
`rank=<n>` override, rank otherwise assigned by list order).

On TPU the transport is XLA's ICI/DCN collectives; what remains of the
network layer is PROCESS bootstrap: every host calls
`jax.distributed.initialize(coordinator, num_processes, process_id)`, after
which `jax.devices()` is the GLOBAL device list and the mesh learners
(learner/fused.py make_mesh) shard over all hosts' chips with zero further
changes — psum/all_gather ride ICI within a slice and DCN across slices.

Launch recipe (2 hosts x 4 chips each):
    # mlist.txt on both hosts:
    #   10.0.0.1 12400
    #   10.0.0.2 12400
    host0$ python -m lightgbm_tpu config=train.conf num_machines=2 \
               machine_list_file=mlist.txt        # rank inferred: local ip
    host1$ python -m lightgbm_tpu config=train.conf num_machines=2 \
               machine_list_file=mlist.txt
    # rank can be forced per host: LIGHTGBM_TPU_MACHINE_RANK=1 or a
    # `rank=1` suffix on the machine line, like the reference's parser.
"""
from __future__ import annotations

import os
import socket
import warnings
from typing import List, Optional, Tuple

_initialized = False


def parse_machine_list(path: str) -> List[Tuple[str, int, Optional[int]]]:
    """`ip port [rank=<n>]` per line (linkers_socket.cpp:73-110)."""
    out: List[Tuple[str, int, Optional[int]]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            rank = None
            toks = []
            for t in line.replace(",", " ").split():
                if t.startswith("rank="):
                    rank = int(t[5:])
                else:
                    toks.append(t)
            if len(toks) < 2:
                raise ValueError(
                    f"machine_list line needs 'ip port': {line!r}")
            out.append((toks[0], int(toks[1]), rank))
    return out


def _local_addresses() -> List[str]:
    addrs = {"127.0.0.1", "localhost"}
    try:
        hostname = socket.gethostname()
        addrs.add(hostname)
        for info in socket.getaddrinfo(hostname, None):
            addrs.add(info[4][0])
    except OSError:
        pass
    return list(addrs)


def resolve_rank(machines: List[Tuple[str, int, Optional[int]]]) -> int:
    """This process's rank: env override, then explicit rank= entries,
    then local-address match (the reference matches the local ip against
    the list the same way, linkers_socket.cpp:84-103)."""
    env = os.environ.get("LIGHTGBM_TPU_MACHINE_RANK")
    if env is not None:
        return int(env)
    local = set(_local_addresses())
    matches = [(i, rank) for i, (ip, _port, rank) in enumerate(machines)
               if ip in local]
    if len(matches) > 1:
        # same host listed more than once (multi-process single host):
        # the address alone cannot disambiguate the processes
        raise ValueError(
            "machine_list has multiple local entries; set "
            "LIGHTGBM_TPU_MACHINE_RANK per process to disambiguate")
    if matches:
        i, rank = matches[0]
        return rank if rank is not None else i
    raise ValueError(
        "cannot determine this machine's rank: none of the machine_list "
        "addresses are local; set LIGHTGBM_TPU_MACHINE_RANK")


def init_distributed(num_machines: int, machine_list_file: str = "",
                     local_listen_port: int = 12400) -> bool:
    """Bring up the multi-process JAX runtime.  Returns True if a
    multi-host world was initialized (idempotent; False for single-host).

    Maps the reference config exactly: `num_machines` processes, the
    coordinator is the FIRST machine in the list (reference rank 0), and
    `local_listen_port` is the fallback port when no list file is given
    (single-host multi-process testing: coordinator on localhost)."""
    global _initialized
    if num_machines <= 1:
        return False
    if _initialized:
        return True
    import jax
    if machine_list_file:
        machines = parse_machine_list(machine_list_file)
        if len(machines) != num_machines:
            raise ValueError(
                f"machine_list_file has {len(machines)} entries, "
                f"num_machines={num_machines}")
        rank = resolve_rank(machines)
        # the coordinator is the machine whose EFFECTIVE rank is 0 —
        # rank= overrides can move rank 0 away from the first list line
        coord_machine = machines[0]
        for i, m in enumerate(machines):
            eff = m[2] if m[2] is not None else i
            if eff == 0:
                coord_machine = m
                break
        coord = f"{coord_machine[0]}:{coord_machine[1]}"
    else:
        rank_env = os.environ.get("LIGHTGBM_TPU_MACHINE_RANK")
        if rank_env is None:
            warnings.warn(
                "num_machines>1 without machine_list_file or "
                "LIGHTGBM_TPU_MACHINE_RANK: assuming single-host test "
                "mode, skipping jax.distributed")
            return False
        rank = int(rank_env)
        coord = f"127.0.0.1:{local_listen_port}"
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=num_machines,
                               process_id=rank)
    _initialized = True
    return True


def maybe_init_from_config(cfg) -> bool:
    """Application entry (application.cpp:185-197 Network::Init analog)."""
    return init_distributed(int(getattr(cfg, "num_machines", 0) or 0),
                            getattr(cfg, "machine_list_file", ""),
                            int(getattr(cfg, "local_listen_port", 12400)))


# ---------------------------------------------------------------------------
# Distributed ingestion: pre-partitioned rows + global bin mappers
# (reference dataset_loader.cpp:554-659 row assignment and :733-833
# distributed bin finding)
# ---------------------------------------------------------------------------

def local_row_slice(n: int) -> slice:
    """This process's contiguous row block of an n-row dataset —
    the TPU-era analog of the reference's pre-partition row assignment
    (contiguous blocks instead of mod-assignment so binned stores stay
    gather-free)."""
    import jax
    world = jax.process_count()
    rank = jax.process_index()
    per = (n + world - 1) // world
    return slice(min(rank * per, n), min((rank + 1) * per, n))


def allgather_f64(arr) -> "np.ndarray":
    """Process-allgather a float64 array BIT-EXACTLY.

    jax with x64 disabled silently rounds float64 collective payloads to
    float32 — enough to perturb bin boundaries and init scores in their
    last ulps, which breaks the multi-process == single-process model
    equality the data-parallel scheme promises.  uint32 words survive
    the collective unchanged.  Returns [world, *arr.shape] float64."""
    import numpy as np
    from jax.experimental import multihost_utils
    a = np.ascontiguousarray(np.asarray(arr, np.float64))
    words = a.view(np.uint32)
    out = np.asarray(multihost_utils.process_allgather(words))
    # process_allgather returns [W, *words.shape] on a multi-process
    # world but the bare words.shape when W == 1 — normalize so the
    # documented [world, *arr.shape] contract holds for every caller
    out = np.ascontiguousarray(out).reshape((-1,) + words.shape)
    return out.view(np.float64)


def find_bin_mappers_distributed(local_sample, cfg, categorical=(),
                                 return_sample=False):
    """Global BinMappers from per-process local samples.

    The reference shards FEATURES across machines, finds local mappers,
    and allgathers the serialized results (dataset_loader.cpp:733-833).
    Here the sample rows are allgathered instead (one collective on a
    [S, F] float array) and every process derives identical mappers from
    the identical global sample — no mapper serialization format needed,
    determinism by construction.

    return_sample=True also returns the identical-on-every-rank global
    sample, so rank-consistent derived decisions (the EFB bundle plan)
    can be computed from it without a second collective."""
    import jax
    import numpy as np
    from .binning import find_bin_mappers

    if jax.process_count() == 1:
        m = find_bin_mappers(
            local_sample, cfg.max_bin, cfg.min_data_in_bin,
            cfg.min_data_in_leaf, categorical=categorical,
            sample_cnt=len(local_sample), seed=cfg.data_random_seed)
        return (m, local_sample) if return_sample else m
    from jax.experimental import multihost_utils

    # pad local samples to one shape (process sample sizes can differ by
    # one chunk); true per-process sizes travel alongside so padding rows
    # are sliced away exactly (no sentinel values — data may contain any)
    sizes = multihost_utils.process_allgather(
        np.array([len(local_sample)], np.int64)).reshape(-1)
    smax = int(sizes.max())
    padded = np.zeros((smax, local_sample.shape[1]), np.float64)
    padded[: len(local_sample)] = local_sample
    gathered = allgather_f64(padded)                      # [W, smax, F]
    flat = np.concatenate([gathered[w, : int(sizes[w])]
                           for w in range(gathered.shape[0])])
    cap = int(cfg.bin_construct_sample_cnt)
    if len(flat) > cap:
        idx = np.random.RandomState(cfg.data_random_seed).choice(
            len(flat), cap, replace=False)
        flat = flat[np.sort(idx)]
    m = find_bin_mappers(
        flat, cfg.max_bin, cfg.min_data_in_bin, cfg.min_data_in_leaf,
        categorical=categorical, sample_cnt=len(flat),
        seed=cfg.data_random_seed)
    return (m, flat) if return_sample else m
