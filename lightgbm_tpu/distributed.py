"""Multi-host bootstrap: the reference's Network::Init for the TPU world.

The reference brings up a TCP/MPI mesh from `num_machines` +
`machine_list_file` (+ `local_listen_port`) at training start
(/root/reference/src/application/application.cpp:185-197,
src/network/linkers_socket.cpp:73-110: "ip port" lines, optional
`rank=<n>` override, rank otherwise assigned by list order).

On TPU the transport is XLA's ICI/DCN collectives; what remains of the
network layer is PROCESS bootstrap: every host calls
`jax.distributed.initialize(coordinator, num_processes, process_id)`, after
which `jax.devices()` is the GLOBAL device list and the mesh learners
(learner/fused.py make_mesh) shard over all hosts' chips with zero further
changes — psum/all_gather ride ICI within a slice and DCN across slices.

Launch recipe (2 hosts x 4 chips each):
    # mlist.txt on both hosts:
    #   10.0.0.1 12400
    #   10.0.0.2 12400
    host0$ python -m lightgbm_tpu config=train.conf num_machines=2 \
               machine_list_file=mlist.txt        # rank inferred: local ip
    host1$ python -m lightgbm_tpu config=train.conf num_machines=2 \
               machine_list_file=mlist.txt
    # rank can be forced per host: LIGHTGBM_TPU_MACHINE_RANK=1 or a
    # `rank=1` suffix on the machine line, like the reference's parser.
"""
from __future__ import annotations

import os
import socket
import warnings
from typing import List, Optional, Tuple

_initialized = False


def parse_machine_list(path: str) -> List[Tuple[str, int, Optional[int]]]:
    """`ip port [rank=<n>]` per line (linkers_socket.cpp:73-110)."""
    out: List[Tuple[str, int, Optional[int]]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            rank = None
            toks = []
            for t in line.replace(",", " ").split():
                if t.startswith("rank="):
                    rank = int(t[5:])
                else:
                    toks.append(t)
            if len(toks) < 2:
                raise ValueError(
                    f"machine_list line needs 'ip port': {line!r}")
            out.append((toks[0], int(toks[1]), rank))
    return out


def _local_addresses() -> List[str]:
    addrs = {"127.0.0.1", "localhost"}
    try:
        hostname = socket.gethostname()
        addrs.add(hostname)
        for info in socket.getaddrinfo(hostname, None):
            addrs.add(info[4][0])
    except OSError:
        pass
    return list(addrs)


def resolve_rank(machines: List[Tuple[str, int, Optional[int]]]) -> int:
    """This process's rank: env override, then explicit rank= entries,
    then local-address match (the reference matches the local ip against
    the list the same way, linkers_socket.cpp:84-103)."""
    env = os.environ.get("LIGHTGBM_TPU_MACHINE_RANK")
    if env is not None:
        return int(env)
    local = set(_local_addresses())
    matches = [(i, rank) for i, (ip, _port, rank) in enumerate(machines)
               if ip in local]
    if len(matches) > 1:
        # same host listed more than once (multi-process single host):
        # the address alone cannot disambiguate the processes
        raise ValueError(
            "machine_list has multiple local entries; set "
            "LIGHTGBM_TPU_MACHINE_RANK per process to disambiguate")
    if matches:
        i, rank = matches[0]
        return rank if rank is not None else i
    raise ValueError(
        "cannot determine this machine's rank: none of the machine_list "
        "addresses are local; set LIGHTGBM_TPU_MACHINE_RANK")


def init_distributed(num_machines: int, machine_list_file: str = "",
                     local_listen_port: int = 12400) -> bool:
    """Bring up the multi-process JAX runtime.  Returns True if a
    multi-host world was initialized (idempotent; False for single-host).

    Maps the reference config exactly: `num_machines` processes, the
    coordinator is the FIRST machine in the list (reference rank 0), and
    `local_listen_port` is the fallback port when no list file is given
    (single-host multi-process testing: coordinator on localhost)."""
    global _initialized
    if num_machines <= 1:
        return False
    if _initialized:
        return True
    import jax
    if machine_list_file:
        machines = parse_machine_list(machine_list_file)
        if len(machines) != num_machines:
            raise ValueError(
                f"machine_list_file has {len(machines)} entries, "
                f"num_machines={num_machines}")
        rank = resolve_rank(machines)
        # the coordinator is the machine whose EFFECTIVE rank is 0 —
        # rank= overrides can move rank 0 away from the first list line
        coord_machine = machines[0]
        for i, m in enumerate(machines):
            eff = m[2] if m[2] is not None else i
            if eff == 0:
                coord_machine = m
                break
        coord = f"{coord_machine[0]}:{coord_machine[1]}"
    else:
        rank_env = os.environ.get("LIGHTGBM_TPU_MACHINE_RANK")
        if rank_env is None:
            warnings.warn(
                "num_machines>1 without machine_list_file or "
                "LIGHTGBM_TPU_MACHINE_RANK: assuming single-host test "
                "mode, skipping jax.distributed")
            return False
        rank = int(rank_env)
        coord = f"127.0.0.1:{local_listen_port}"
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=num_machines,
                               process_id=rank)
    _initialized = True
    return True


def maybe_init_from_config(cfg) -> bool:
    """Application entry (application.cpp:185-197 Network::Init analog)."""
    return init_distributed(int(getattr(cfg, "num_machines", 0) or 0),
                            getattr(cfg, "machine_list_file", ""),
                            int(getattr(cfg, "local_listen_port", 12400)))


# ---------------------------------------------------------------------------
# Distributed ingestion: pre-partitioned rows + global bin mappers
# (reference dataset_loader.cpp:554-659 row assignment and :733-833
# distributed bin finding)
# ---------------------------------------------------------------------------

def local_row_slice(n: int) -> slice:
    """This process's contiguous row block of an n-row dataset —
    the TPU-era analog of the reference's pre-partition row assignment
    (contiguous blocks instead of mod-assignment so binned stores stay
    gather-free)."""
    import jax
    world = jax.process_count()
    rank = jax.process_index()
    per = (n + world - 1) // world
    return slice(min(rank * per, n), min((rank + 1) * per, n))


def allgather_f64(arr) -> "np.ndarray":
    """Process-allgather a float64 array BIT-EXACTLY.

    jax with x64 disabled silently rounds float64 collective payloads to
    float32 — enough to perturb bin boundaries and init scores in their
    last ulps, which breaks the multi-process == single-process model
    equality the data-parallel scheme promises.  uint32 words survive
    the collective unchanged.  Returns [world, *arr.shape] float64."""
    import numpy as np
    from jax.experimental import multihost_utils
    a = np.ascontiguousarray(np.asarray(arr, np.float64))
    words = a.view(np.uint32)
    out = np.asarray(multihost_utils.process_allgather(words))
    # process_allgather returns [W, *words.shape] on a multi-process
    # world but the bare words.shape when W == 1 — normalize so the
    # documented [world, *arr.shape] contract holds for every caller
    out = np.ascontiguousarray(out).reshape((-1,) + words.shape)
    return out.view(np.float64)


def resolve_bin_find(cfg, n_sample_global: int, world: int = 1) -> str:
    """Resolve the `bin_find` knob to the path distributed bin finding
    runs.  "allgather" is the validated exact path (every rank derives
    mappers from the identical allgathered global sample);  "sketch"
    merges per-host quantile summaries (sharded/sketch.py) so no host
    ever materializes the global sample.  "auto" stays exact while the
    combined sample fits the bin-construction budget — the
    pre-partition loader caps each rank at `budget // world + 1` rows,
    so the `+ world` slack keeps its combined sample INSIDE the exact
    path (default distributed binning stays the validated allgather;
    sketches engage only when a caller feeds samples genuinely beyond
    the budget, or explicitly via bin_find=sketch)."""
    mode = getattr(cfg, "bin_find", "auto")
    if mode == "auto":
        budget = int(cfg.bin_construct_sample_cnt) + max(int(world), 1)
        return "sketch" if n_sample_global > budget else "allgather"
    return mode


def _gathered_sizes(n_local: int) -> "np.ndarray":
    import numpy as np
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(
        np.array([n_local], np.int64))).reshape(-1)


def _allgather_rows(local_rows, smax: int, sizes) -> "np.ndarray":
    """Allgather variable-length row blocks (padded to `smax`, sliced
    back by the true sizes) into one concatenated array."""
    import numpy as np
    padded = np.zeros((smax, local_rows.shape[1]), np.float64)
    padded[: len(local_rows)] = local_rows
    gathered = allgather_f64(padded)                      # [W, smax, F]
    return np.concatenate([gathered[w, : int(sizes[w])]
                           for w in range(gathered.shape[0])])


def find_bin_mappers_sketch(local_sample, cfg, categorical=(),
                            return_sample=False):
    """Global BinMappers by MERGING per-host quantile sketches — the
    distributed bin finding of the reference's Network layer
    (dataset_loader.cpp:733-833) rebuilt on mergeable summaries
    (arXiv:1706.08359 §4, arXiv:1806.11248 §5): each host summarizes
    its local sample into O(F / eps) weighted entries, ONE small
    allgather exchanges the fixed-width summaries, and every rank
    derives identical mappers from the deterministic rank-order merge.
    No host ever materializes the global sample.

    return_sample=True returns a BOUNDED plan sample alongside (for the
    EFB bundle planner, which needs row-level co-occurrence): each rank
    contributes at most BUNDLE_PLAN_SAMPLE_CNT / world rows, so the
    gathered sample is O(50k) rows regardless of the dataset — never
    the global sample."""
    import jax
    import numpy as np
    from .sharded.sketch import SketchSet, sketch_columns

    world = jax.process_count()
    ss = sketch_columns(local_sample, cfg, categorical=categorical)
    if world > 1:
        packed = ss.pack()                     # [F+1, 2*cap+4]
        stack = allgather_f64(packed)          # [W, F+1, 2*cap+4]
        ss = SketchSet.merge_packed(stack, categorical=categorical)
    mappers = ss.mappers_from_config(cfg)
    if not return_sample:
        return mappers
    from .dataset import BUNDLE_PLAN_SAMPLE_CNT
    cap = max(BUNDLE_PLAN_SAMPLE_CNT // max(world, 1), 1)
    plan_local = np.ascontiguousarray(
        np.asarray(local_sample, np.float64)[:cap])
    if world > 1:
        sizes = _gathered_sizes(len(plan_local))
        plan_sample = _allgather_rows(plan_local, int(sizes.max()), sizes)
    else:
        plan_sample = plan_local
    return mappers, plan_sample


def find_bin_mappers_distributed(local_sample, cfg, categorical=(),
                                 return_sample=False):
    """Global BinMappers from per-process local samples.

    Two paths behind the `bin_find` knob (resolve_bin_find):

    - "allgather" (the validated exact path): the sample rows are
      allgathered (one collective on a [S, F] float array) and every
      process derives identical mappers from the identical global
      sample — no mapper serialization format needed, determinism by
      construction.  The reference instead shards FEATURES across
      machines and allgathers serialized mappers
      (dataset_loader.cpp:733-833).
    - "sketch": per-host mergeable quantile summaries exchanged in ONE
      O(F / eps) collective (find_bin_mappers_sketch) — the path that
      scales past the sample budget, because no host ever holds the
      global sample.

    return_sample=True also returns an identical-on-every-rank sample,
    so rank-consistent derived decisions (the EFB bundle plan) can be
    computed from it without a second collective — the full global
    sample on the allgather path, a bounded O(50k)-row plan sample on
    the sketch path."""
    import jax
    import numpy as np
    from .binning import find_bin_mappers

    world = jax.process_count()
    if world > 1:
        sizes = _gathered_sizes(len(local_sample))
        n_global = int(sizes.sum())
    else:
        sizes = np.array([len(local_sample)], np.int64)
        n_global = len(local_sample)
    if resolve_bin_find(cfg, n_global, world) == "sketch":
        return find_bin_mappers_sketch(local_sample, cfg,
                                       categorical=categorical,
                                       return_sample=return_sample)

    if world == 1:
        m = find_bin_mappers(
            local_sample, cfg.max_bin, cfg.min_data_in_bin,
            cfg.min_data_in_leaf, categorical=categorical,
            sample_cnt=len(local_sample), seed=cfg.data_random_seed)
        return (m, local_sample) if return_sample else m

    # pad local samples to one shape (process sample sizes can differ by
    # one chunk); true per-process sizes travel alongside so padding rows
    # are sliced away exactly (no sentinel values — data may contain any)
    flat = _allgather_rows(local_sample, int(sizes.max()), sizes)
    cap = int(cfg.bin_construct_sample_cnt)
    if len(flat) > cap:
        idx = np.random.RandomState(cfg.data_random_seed).choice(
            len(flat), cap, replace=False)
        flat = flat[np.sort(idx)]
    m = find_bin_mappers(
        flat, cfg.max_bin, cfg.min_data_in_bin, cfg.min_data_in_leaf,
        categorical=categorical, sample_cnt=len(flat),
        seed=cfg.data_random_seed)
    return (m, flat) if return_sample else m
