"""Shared stdlib HTTP-server base for the serving and router tiers.

``ThreadingHTTPServer.shutdown()`` only stops the accept loop: handler
threads serving keep-alive (HTTP/1.1) clients keep answering on their
ESTABLISHED sockets until the *client* hangs up.  An in-process
``stop()`` must instead look like a process kill — every live socket
severed, clients seeing a transport error — or the router's breaker
drills (and its per-thread backend connection pool) would observe a
"dead" backend that still answers through zombie handler threads.
Stdlib-only on purpose: the router tier imports this without pulling
numpy/jax.
"""
from __future__ import annotations

import socket
import threading
from http.server import ThreadingHTTPServer


class SeveringHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that tracks established connections so
    ``close_client_connections`` can sever them all at stop."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._live_conns = set()
        self._live_lock = threading.Lock()

    def process_request(self, request, client_address):
        with self._live_lock:
            self._live_conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._live_lock:
            self._live_conns.discard(request)
        super().shutdown_request(request)

    def close_client_connections(self) -> None:
        """Sever every established connection — idle keep-alive AND
        in-flight.  ``socket.shutdown`` only (never ``close``): the
        handler thread still owns the fd and closes it on its own way
        out via ``shutdown_request``."""
        with self._live_lock:
            conns = list(self._live_conns)
            self._live_conns.clear()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
