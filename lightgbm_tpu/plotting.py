"""Plotting utilities (reference python-package/lightgbm/plotting.py:22+):
feature importance, metric curves during training, tree structure.

matplotlib is required for plot_*; graphviz (optional in this image) for
create_tree_digraph/plot_tree — a clear ImportError is raised when absent.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt  # noqa: F401
        return plt
    except ImportError as e:  # pragma: no cover
        raise ImportError("matplotlib is required for plotting") from e


def _to_booster(booster) -> Booster:
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be a Booster or LGBMModel")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple] = None, ylim: Optional[Tuple] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features", max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, grid: bool = True, **kwargs):
    """Horizontal bar plot of split-count feature importance
    (reference plotting.py:22-106)."""
    plt = _check_matplotlib()
    bst = _to_booster(booster)
    importance = bst.feature_importance()
    names = bst.feature_name()
    tuples = sorted(zip(names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [t for t in tuples if t[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("cannot plot importance: no nonzero importances")
    labels, values = zip(*tuples)

    if ax is None:
        _, ax = plt.subplots(1, 1)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, str(int(x)), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    else:
        ax.set_ylim(-1, len(values))
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster_or_evals_result, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None, title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "auto",
                grid: bool = True):
    """Plot recorded eval results (reference plotting.py:109-200).  Accepts
    the dict produced by `evals_result`/`record_evaluation` or a fitted
    sklearn model with `evals_result_`."""
    plt = _check_matplotlib()
    if isinstance(booster_or_evals_result, LGBMModel):
        eval_results = booster_or_evals_result.evals_result_
    elif isinstance(booster_or_evals_result, dict):
        eval_results = booster_or_evals_result
    else:
        raise TypeError("plot_metric needs an evals_result dict or a "
                        "fitted sklearn model")
    if not eval_results:
        raise ValueError("eval results are empty")
    if ax is None:
        _, ax = plt.subplots(1, 1)
    names = dataset_names or list(eval_results.keys())
    if names[0] not in eval_results:
        raise ValueError(f"dataset {names[0]!r} not in eval results "
                         f"(have: {list(eval_results)})")
    msets = eval_results[names[0]]
    if metric is None:
        metric = next(iter(msets.keys()))
    for name in names:
        if metric in eval_results.get(name, {}):
            results = eval_results[name][metric]
            ax.plot(range(1, len(results) + 1), results, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(metric if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        name=None, comment=None, **kwargs):
    """Graphviz digraph of one tree (reference plotting.py:203-300)."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("graphviz is required for tree plotting") from e
    bst = _to_booster(booster)
    model = bst.dump_model()
    if tree_index >= len(model["tree_info"]):
        raise IndexError(f"tree_index {tree_index} out of range")
    tree_info = model["tree_info"][tree_index]
    show_info = show_info or []
    graph = Digraph(name=name, comment=comment, **kwargs)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            nid = f"split{node['split_index']}"
            # dump_model carries the reference's JSON type names
            # ("no_greater"/"is", tree.cpp:347); plot the operator symbol
            op = {"no_greater": "<=", "is": "=="}.get(
                node.get("decision_type"), "<=")
            label = (f"feature {node['split_feature']}\n"
                     f"{op} {node['threshold']:g}")
            if "split_gain" in show_info:
                label += f"\ngain: {node['split_gain']:g}"
            if "internal_count" in show_info and "internal_count" in node:
                label += f"\ncount: {node['internal_count']}"
            graph.node(nid, label=label)
            add(node["left_child"], nid, "yes")
            add(node["right_child"], nid, "no")
        else:
            nid = f"leaf{node['leaf_index']}"
            label = f"leaf {node['leaf_index']}: {node['leaf_value']:g}"
            if "leaf_count" in show_info and "leaf_count" in node:
                label += f"\ncount: {node['leaf_count']}"
            graph.node(nid, label=label)
        if parent is not None:
            graph.edge(parent, nid, decision)

    add(tree_info["tree_structure"])
    return graph


def plot_tree(booster, tree_index: int = 0, ax=None, figsize=None,
              show_info=None, **kwargs):
    """Render one tree into a matplotlib axis via graphviz
    (reference plotting.py:303-427)."""
    plt = _check_matplotlib()
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, **kwargs)
    import io
    try:
        s = graph.pipe(format="png")
    except Exception as e:  # pragma: no cover - graphviz binary missing
        raise RuntimeError("graphviz executable is required to render "
                           "trees") from e
    import matplotlib.image as mpimg
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    img = mpimg.imread(io.BytesIO(s))
    ax.imshow(img)
    ax.axis("off")
    return ax
