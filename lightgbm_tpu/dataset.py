"""Binned Dataset: the device-resident training matrix.

TPU re-design of the reference IO layer (/root/reference/src/io/):

- `Metadata` — labels/weights/query boundaries/init score incl. the
  `<data>.weight` / `<data>.init` / `<data>.query` side files
  (metadata.cpp:372-437).
- text `Parser` — CSV / TSV / LibSVM auto-detection (parser.cpp).
- `Dataset` — instead of the reference's FeatureGroup/DenseBin/SparseBin/
  OrderedBin class zoo (dense_bin.hpp, sparse_bin.hpp, ordered_sparse_bin.hpp),
  ONE dense `[num_used_features, num_rows]` uint8/uint16 array of bin ids,
  padded with a sentinel row slot so masked gathers are branch-free.  Binned
  values are ~1 byte each, so even Epsilon-scale data fits HBM dense; there
  is no sparse path on TPU (SURVEY.md §7 "start dense").

Validation datasets are binned with the training set's BinMappers
(reference Dataset::CheckAlign + LoadFromFileAlignWithOtherDataset,
dataset_loader.cpp:220-261).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .binning import (BinMapper, BundlePlan, find_bin_mappers,
                      plan_bundles, CATEGORICAL)
from .config import Config

# ----------------------------------------------------------------------------
# Sparse binned store (docs/Sparse.md)
# ----------------------------------------------------------------------------

def nnz_capacity_tier(n: int, base: int = 4) -> int:
    """Smallest power-of-two >= n (floor `base`): the ELL row width R of
    a sparse store.  Device kernels key compiled shapes on R, so
    datasets whose max per-row entry count lands in the same tier share
    every compiled program — the ladder bounds compiles at O(log nnz),
    the same contract as row_capacity_tier for streaming stores."""
    cap = max(int(base), 1)
    n = max(int(n), 1)
    while cap < n:
        cap <<= 1
    return cap


@dataclass
class SparseStore:
    """CSR/ELL-packed binned store: per row, up to R (store column,
    bin) entries for exactly the cells whose bin differs from the
    column's zero bin — the bin an implicit raw 0.0 maps to (the
    feature's default bin; 0 = "all members at default" for EFB-packed
    columns).  Implicit zeros are never stored: the histogram kernels
    reconstruct each column's zero-bin row from per-leaf totals
    (ops/histogram._apply_zero_bin), so compute and input bytes scale
    with nnz instead of F x N.  `densify()` reproduces the dense store
    bitwise — the entry set is lossless by construction."""
    cols: np.ndarray      # [N, R] int32 store-column ids; C = empty slot
    bins: np.ndarray      # [N, R] uint8/uint16 bin values
    zero_bin: np.ndarray  # [C] int32 implicit-zero bin per store column
    nnz: int = 0          # stored entries (excluding ELL padding)

    @property
    def num_columns(self) -> int:
        return int(self.zero_bin.shape[0])

    @property
    def nnz_capacity(self) -> int:
        return int(self.cols.shape[1])

    def densify(self, dtype) -> np.ndarray:
        """Materialize the dense [C, N] store (the fallback for
        consumers without a sparse path; callers count it)."""
        C = self.num_columns
        n = self.cols.shape[0]
        out = np.repeat(self.zero_bin.astype(dtype)[:, None], n, axis=1)
        ri, sj = np.nonzero(self.cols < C)
        out[self.cols[ri, sj], ri] = self.bins[ri, sj]
        return out


def _pack_ell(rows: np.ndarray, cols: np.ndarray, binvals: np.ndarray,
              n: int, num_columns: int, zero_bin: np.ndarray,
              dtype) -> SparseStore:
    """Row-sorted COO entries -> ELL arrays at the nnz capacity tier."""
    cnt = np.bincount(rows, minlength=n) if rows.size else \
        np.zeros(n, np.int64)
    R = nnz_capacity_tier(int(cnt.max(initial=1)))
    ell_c = np.full((n, R), num_columns, np.int32)
    ell_b = np.zeros((n, R), dtype)
    if rows.size:
        offs = np.concatenate([[0], np.cumsum(cnt)])
        pos = np.arange(rows.size, dtype=np.int64) - offs[rows]
        ell_c[rows, pos] = cols
        ell_b[rows, pos] = binvals
    return SparseStore(cols=ell_c, bins=ell_b,
                       zero_bin=np.asarray(zero_bin, np.int32),
                       nnz=int(rows.size))


def store_zero_bins(mappers: List[BinMapper], used: Sequence[int],
                    plan: Optional[BundlePlan]) -> np.ndarray:
    """[C] int32 bin an implicit raw zero maps to, per STORE column:
    the member feature's default bin for singleton columns, 0 ("every
    member at its default") for EFB-packed columns."""
    if plan is None:
        return np.asarray([mappers[i].default_bin for i in used],
                          np.int32)
    zb = np.zeros(plan.num_columns, np.int32)
    for k, i in enumerate(used):
        if not plan.feat_packed[k]:
            zb[int(plan.feat_col[k])] = int(mappers[i].default_bin)
    return zb


def resolve_sparse_store(cfg: Config, mappers: List[BinMapper],
                         used: Sequence[int],
                         plan: Optional[BundlePlan]) -> bool:
    """Resolve the `sparse_store` knob for a store about to be built.

    "auto" picks csr only when (1) `is_enable_sparse` is on (the
    reference's master sparse switch), (2) the store is wide enough
    that nnz-iteration can beat the dense kernels (>= 128 columns), and
    (3) the estimated zero-bin rate — the mean of the mappers'
    sampled `sparse_rate` over stored columns, with a packed column's
    rate the complement of its members' summed non-default rates —
    clears `sparse_threshold` (reference semantics: the zero fraction
    above which a feature is worth storing sparse)."""
    mode = getattr(cfg, "sparse_store", "dense")
    if mode == "csr":
        return True
    if mode != "auto" or not cfg.is_enable_sparse or not used:
        return False
    # auto never changes the growth schedule out from under a default
    # config: the nonzero-iterating kernels live in the rounds learner,
    # so auto engages only where rounds is already the resolved default
    # (TPU) or explicitly pinned — a CPU run with stock params keeps
    # the exact learner over the dense store, byte-identical to pre-
    # sparse behavior.  sparse_store=csr remains the explicit opt-in
    # everywhere.
    growth = getattr(cfg, "tree_growth", "auto")
    if growth == "auto":
        import jax
        if jax.default_backend() != "tpu":
            return False
    elif growth != "rounds":
        return False
    C = plan.num_columns if plan is not None else len(used)
    if C < 128:
        return False
    if plan is None:
        rates = np.asarray([mappers[i].sparse_rate for i in used])
    else:
        nd = np.zeros(plan.num_columns)
        for k, i in enumerate(used):
            nd[int(plan.feat_col[k])] += 1.0 - mappers[i].sparse_rate
        rates = 1.0 - np.minimum(nd, 1.0)
    return float(np.mean(rates)) >= float(cfg.sparse_threshold)


# rows used to estimate pairwise feature conflicts when planning bundles;
# planning is O(sparse_features^2 * rows) so the sample is capped tighter
# than bin_construct_sample_cnt (the estimate only gates which features
# share a column — realized conflicts are counted exactly during binning)
BUNDLE_PLAN_SAMPLE_CNT = 50_000

# smallest row capacity of a streaming (appendable) dataset store; growth
# doubles from here so the capacity ladder is a power-of-two tier set
STREAM_CAPACITY_BASE = 1024


def row_capacity_tier(n: int, base: int = STREAM_CAPACITY_BASE) -> int:
    """Smallest power-of-two-of-`base` capacity >= n.  Device kernels over
    a streaming store (online refit, binned replay) key their compiled
    shapes on the CAPACITY, so appends within a tier never retrace and
    the ladder bounds the total compile count at O(log rows)."""
    cap = max(int(base), 1)
    n = max(int(n), 1)
    while cap < n:
        cap <<= 1
    return cap


def _plan_bundles_from_sample(sample: np.ndarray, mappers: List[BinMapper],
                              used: List[int], cfg: Config
                              ) -> Optional[BundlePlan]:
    """Bundle plan from a raw-valued row sample (bins each used feature
    with its mapper, then runs the greedy conflict-graph planner).
    Returns None when bundling is off or nothing bundles."""
    if not cfg.enable_bundle or not used:
        return None
    n = len(sample)
    if n == 0:
        return None
    if n > BUNDLE_PLAN_SAMPLE_CNT:
        rng = np.random.RandomState(cfg.data_random_seed)
        sample = sample[np.sort(rng.choice(n, BUNDLE_PLAN_SAMPLE_CNT,
                                           replace=False))]
    sb = np.stack([mappers[i].value_to_bin(
        np.asarray(sample[:, i], np.float64)) for i in used])
    nb = np.asarray([mappers[i].num_bin for i in used], np.int32)
    db = np.asarray([mappers[i].default_bin for i in used], np.int32)
    return plan_bundles(sb, nb, db, cfg.max_conflict_rate)


def _log_bundle_state(plan: Optional[BundlePlan], num_used: int,
                      cfg: Config) -> None:
    """The one-line construction log the enable_bundle satellite asks for,
    plus always-on profiling counters for /stats and bench.py."""
    from . import log, profiling
    if plan is None:
        if cfg.verbose >= 1:
            log.info(f"EFB: bundling {'off' if not cfg.enable_bundle else 'inactive (no exclusive features)'}; "
                     f"{num_used} features histogrammed directly")
        return
    n_multi = plan.num_bundles
    profiling.count("bundle.features", num_used)
    profiling.count("bundle.columns", plan.num_columns)
    profiling.count("bundle.packed_features", plan.num_packed)
    if cfg.verbose >= 1:
        log.info(
            f"EFB: bundled {num_used} features into {plan.num_columns} "
            f"columns ({n_multi} bundles holding {plan.num_packed} "
            f"features; sampled conflict rate {plan.est_conflict_rate:.4f} "
            f"summed over bundles, budget {cfg.max_conflict_rate:g} each)")


# ----------------------------------------------------------------------------
# Text parsing (reference src/io/parser.cpp)
# ----------------------------------------------------------------------------

def _detect_format(line: str) -> str:
    """Probe one line: 'libsvm' | 'tsv' | 'csv' (parser.cpp format probing)."""
    toks = line.strip().split()
    if len(toks) > 1 and ":" in toks[1]:
        return "libsvm"
    if "\t" in line:
        return "tsv"
    if "," in line:
        return "csv"
    return "tsv"  # space separated handled like tsv


def parse_text_file(path: str, has_header: bool = False, label_idx: int = 0
                    ) -> Tuple[np.ndarray, np.ndarray, Optional[List[str]]]:
    """Parse a CSV/TSV/LibSVM data file into (X, y, feature_names).

    Auto-detects the format from the first data line like the reference
    Parser::CreateParser.  The label is column `label_idx` for csv/tsv and
    the first token for libsvm.

    The native C++ parser (src/native/loader.cpp) is used when built;
    header names are only needed for has_header files, which keep the
    Python path.
    """
    if not has_header:
        from .native import parse_text_native
        res = parse_text_native(path, has_header, label_idx)
        if res is not None:
            return res[0], res[1], None
    with open(path, "r") as f:
        first = f.readline()
        if not first:
            raise ValueError(f"empty data file: {path}")
    header_names: Optional[List[str]] = None
    skip = 0
    if has_header:
        sep = "\t" if "\t" in first else ("," if "," in first else None)
        header_names = [t.strip() for t in first.strip().split(sep)]
        skip = 1
        with open(path, "r") as f:
            f.readline()
            first = f.readline()
    fmt = _detect_format(first)
    if fmt == "libsvm":
        labels: List[float] = []
        rows: List[Dict[int, float]] = []
        max_idx = -1
        with open(path, "r") as f:
            for _ in range(skip):
                f.readline()
            for line in f:
                line = line.strip()
                if not line:
                    continue
                toks = line.split()
                labels.append(float(toks[0]))
                row: Dict[int, float] = {}
                for t in toks[1:]:
                    if ":" not in t:
                        continue
                    k, v = t.split(":", 1)
                    ki = int(k)
                    row[ki] = float(v)
                    max_idx = max(max_idx, ki)
                rows.append(row)
        X = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
        for i, row in enumerate(rows):
            for k, v in row.items():
                X[i, k] = v
        return X, np.asarray(labels, dtype=np.float64), header_names
    sep = "\t" if fmt == "tsv" else ","
    raw = np.loadtxt(path, delimiter=None if sep == "\t" else sep,
                     skiprows=skip, dtype=np.float64, ndmin=2)
    y = raw[:, label_idx].copy()
    X = np.delete(raw, label_idx, axis=1)
    return X, y, header_names


# ----------------------------------------------------------------------------
# Metadata (reference include/LightGBM/dataset.h:36-248, src/io/metadata.cpp)
# ----------------------------------------------------------------------------

@dataclass
class Metadata:
    label: np.ndarray = field(default_factory=lambda: np.zeros(0, np.float32))
    weights: Optional[np.ndarray] = None        # fp32 [N]
    query_boundaries: Optional[np.ndarray] = None  # int32 [num_queries+1]
    init_score: Optional[np.ndarray] = None     # fp64 [N * num_tree_per_iter]

    @property
    def num_data(self) -> int:
        return int(self.label.shape[0])

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    def set_query_from_sizes(self, sizes: np.ndarray) -> None:
        """group sizes -> boundaries (metadata.cpp query loading)."""
        sizes = np.asarray(sizes, dtype=np.int64)
        self.query_boundaries = np.concatenate(
            [[0], np.cumsum(sizes)]).astype(np.int32)

    @property
    def query_weights(self) -> Optional[np.ndarray]:
        """Per-query weight = MEAN of the row weights over the query's
        rows, derived only when both row weights and query boundaries
        exist (metadata.cpp:457-470 LoadQueryWeights).  NDCG/MAP average
        per-query results by these (rank_metric.hpp:113-136,
        map_metric.hpp:113-130); lambdarank itself uses ROW weights
        directly (rank_objective.hpp:164-167)."""
        if self.weights is None or self.query_boundaries is None:
            return None
        qb = self.query_boundaries.astype(np.int64)
        sizes = np.diff(qb)
        # prefix-sum differences instead of add.reduceat: reduceat
        # raises/mis-sums on zero-size queries, this is exact for them
        # (an empty query gets weight 0)
        csum = np.concatenate([[0.0], np.cumsum(
            self.weights.astype(np.float64))])
        sums = csum[qb[1:]] - csum[qb[:-1]]
        return (sums / np.maximum(sizes, 1)).astype(np.float32)

    @staticmethod
    def load_side_files(data_path: str, num_data: int) -> "Metadata":
        """Load `<data>.weight`, `<data>.init`, `<data>.query` if present
        (metadata.cpp:372-437)."""
        md = Metadata()
        wpath = data_path + ".weight"
        if os.path.exists(wpath):
            md.weights = np.loadtxt(wpath, dtype=np.float32).reshape(-1)
        ipath = data_path + ".init"
        if os.path.exists(ipath):
            md.init_score = np.loadtxt(ipath, dtype=np.float64).reshape(-1)
        qpath = data_path + ".query"
        if os.path.exists(qpath):
            sizes = np.loadtxt(qpath, dtype=np.int64).reshape(-1)
            md.set_query_from_sizes(sizes)
        return md


# ----------------------------------------------------------------------------
# Dataset
# ----------------------------------------------------------------------------

def _parse_categorical_column(spec: str, feature_names: Optional[List[str]],
                              num_features: int) -> List[int]:
    """Parse the `categorical_column` selector (index list or name: prefix,
    dataset_loader.cpp:22-157)."""
    if not spec:
        return []
    out: List[int] = []
    if spec.startswith("name:"):
        if not feature_names:
            raise ValueError("categorical_column=name: requires a header")
        wanted = spec[5:].split(",")
        for w in wanted:
            out.append(feature_names.index(w.strip()))
    else:
        for tok in spec.replace(",", " ").split():
            out.append(int(tok))
    return [i for i in out if 0 <= i < num_features]


def _resolve_column_selectors(cfg: Config, names: Optional[List[str]],
                              label_idx: int, n_xcols: int
                              ) -> Tuple[Optional[int], Optional[int],
                                         List[int]]:
    """Resolve `weight_column` / `group_column` / `ignore_column` to
    X-space column indices (file columns with the label removed),
    validating ranges and `name:` selectors (dataset_loader.cpp:22-157).
    Returns (weight_xcol, group_xcol, drop_xcols) — weight/group columns
    are included in drop_xcols."""

    def _resolve(spec: str, what: str) -> Optional[int]:
        spec = spec.strip()
        if not spec:
            return None
        if spec.startswith("name:"):
            if not names:
                raise ValueError(
                    f"{what}={spec} needs has_header=true with a header")
            nm = spec[5:].strip()
            if nm not in names:
                raise ValueError(f"{what}: no column named {nm!r}")
            return names.index(nm)
        return int(spec)

    def _xcol(c: int, what: str) -> int:
        if c == label_idx:
            raise ValueError(f"{what} column {c} is the label column")
        if not 0 <= c <= n_xcols:
            raise ValueError(f"{what} column {c} out of range")
        return c - 1 if c > label_idx else c

    drop: List[int] = []
    wi = _resolve(cfg.weight_column, "weight_column")
    xw = None
    if wi is not None:
        xw = _xcol(wi, "weight_column")
        drop.append(xw)
    gi = _resolve(cfg.group_column, "group_column")
    xg = None
    if gi is not None:
        xg = _xcol(gi, "group_column")
        drop.append(xg)
    ign = cfg.ignore_column.strip()
    if ign.startswith("name:"):
        # `name:` prefixes the WHOLE comma-separated list
        # (dataset_loader.cpp ignore-column parsing)
        for nm in ign[5:].split(","):
            ci = _resolve(f"name:{nm.strip()}", "ignore_column")
            if ci is not None:
                drop.append(_xcol(ci, "ignore_column"))
    elif ign:
        for tok in ign.replace(",", " ").split():
            drop.append(_xcol(int(tok), "ignore_column"))
    return xw, xg, drop


def _query_boundaries_from_ids(qid: np.ndarray) -> np.ndarray:
    """Per-row query ids -> boundaries (metadata.cpp group-column
    handling): rows of one query must be contiguous."""
    change = np.nonzero(qid[1:] != qid[:-1])[0] + 1
    starts = np.concatenate([[0], change])
    if len(np.unique(qid)) != len(starts):
        raise ValueError("group_column: rows of the same query must be "
                         "contiguous in the data file")
    return np.concatenate([starts, [len(qid)]]).astype(np.int32)


def load_file_two_round(path: str, cfg: Config,
                        reference: Optional["Dataset"] = None,
                        chunk_rows: int = 0) -> "Dataset":
    """Streaming two-round ingestion for bigger-than-RAM text files
    (reference DatasetLoader two-round mode, dataset_loader.cpp:159-216):

    - pass 1 streams the file in chunks, reservoir-sampling
      `bin_construct_sample_cnt` rows for BinMapper construction and
      collecting only the label/selector columns in full;
    - pass 2 streams again, binning each chunk straight into the uint8/16
      store — the full float64 matrix never exists.

    Peak memory ≈ binned store + one chunk (~60 MB at 28 features), vs
    ~2.4 GB float64 for the one-shot path at HIGGS scale.
    CSV/TSV only (LibSVM keeps the one-shot path).
    """
    import pandas as pd

    # the shared ingestion chunk knob (docs/Distributed-Data.md): peak
    # parse memory of both streaming loaders scales with this, not N
    chunk_rows = chunk_rows or int(cfg.stream_chunk_rows)
    label_idx = 0
    if cfg.label_column.startswith("name:"):
        raise NotImplementedError("label by name requires header support")
    elif cfg.label_column:
        label_idx = int(cfg.label_column)

    with open(path, "r") as f:
        first = f.readline()
        if cfg.has_header:
            first = f.readline()  # probe a DATA line, not the header
    fmt = _detect_format(first)
    if fmt == "libsvm":
        raise ValueError("use_two_round_loading supports csv/tsv only")
    # "tsv" covers any whitespace separation (one-shot path passes
    # delimiter=None to np.loadtxt)
    sep = r"\s+" if fmt == "tsv" else ","

    def chunks():
        return pd.read_csv(path, sep=sep, header=0 if cfg.has_header
                           else None, chunksize=chunk_rows,
                           dtype=np.float64)

    # ---- pass 1: count rows, reservoir-sample, collect label ------------
    # (and the weight/group selector columns in full, like the one-shot
    # path: the reference streams selector columns during its first pass,
    # dataset_loader.cpp:159-216 + :22-157)
    S = int(cfg.bin_construct_sample_cnt)
    rng = np.random.RandomState(cfg.data_random_seed)
    sample: Optional[np.ndarray] = None     # [S, F] reservoir
    filled = 0
    labels: List[np.ndarray] = []
    wvals: List[np.ndarray] = []
    gvals: List[np.ndarray] = []
    names: Optional[List[str]] = None
    sel = None                               # (weight_x, group_x, keep)
    n_seen = 0
    for ch in chunks():
        arr = ch.to_numpy(dtype=np.float64)
        if names is None and cfg.has_header:
            names = [str(c) for c in ch.columns]
        labels.append(arr[:, label_idx].copy())
        if sel is None:
            n_x = arr.shape[1] - 1
            xw, xg, drop = _resolve_column_selectors(cfg, names, label_idx,
                                                     n_x)
            # map every selector to FILE-space once (X-space -> file-space
            # is +1 past the label column); per-chunk reads index arr
            # directly, and the feature take is ONE fused column take of
            # the kept file columns
            def _fcol(c):
                return c + 1 if c >= label_idx else c
            dropped = set(drop)
            use_cols = [_fcol(c) for c in range(n_x) if c not in dropped]
            keep = ([c for c in range(n_x) if c not in dropped]
                    if drop else None)
            sel = (None if xw is None else _fcol(xw),
                   None if xg is None else _fcol(xg), keep, use_cols)
        wcol, gcol, keep, use_cols = sel
        if wcol is not None:
            wvals.append(arr[:, wcol].copy())
        if gcol is not None:
            gvals.append(arr[:, gcol].copy())
        X = arr[:, use_cols]
        if sample is None:
            sample = np.empty((S, X.shape[1]), np.float64)
        take = min(S - filled, len(X))       # fill phase
        if take > 0:
            sample[filled:filled + take] = X[:take]
            filled += take
        rest = X[take:]                      # replacement phase
        if len(rest):
            gidx = np.arange(n_seen + take, n_seen + take + len(rest))
            accept = rng.rand(len(rest)) < S / (gidx + 1.0)
            if accept.any():
                slots = rng.randint(0, S, size=int(accept.sum()))
                sample[slots] = rest[accept]
        n_seen += len(X)
    if sel is None or n_seen == 0:
        # match the one-shot loader's error instead of an opaque unpack
        # failure further down
        raise ValueError(f"empty data file: {path}")
    y = np.concatenate(labels)
    n = len(y)
    sample = sample[:filled]
    md = Metadata.load_side_files(path, n)
    md.label = np.asarray(y, np.float32)
    wcol, gcol, keep, use_cols = sel
    if wcol is not None:
        if md.weights is not None:
            from . import log
            log.warning("weight_column overrides the .weight side file")
        md.weights = np.concatenate(wvals).astype(np.float32)
    if gcol is not None:
        if md.query_boundaries is not None:
            from . import log
            log.warning("group_column overrides the .query side file")
        md.query_boundaries = _query_boundaries_from_ids(
            np.concatenate(gvals))

    x_names = None
    if names:
        x_names = [nm for c, nm in enumerate(names) if c != label_idx]
        if keep is not None:
            x_names = [x_names[c] for c in keep]

    # ---- mappers from the sample ----------------------------------------
    cats = _parse_categorical_column(cfg.categorical_column, x_names,
                                     sample.shape[1])
    if reference is not None:
        if sample.shape[1] != reference.num_total_features:
            raise ValueError("validation data has different #features")
        mappers = reference.mappers
        used = reference.used_features
        plan = reference.bundle_plan
    else:
        mappers = find_bin_mappers(
            sample, cfg.max_bin, cfg.min_data_in_bin, cfg.min_data_in_leaf,
            categorical=cats, sample_cnt=len(sample),
            seed=cfg.data_random_seed, bin_budget=cfg.bin_budget)
        used = [i for i, m in enumerate(mappers) if not m.is_trivial]
        plan = _plan_bundles_from_sample(sample, mappers, used, cfg)
        _log_bundle_state(plan, len(used), cfg)

    # ---- pass 2: bin straight into the store ----------------------------
    ds = Dataset._empty_from_mappers(cfg, mappers, used, n,
                                     sample.shape[1], x_names, plan=plan)
    row = 0
    for ch in chunks():
        arr = ch.to_numpy(dtype=np.float64)
        ds._bin_rows_into(arr[:, use_cols], row)
        row += len(arr)
    ds._check_realized_conflicts()
    ds.metadata = md
    return ds


class Dataset:
    """Binned feature matrix + metadata.

    Attributes
    ----------
    bins : np.ndarray  [num_used_features, num_data] uint8/uint16 bin ids
    num_bins : np.ndarray [num_used_features] int32 per-feature bin counts
    mappers : list[BinMapper], one per RAW feature
    used_features : list[int] raw indices of non-trivial features
    """

    def __init__(self, X: np.ndarray, label: Optional[np.ndarray] = None,
                 config: Optional[Config] = None,
                 reference: Optional["Dataset"] = None,
                 metadata: Optional[Metadata] = None,
                 feature_names: Optional[List[str]] = None,
                 categorical_feature: Sequence[int] = ()):
        cfg = config or Config()
        X = np.ascontiguousarray(np.asarray(X, dtype=np.float64))
        if X.ndim != 2:
            raise ValueError("X must be 2-dimensional")
        n, num_raw = X.shape
        self.num_data = n
        self.num_total_features = num_raw
        self.config = cfg
        self.feature_names = feature_names or [f"Column_{i}" for i in range(num_raw)]

        if reference is not None:
            # align with reference (valid set): reuse its mappers AND its
            # bundle plan — a valid set binned into a different column
            # layout could not share the training walk/unbundle tables
            if num_raw != reference.num_total_features:
                raise ValueError("validation data has different #features")
            self.mappers = reference.mappers
            self.used_features = reference.used_features
            plan = reference.bundle_plan
        else:
            if cfg.bin_find == "sketch":
                # explicit sketch opt-in: mappers from the mergeable
                # quantile summaries over ALL rows (exact whenever eps
                # is tight enough to hold every distinct value) — the
                # same derivation the distributed and streamed
                # construction paths run, so tree parity with those
                # paths is testable from the batch API
                from .sharded.sketch import sketch_columns
                self.mappers = sketch_columns(
                    X, cfg, categorical=categorical_feature
                ).mappers_from_config(cfg)
            else:
                self.mappers = find_bin_mappers(
                    X, cfg.max_bin, cfg.min_data_in_bin,
                    cfg.min_data_in_leaf,
                    categorical=categorical_feature,
                    sample_cnt=cfg.bin_construct_sample_cnt,
                    seed=cfg.data_random_seed,
                    bin_budget=cfg.bin_budget)
            self.used_features = [i for i, m in enumerate(self.mappers)
                                  if not m.is_trivial]
            plan = _plan_bundles_from_sample(X, self.mappers,
                                             self.used_features, cfg)
            _log_bundle_state(plan, len(self.used_features), cfg)
        self._init_store(plan, n)
        # numerical columns go through the native bulk binner when built
        # (src/native/loader.cpp lgbt_bin_numerical); the rest via NumPy
        self._bin_rows_into(X, 0)
        self._check_realized_conflicts()
        # sparse store: training sets by the resolver; valid sets follow
        # their reference's layout — the score updater walks the ELL
        # segments directly (predict_ensemble_binned_sparse), so a csr
        # run never densifies for valid-set scoring (docs/Sparse.md)
        if ((reference is None or reference.sparse is not None)
                and resolve_sparse_store(
                    cfg, self.mappers, self.used_features,
                    self.bundle_plan)):
            self._sparsify_store()

        md = metadata or Metadata()
        if label is not None:
            md.label = np.asarray(label, dtype=np.float32).reshape(-1)
        if md.label.size == 0:
            md.label = np.zeros(n, dtype=np.float32)
        if md.label.size != n:
            raise ValueError("label length mismatch")
        self.metadata = md
        self._device_bins = None

    # -- store access --------------------------------------------------------

    @property
    def bins(self) -> np.ndarray:
        """[C, N] dense binned store.  A sparse dataset materializes it
        LAZILY on first access — counted as tree/sparse_fallbacks so
        silent densification is operator-visible (docs/Sparse.md lists
        the consumers without a sparse path: bundled feature-sharded
        feeds, binary-cache writes, C-API subsets).  Consumers that can
        name themselves call `dense_bins(site=...)` instead, which also
        bumps the site-labeled series."""
        return self.dense_bins()

    def dense_bins(self, site: str = "unlabeled") -> np.ndarray:
        """`bins` with the densifying consumer named: the canonical
        tree/sparse_fallbacks total stays (alerts key on it), and a
        site-labeled series (same registry discipline as the serve/*
        labels) tells operators WHICH consumer densified."""
        if self._bins is None and self.sparse is not None:
            from . import log, profiling
            profiling.count(profiling.SPARSE_FALLBACKS)
            profiling.count(profiling.labeled(profiling.SPARSE_FALLBACKS,
                                              site=site))
            log.warning(
                f"sparse store materialized dense ({self.num_store_columns}"
                f" x {self.num_data} cells) for a consumer without a "
                f"sparse path (site={site})")
            self._bins = self.sparse.densify(self._store_dtype)
        return self._bins

    @bins.setter
    def bins(self, value) -> None:
        self._bins = value

    def sparse_triple(self):
        """Device (cols [N, R] int32, binsv [N, R] int32, zero_bin [C]
        int32) view of the sparse store — the ELL traversal feed for
        the ScoreUpdater / `predict_ensemble_binned_sparse` consumers
        (bin per (row, column) answered by probing the row's stored
        entries, zero bin otherwise).  None for dense datasets."""
        if self.sparse is None:
            return None
        import jax.numpy as jnp
        sp = self.sparse
        n = self.num_data
        return (jnp.asarray(np.ascontiguousarray(sp.cols[:n]),
                            dtype=jnp.int32),
                jnp.asarray(np.ascontiguousarray(
                    sp.bins[:n].astype(np.int32))),
                jnp.asarray(sp.zero_bin, dtype=jnp.int32))

    def _sparsify_store(self) -> None:
        """Convert the freshly-binned dense store to the CSR/ELL sparse
        layout and drop the dense matrix.  The entry set — cells whose
        bin differs from the column's zero bin — is lossless: densify()
        reproduces the dense store bitwise, so sparse and dense
        datasets built from the same rows train identical trees."""
        zb = store_zero_bins(self.mappers, self.used_features,
                             self.bundle_plan)
        dense = self._bins
        nz = dense != zb[:, None].astype(dense.dtype)
        nzr, nzc = np.nonzero(nz.T)          # row-major entry order
        self.sparse = _pack_ell(nzr, nzc, dense[nzc, nzr], dense.shape[1],
                                dense.shape[0], zb, self._store_dtype)
        self._bins = None
        self._device_bins = None

    # -- helpers ------------------------------------------------------------

    def _init_store(self, plan: Optional[BundlePlan], n: int) -> None:
        """Derive the per-feature metadata and allocate the binned store.

        `num_bins` / `is_categorical` keep their ORIGINAL per-used-feature
        semantics (split search and tree building never see bundles);
        `bins` / `store_num_bins` / `max_num_bin` describe the STORED
        columns — identical to the original view when plan is None, the
        narrower bundled layout otherwise."""
        used = self.used_features
        F = len(used)
        self.num_bins = np.array([self.mappers[i].num_bin for i in used],
                                 dtype=np.int32)
        self.is_categorical = np.array(
            [self.mappers[i].bin_type == CATEGORICAL for i in used],
            dtype=bool)
        self.bundle_plan = plan
        self.bundle_conflict_rows = 0
        if plan is None:
            self.store_num_bins = self.num_bins
        else:
            self.store_num_bins = plan.col_num_bins
        C = len(self.store_num_bins)
        self.max_num_bin = int(self.store_num_bins.max()) if C else 1
        dtype = np.uint8 if self.max_num_bin <= 256 else np.uint16
        self._store_dtype = dtype
        self.sparse = None
        # packed columns rely on 0 meaning "all members at default"
        self.bins = (np.empty((C, n), dtype=dtype) if plan is None
                     else np.zeros((C, n), dtype=dtype))
        self._device_bins = None

    @classmethod
    def _empty_from_mappers(cls, cfg: Config, mappers: List[BinMapper],
                            used: List[int], n: int, num_total: int,
                            feature_names: Optional[List[str]],
                            plan: Optional[BundlePlan] = None) -> "Dataset":
        """Allocate a Dataset shell (store + derived per-feature metadata)
        from existing bin mappers; callers fill `bins` and `metadata`.
        The single place the mapper→store derivation lives — __init__ and
        the streaming two-round loader both use it."""
        ds = cls.__new__(cls)
        ds.config = cfg
        ds.num_data = n
        ds.num_total_features = num_total
        ds.feature_names = (feature_names
                            or [f"Column_{i}" for i in range(num_total)])
        ds.mappers = mappers
        ds.used_features = used
        ds._init_store(plan, n)
        ds.metadata = Metadata()
        return ds

    def _bin_rows_into(self, X: np.ndarray, row0: int) -> None:
        """Bin raw rows X into self.bins[:, row0:row0+len(X)] through
        the SHARED quantization module (quantize.bin_rows_into — the
        train-policy mapper application dataset construction, streaming
        ingestion, and the serving ingress all derive from, so mappers
        can never drift between train and serve).  With a bundle plan,
        packed features fold into their shared column (last writer wins
        on conflicting rows; realized conflicts are counted into
        `bundle_conflict_rows`)."""
        from .quantize import bin_rows_into
        self.bundle_conflict_rows += bin_rows_into(
            X, self.mappers, self.used_features, self.bundle_plan,
            self.bins, row0)

    def _bin_column_into(self, k: int, values: np.ndarray) -> None:
        """Bin ONE used feature's full raw column into the store — the
        column-streaming entry the scipy-CSC path uses so the dense
        [N, F] matrix never materializes."""
        from .quantize import bin_column_into
        self.bundle_conflict_rows += bin_column_into(
            k, values, self.mappers, self.used_features,
            self.bundle_plan, self.bins)

    # -- streaming append path (online ingestion; ROADMAP items 1 + 5) ------
    #
    # A streaming dataset shares a reference dataset's FROZEN BinMappers
    # and BundlePlan (no re-quantization — incoming chunks bin into the
    # exact store layout the model's trees were rebinned to) and grows
    # its [F_eff, capacity] store along a power-of-two capacity ladder,
    # so the device kernels that consume it (online leaf refit, binned
    # replay) compile once per TIER instead of once per append.

    @property
    def row_capacity(self) -> int:
        """Allocated row slots of the store (== num_data except for
        streaming datasets, whose store grows in capacity tiers)."""
        if self._bins is None and self.sparse is not None:
            return int(self.num_data)
        return int(self.bins.shape[1])

    @classmethod
    def from_stream(cls, chunks, config: Optional[Config] = None,
                    reference: Optional["Dataset"] = None,
                    feature_names: Optional[List[str]] = None,
                    categorical_feature: Sequence[int] = (),
                    capacity: int = 0) -> "Dataset":
        """Out-of-core streamed construction (sharded/ingest.py): a
        sketch pass over the chunk stream derives the bin mappers, then
        each chunk bins straight into the capacity-tiered store — peak
        host memory scales with `stream_chunk_rows`, not the dataset
        length, and while the data fits the sample budget the result is
        BITWISE the batch construction.  `chunks` is a callable
        returning a fresh iterator of (X, y, w) tuples, a list of such
        tuples, or an (X, y[, w]) array tuple; `reference` skips the
        sketch pass and bins against frozen mappers (the online-window
        path)."""
        from .sharded.ingest import dataset_from_stream
        return dataset_from_stream(
            chunks, config=config, reference=reference,
            feature_names=feature_names,
            categorical_feature=categorical_feature, capacity=capacity)

    @classmethod
    def streaming_from(cls, reference: "Dataset",
                       config: Optional[Config] = None,
                       capacity: int = STREAM_CAPACITY_BASE) -> "Dataset":
        """Empty appendable Dataset binning against `reference`'s frozen
        mappers + bundle plan.  `capacity` seeds the tier ladder."""
        cfg = config or reference.config
        cap = row_capacity_tier(capacity)
        ds = cls._empty_from_mappers(cfg, reference.mappers,
                                     list(reference.used_features), cap,
                                     reference.num_total_features,
                                     list(reference.feature_names),
                                     plan=reference.bundle_plan)
        # the unbundled store allocates with np.empty; streaming slots
        # beyond num_data must hold bin 0 (the branch-free sentinel
        # value, and "all members at default" for packed columns)
        ds.bins[:] = 0
        ds.num_data = 0
        return ds

    def _reserve_rows(self, n: int) -> None:
        """Grow the store to the next capacity tier holding n rows."""
        cap = self.row_capacity
        if n <= cap:
            return
        new_cap = row_capacity_tier(n, base=max(cap, 1) * 2)
        grown = np.zeros((self.bins.shape[0], new_cap), self.bins.dtype)
        grown[:, :cap] = self.bins
        self.bins = grown
        self._device_bins = None

    def append_rows(self, X: np.ndarray, label=None, weight=None) -> int:
        """Bin a chunk of raw rows into the store (frozen mappers, no
        re-quantization) and append its labels/weights; returns the new
        row count.  Appends within a capacity tier keep the store (and
        therefore every compiled kernel shape over it) stable."""
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        if X.ndim != 2 or X.shape[1] != self.num_total_features:
            raise ValueError(
                f"append_rows expects [rows, {self.num_total_features}] "
                f"features, got {X.shape}")
        n0, add = self.num_data, len(X)
        if add == 0:
            return n0
        self._reserve_rows(n0 + add)
        self._bin_rows_into(X, n0)
        md = self.metadata
        if label is not None:
            lab = np.asarray(label, np.float32).reshape(-1)
            if lab.size != add:
                raise ValueError("label length mismatch")
            if n0 and md.label.size != n0:
                raise ValueError(
                    "cannot append labeled rows to an unlabeled dataset")
            md.label = np.concatenate([md.label, lab]) if n0 else lab
        elif md.label.size:
            raise ValueError(
                "cannot append unlabeled rows to a labeled dataset")
        if weight is not None:
            w = np.asarray(weight, np.float32).reshape(-1)
            if w.size != add:
                raise ValueError("weight length mismatch")
            if md.weights is None:
                md.weights = (np.concatenate(
                    [np.ones(n0, np.float32), w]) if n0 else w)
            else:
                md.weights = np.concatenate([md.weights, w])
        elif md.weights is not None:
            md.weights = np.concatenate(
                [md.weights, np.ones(add, np.float32)])
        self.num_data = n0 + add
        self._device_bins = None
        return self.num_data

    def reset_rows(self) -> None:
        """Drop all rows but KEEP the capacity tier — the online
        trainer's per-refresh window: compiled kernel shapes over the
        store survive the reset, so steady-state refits never retrace."""
        self.bins[:] = 0
        self.num_data = 0
        self.bundle_conflict_rows = 0
        self.metadata = Metadata()
        self._device_bins = None

    def compacted(self) -> "Dataset":
        """Trimmed [F_eff, num_data] copy of a streaming dataset (the
        capacity slack dropped) — what the training learners consume
        (they size scores and partitions off the store width).  Metadata
        is shared (its arrays are already logical-length)."""
        ds = Dataset._empty_from_mappers(
            self.config, self.mappers, list(self.used_features),
            self.num_data, self.num_total_features,
            list(self.feature_names), plan=self.bundle_plan)
        # explicit copy: at num_data == capacity the slice is the whole
        # array and ascontiguousarray would alias it — reset_rows()
        # would then zero the "copy" in place
        ds.bins = self.bins[:, : self.num_data].copy()
        ds.bundle_conflict_rows = self.bundle_conflict_rows
        ds.metadata = self.metadata
        return ds

    @classmethod
    def from_csc(cls, sp_matrix, label: Optional[np.ndarray],
                 cfg: Config, metadata: Optional[Metadata] = None,
                 feature_names: Optional[List[str]] = None,
                 categorical_feature: Sequence[int] = (),
                 reference: Optional["Dataset"] = None) -> "Dataset":
        """Construct from a scipy sparse matrix: a row sample is
        densified once for BinMapper construction (exactly what the
        dense path samples anyway); then, when `sparse_store` resolves
        sparse, the CSR/ELL store is built DIRECTLY from the CSC
        columns — one dense scratch column at a time, entries extracted
        per store column, so peak memory is sample + one column + the
        nnz-scaled store.  Otherwise (the dense fallback) each column is
        densified one at a time and binned into the dense [C, N] store,
        which still avoids the full N×F float64 matrix but pays the
        dense store's memory and histogram cost."""
        sp = sp_matrix.tocsc()
        n, num_raw = sp.shape
        # ---- dense row sample for FindBin ---------------------------------
        S = min(int(cfg.bin_construct_sample_cnt), n)
        rng = np.random.RandomState(cfg.data_random_seed)
        rows = (np.sort(rng.choice(n, S, replace=False)) if n > S
                else np.arange(n))
        sample = np.zeros((len(rows), num_raw), np.float64)
        indptr, indices, data = sp.indptr, sp.indices, sp.data
        for j in range(num_raw):
            s, e = int(indptr[j]), int(indptr[j + 1])
            if s == e:
                continue
            pos = np.searchsorted(rows, indices[s:e])
            hit = (pos < len(rows))
            hit[hit] = rows[pos[hit]] == indices[s:e][hit]
            sample[pos[hit], j] = np.asarray(data[s:e], np.float64)[hit]
        if reference is not None:
            if num_raw != reference.num_total_features:
                raise ValueError("validation data has different #features")
            mappers = reference.mappers
            used = reference.used_features
            plan = reference.bundle_plan
        else:
            mappers = find_bin_mappers(
                sample, cfg.max_bin, cfg.min_data_in_bin,
                cfg.min_data_in_leaf, categorical=categorical_feature,
                sample_cnt=len(sample), seed=cfg.data_random_seed,
                bin_budget=cfg.bin_budget)
            used = [i for i, m in enumerate(mappers) if not m.is_trivial]
            plan = _plan_bundles_from_sample(sample, mappers, used, cfg)
            _log_bundle_state(plan, len(used), cfg)
        ds = cls._empty_from_mappers(cfg, mappers, used, n, num_raw,
                                     feature_names, plan=plan)
        if reference is None and resolve_sparse_store(cfg, mappers, used,
                                                      plan):
            ds._build_sparse_from_csc(indptr, indices, data)
        else:
            # ---- stream one dense column at a time ----------------------
            col = np.empty(n, np.float64)
            for k, i in enumerate(used):
                col[:] = 0.0
                s, e = int(indptr[i]), int(indptr[i + 1])
                col[indices[s:e]] = data[s:e]
                ds._bin_column_into(k, col)
        ds._check_realized_conflicts()
        md = metadata or Metadata()
        if label is not None:
            md.label = np.asarray(label, dtype=np.float32).reshape(-1)
        if md.label.size == 0:
            md.label = np.zeros(n, dtype=np.float32)
        if md.label.size != n:
            raise ValueError("label length mismatch")
        ds.metadata = md
        return ds

    def _build_sparse_from_csc(self, indptr, indices, data) -> None:
        """Construct the CSR/ELL store STRAIGHT from scipy CSC arrays:
        store columns are binned one dense [N] scratch at a time (the
        dense route's exact per-column semantics, including EFB
        last-writer-wins packing, so entries match the dense store
        bitwise) and only the non-zero-bin cells are kept.  The dense
        [C, N] matrix never materializes."""
        from .quantize import bin_feature_column
        n = self.num_data
        plan = self.bundle_plan
        used = self.used_features
        zb = store_zero_bins(self.mappers, used, plan)
        C = self.num_store_columns
        members: List[List[int]] = [[] for _ in range(C)]
        for k in range(len(used)):
            c = k if plan is None else int(plan.feat_col[k])
            members[c].append(k)
        col = np.empty(n, np.float64)
        scratch = np.zeros(n, self._store_dtype)
        rows_l: List[np.ndarray] = []
        cols_l: List[np.ndarray] = []
        bins_l: List[np.ndarray] = []
        for c in range(C):
            scratch[:] = 0
            for k in members[c]:
                i = used[k]
                col[:] = 0.0
                s, e = int(indptr[i]), int(indptr[i + 1])
                col[indices[s:e]] = data[s:e]
                self.bundle_conflict_rows += bin_feature_column(
                    k, col, self.mappers, used, plan, scratch)
            nz = np.flatnonzero(scratch != int(zb[c]))
            if nz.size:
                rows_l.append(nz.astype(np.int64))
                cols_l.append(np.full(nz.size, c, np.int64))
                bins_l.append(scratch[nz].copy())
        if rows_l:
            rows = np.concatenate(rows_l)
            colsv = np.concatenate(cols_l)
            binsv = np.concatenate(bins_l)
            order = np.argsort(rows, kind="stable")
            rows, colsv, binsv = rows[order], colsv[order], binsv[order]
        else:
            rows = np.zeros(0, np.int64)
            colsv = np.zeros(0, np.int64)
            binsv = np.zeros(0, self._store_dtype)
        self.sparse = _pack_ell(rows, colsv, binsv, n, C, zb,
                                self._store_dtype)
        self._bins = None
        self._device_bins = None

    # -- bundle views --------------------------------------------------------

    @property
    def num_store_columns(self) -> int:
        """Stored (histogrammed) columns — F_eff <= num_features.
        Derived from the per-column metadata so a sparse store answers
        without materializing the dense matrix."""
        return int(len(self.store_num_bins))

    def bundle_feat_table(self) -> Optional[np.ndarray]:
        """[5, F] f32 walk/predicate table, or None when unbundled."""
        if self.bundle_plan is None:
            return None
        return self.bundle_plan.feat_table()

    def unbundle_tables(self, num_bins_padded: int,
                        num_columns_padded: int = 0):
        """(src, dmask) gather tables for ops/split.unbundle_hist, or
        None when the store already is the original per-feature layout.
        num_columns_padded: pass the learner's padded column count when
        it pads the store (see BundlePlan.unbundle_tables)."""
        if self.bundle_plan is None:
            return None
        return self.bundle_plan.unbundle_tables(self.num_bins,
                                                num_bins_padded,
                                                num_columns_padded)

    def unbundled_bins(self) -> np.ndarray:
        """Materialize the ORIGINAL [num_features, N] per-feature store
        from the bundled columns (feature-sharded learners need per-
        feature rows; everything else consumes the bundled store)."""
        if self.bundle_plan is None:
            return self.dense_bins(site="unbundled_bins")
        store = self.dense_bins(site="unbundled_bins")
        plan = self.bundle_plan
        F = len(self.used_features)
        out = np.empty((F, self.num_data), store.dtype)
        for k in range(F):
            col = store[int(plan.feat_col[k])]
            if not plan.feat_packed[k]:
                out[k] = col
                continue
            off = int(plan.feat_offset[k])
            d = int(plan.feat_default[k])
            s = col.astype(np.int32) - off
            in_r = (s >= 0) & (s < int(plan.feat_nslots[k]))
            orig = np.where(in_r, s + (s >= d), d)
            out[k] = orig.astype(store.dtype)
        return out

    def sparse_entries(self):
        """Host COO view of the sparse store — (rows int64, cols int32,
        binv int32, zero_bin int32) over exactly the stored cells in
        row-major entry order.  None for dense datasets.  Streaming
        capacity rows past num_data are sliced off, matching
        sparse_triple."""
        if self.sparse is None:
            return None
        sp = self.sparse
        n = self.num_data
        ri, sj = np.nonzero(sp.cols[:n] < sp.num_columns)
        return (ri.astype(np.int64), sp.cols[ri, sj].astype(np.int32),
                sp.bins[ri, sj].astype(np.int32),
                sp.zero_bin.astype(np.int32))

    def unbundled_sparse_entries(self):
        """COO entries of `unbundled_bins()` WITHOUT densifying — the
        feature-sharded / voting learners' sparse feed under EFB.

        Each stored (row, store column, bin) entry decodes to at most
        ONE (row, original feature, original bin) nonzero: the bundle's
        slot windows are disjoint, and an in-window slot value never
        decodes to its member's default bin (s < d -> orig = s != d;
        s >= d -> orig = s + 1 > d — the same decode as unbundled_bins,
        which maps out-of-window values to the default).  Singleton
        columns copy through (stored bins differ from the column zero
        bin, which IS the feature default).  Conflict-remainder entries
        outside every member's window decode to all-defaults and drop.

        Returns (rows int64, feats int32, binv int32, zero_bin_f int32)
        with entries in row-major order and zero_bin_f the per-ORIGINAL-
        feature default bins."""
        ent = self.sparse_entries()
        if ent is None:
            raise ValueError("unbundled_sparse_entries needs a sparse store")
        ri, ci, bi, _ = ent
        zb_f = store_zero_bins(self.mappers, self.used_features, None)
        plan = self.bundle_plan
        if plan is None:
            return ri, ci, bi, zb_f
        order = np.argsort(ci, kind="stable")
        ri, ci, bi = ri[order], ci[order], bi[order]
        out_r, out_f, out_b = [], [], []
        for k in range(len(self.used_features)):
            col = int(plan.feat_col[k])
            lo = np.searchsorted(ci, col, side="left")
            hi = np.searchsorted(ci, col, side="right")
            if lo == hi:
                continue
            rk, bk = ri[lo:hi], bi[lo:hi]
            if plan.feat_packed[k]:
                s = bk - int(plan.feat_offset[k])
                m = (s >= 0) & (s < int(plan.feat_nslots[k]))
                rk, s = rk[m], s[m]
                bk = s + (s >= int(plan.feat_default[k]))
            out_r.append(rk)
            out_f.append(np.full(rk.size, k, np.int32))
            out_b.append(bk.astype(np.int32))
        if not out_r:
            z = np.zeros(0, np.int64)
            return z, z.astype(np.int32), z.astype(np.int32), zb_f
        rows = np.concatenate(out_r)
        order = np.argsort(rows, kind="stable")
        return (rows[order], np.concatenate(out_f)[order],
                np.concatenate(out_b)[order], zb_f)

    def realized_conflict_rate(self) -> float:
        if self.bundle_plan is None or self.num_data == 0:
            return 0.0
        return float(self.bundle_conflict_rows) / float(self.num_data)

    def _check_realized_conflicts(self) -> None:
        """The plan judges exclusivity on a row SAMPLE; binning counts
        conflicts exactly.  When the full data conflicts more than the
        budget promised — in particular ANY conflict under
        max_conflict_rate=0, which is advertised as exactly lossless —
        say so loudly instead of silently degrading."""
        if self.bundle_plan is None or self.bundle_conflict_rows == 0:
            return
        rate = self.realized_conflict_rate()
        budget = float(self.config.max_conflict_rate)
        if budget == 0.0 or rate > budget * max(self.bundle_plan.num_bundles, 1):
            from . import log
            log.warning(
                f"EFB: {self.bundle_conflict_rows} conflicting rows "
                f"(rate {rate:.5f}) exceed what the planning sample "
                f"promised (budget {budget:g}/bundle); conflicting rows "
                "keep only the last-bundled feature's bin. Set "
                "enable_bundle=false (or raise bin_construct_sample_cnt) "
                "for exact training")

    @property
    def num_features(self) -> int:
        return len(self.used_features)

    def inner_to_real(self, inner: int) -> int:
        return self.used_features[inner]

    def real_to_inner(self, real: int) -> int:
        """Inner (used-feature) index, or -1 when the raw feature was
        filtered as trivial."""
        try:
            return self.used_features.index(real)
        except ValueError:
            return -1

    def device_bins(self):
        """[F, N+1] device array with a sentinel row slot at index N
        (bin 0, weight 0) so padded gathers need no branches."""
        if self._device_bins is None:
            import jax.numpy as jnp
            store = self.dense_bins(site="device_bins")
            padded = np.concatenate(
                [store, np.zeros((store.shape[0], 1), store.dtype)],
                axis=1)
            self._device_bins = jnp.asarray(padded.astype(np.int8 if
                padded.dtype == np.uint8 else np.int16))
        return self._device_bins

    def feature_infos(self) -> List[str]:
        return [m.feature_info() for m in self.mappers]

    # -- binary cache (reference dataset.cpp:18,323-407 SaveBinaryFile /
    #    LoadFromBinFile with magic token) --------------------------------
    # Stored as a magic line + npz (allow_pickle=False on load: a data
    # file is untrusted input and must never reach pickle).

    BINARY_MAGIC = "lightgbm_tpu.dataset.v3"

    def save_binary(self, path: str) -> None:
        """Serialize the binned dataset so reloads skip parse+bin.

        A streaming dataset's capacity slack (store columns past
        num_data) is trimmed on the way out, so the cache round-trips
        as a normal dataset — bitwise the store a batch construction of
        the same rows would write — instead of freezing one run's
        capacity tier into the file."""
        md = self.metadata
        store = self.dense_bins(site="binary_cache")
        arrays = {
            "bins": (store if store.shape[1] == self.num_data
                     else np.ascontiguousarray(
                         store[:, : self.num_data])),
            "num_data": np.int64(self.num_data),
            "num_total_features": np.int64(self.num_total_features),
            "used_features": np.asarray(self.used_features, np.int64),
            "feature_names": np.asarray(self.feature_names, dtype="U"),
            "label": md.label,
            "max_bin": np.int64(self.config.max_bin),
            "enable_bundle": np.int64(1 if self.config.enable_bundle else 0),
            "bundle_conflict_rows": np.int64(self.bundle_conflict_rows),
        }
        if self.bundle_plan is not None:
            p = self.bundle_plan
            arrays["bundle_feat"] = np.stack([
                p.feat_col, p.feat_offset, p.feat_default, p.feat_nslots,
                p.feat_packed.astype(np.int32)]).astype(np.int64)
            arrays["bundle_col_bins"] = p.col_num_bins.astype(np.int64)
        for opt, name in ((md.weights, "weights"),
                          (md.query_boundaries, "query_boundaries"),
                          (md.init_score, "init_score")):
            if opt is not None:
                arrays[name] = opt
        for i, m in enumerate(self.mappers):
            arrays[f"m{i}_meta"] = np.asarray(
                [m.bin_type, m.num_bin, 1 if m.is_trivial else 0,
                 m.default_bin], np.int64)
            arrays[f"m{i}_fl"] = np.asarray(
                [m.min_val, m.max_val, m.sparse_rate], np.float64)
            arrays[f"m{i}_upper"] = np.asarray(m.bin_upper_bound, np.float64)
            arrays[f"m{i}_cats"] = np.asarray(m.bin_2_categorical, np.int64)
        # stream straight to disk: at Expo scale (11M x 700) a BytesIO
        # staging copy would add a multi-GB compressed buffer to peak
        # RSS at exactly the moment the raw matrix is also resident
        with open(path, "wb") as f:
            f.write(self.BINARY_MAGIC.encode() + b"\n")
            np.savez_compressed(f, **arrays)

    def save_refbin(self, path: str) -> None:
        """Persist ONLY the frozen mapper set (+ bundle plan + used
        features) as a 0-row binary-dataset shell — the serving
        registry's ``.refbin`` sidecar contract for models trained
        offline (docs/serving.md "Binned inference"; the online trainer
        publishes its whole window store instead).  Loads back through
        `quantize.load_refbin` / `from_binary` like any binary
        dataset."""
        shell = Dataset._empty_from_mappers(
            self.config, self.mappers, list(self.used_features), 0,
            self.num_total_features, list(self.feature_names),
            plan=self.bundle_plan)
        shell.save_binary(path)

    @classmethod
    def from_binary(cls, path: str, config: Optional[Config] = None
                    ) -> "Dataset":
        cfg = config or Config()
        with open(path, "rb") as f:
            first = f.readline()
            if first.strip().decode(errors="replace") != cls.BINARY_MAGIC:
                raise ValueError(
                    f"{path} is not a lightgbm_tpu binary dataset")
            npz = np.load(f, allow_pickle=False)
            d = {k: npz[k] for k in npz.files}  # materialize before close
        return cls._from_binary_dict(d, cfg, path)

    @classmethod
    def _from_binary_dict(cls, d: Dict[str, np.ndarray], cfg: Config,
                          path: str) -> "Dataset":
        """Rebuild a Dataset from the already-parsed npz payload — the
        body of `from_binary`, split out so `quantize.load_refbin` can
        hash + parse a sidecar's bytes ONCE instead of re-reading the
        file per stage (`path` is for error messages only)."""
        if int(d["max_bin"]) != cfg.max_bin:
            raise ValueError(
                f"binary dataset {path} was built with max_bin="
                f"{int(d['max_bin'])}, config wants {cfg.max_bin}; "
                "delete the cache to rebuild")
        cached_eb = bool(int(d.get("enable_bundle", 0)))
        if cached_eb != bool(cfg.enable_bundle):
            # a cache built with the other bundling setting would silently
            # change the measured kernel shape — force a rebin instead
            raise ValueError(
                f"binary dataset {path} was built with enable_bundle="
                f"{cached_eb}, config wants {cfg.enable_bundle}; "
                "delete the cache to rebuild")
        ds = cls.__new__(cls)
        ds.config = cfg
        ds.num_data = int(d["num_data"])
        ds.num_total_features = int(d["num_total_features"])
        ds.used_features = [int(i) for i in d["used_features"]]
        ds.feature_names = [str(s) for s in d["feature_names"]]
        ds.mappers = []
        for i in range(ds.num_total_features):
            meta = d[f"m{i}_meta"]
            fl = d[f"m{i}_fl"]
            cats = [int(c) for c in d[f"m{i}_cats"]]
            ds.mappers.append(BinMapper(
                bin_type=int(meta[0]), num_bin=int(meta[1]),
                is_trivial=bool(meta[2]), default_bin=int(meta[3]),
                min_val=float(fl[0]), max_val=float(fl[1]),
                sparse_rate=float(fl[2]),
                bin_upper_bound=d[f"m{i}_upper"],
                bin_2_categorical=cats))
        plan = None
        if "bundle_feat" in d:
            bf = d["bundle_feat"]
            plan = BundlePlan(
                feat_col=bf[0].astype(np.int32),
                feat_offset=bf[1].astype(np.int32),
                feat_default=bf[2].astype(np.int32),
                feat_nslots=bf[3].astype(np.int32),
                feat_packed=bf[4] > 0,
                col_num_bins=d["bundle_col_bins"].astype(np.int32))
        ds._init_store(plan, 0)
        ds.bins = d["bins"]
        ds.bundle_conflict_rows = int(d.get("bundle_conflict_rows", 0))
        ds.metadata = Metadata(
            label=d["label"],
            weights=d["weights"] if "weights" in d else None,
            query_boundaries=(d["query_boundaries"]
                              if "query_boundaries" in d else None),
            init_score=d["init_score"] if "init_score" in d else None)
        ds._device_bins = None
        # the binary cache stores the dense layout; re-derive the
        # sparse store when the config resolves csr so cache hits train
        # the same path as fresh constructions (0-row refbin shells
        # stay dense)
        if ds.num_data and resolve_sparse_store(
                cfg, ds.mappers, ds.used_features, ds.bundle_plan):
            ds._sparsify_store()
        return ds

    @staticmethod
    def _is_binary_file(path: str) -> bool:
        try:
            with open(path, "rb") as f:
                head = f.read(len(Dataset.BINARY_MAGIC) + 1)
            return head.startswith(Dataset.BINARY_MAGIC.encode())
        except OSError:
            return False

    @staticmethod
    def from_file(path: str, config: Optional[Config] = None,
                  reference: Optional["Dataset"] = None) -> "Dataset":
        cfg = config or Config()
        # binary cache: <data>.bin next to the file, or the file itself
        # (reference dataset_loader.cpp:263+ token detection)
        if cfg.enable_load_from_binary_file:
            bin_path = None
            if Dataset._is_binary_file(path):
                bin_path = path
            elif os.path.exists(path + ".bin") and \
                    Dataset._is_binary_file(path + ".bin") and \
                    os.path.getmtime(path + ".bin") >= os.path.getmtime(path):
                bin_path = path + ".bin"
            if bin_path is not None:
                if cfg.verbose >= 1:
                    print(f"[LightGBM-TPU] [Info] loading binary dataset "
                          f"cache {bin_path}", flush=True)
                ds = Dataset.from_binary(bin_path, cfg)
                if reference is not None:
                    # valid-set alignment (reference Dataset::CheckAlign,
                    # dataset.h:298-314): bin mappers must match the
                    # training set's
                    if (ds.num_total_features
                            != reference.num_total_features or
                            any(a.num_bin != b.num_bin for a, b in
                                zip(ds.mappers, reference.mappers))):
                        raise ValueError(
                            f"binary validation data {bin_path} was binned "
                            "differently from the training data")
                return ds
        if cfg.use_two_round_loading:
            # streaming two-pass ingestion: the full float64 matrix never
            # materializes (dataset_loader.cpp:159-216)
            return load_file_two_round(path, cfg, reference)
        label_idx = 0
        if cfg.label_column.startswith("name:"):
            raise NotImplementedError("label by name requires header support")
        elif cfg.label_column:
            label_idx = int(cfg.label_column)
        X, y, names = parse_text_file(path, cfg.has_header, label_idx)
        md = Metadata.load_side_files(path, len(y))

        # ---- in-file column selectors (dataset_loader.cpp:22-157) ----------
        # Indices count the FILE's columns (label included), the reference
        # CSV/TSV convention; `name:` selectors need has_header.
        xw, xg, drop = _resolve_column_selectors(cfg, names, label_idx,
                                                 X.shape[1])
        if xw is not None:
            if md.weights is not None:
                from . import log
                log.warning("weight_column overrides the .weight side file")
            md.weights = X[:, xw].astype(np.float32)
        if xg is not None:
            if md.query_boundaries is not None:
                from . import log
                log.warning("group_column overrides the .query side file")
            md.query_boundaries = _query_boundaries_from_ids(X[:, xg])

        x_names = None
        if names:
            if len(names) == X.shape[1] + 1:
                x_names = [nm for c, nm in enumerate(names) if c != label_idx]
            elif len(names) == X.shape[1]:
                x_names = list(names)
        if drop:
            keep = [c for c in range(X.shape[1]) if c not in set(drop)]
            X = X[:, keep]
            if x_names is not None:
                x_names = [x_names[c] for c in keep]

        cats = _parse_categorical_column(cfg.categorical_column, x_names,
                                         X.shape[1])

        # distributed pre-partition (reference dataset_loader.cpp:554-659
        # + distributed bin finding :733-833): in a multi-process world
        # each process keeps only its row block, with bin mappers derived
        # from a process-allgathered global sample so every rank bins
        # identically
        if cfg.is_pre_partition:
            import jax
            if jax.process_count() > 1:
                from .distributed import (find_bin_mappers_distributed,
                                          local_row_slice)
                if md.query_boundaries is not None:
                    raise NotImplementedError(
                        "pre_partition with query data is not supported "
                        "yet (queries would straddle row blocks)")
                sl = local_row_slice(len(y))
                n_local = sl.stop - sl.start
                if reference is not None:
                    if X.shape[1] != reference.num_total_features:
                        raise ValueError(
                            "validation data has different #features")
                    # valid sets bin with the TRAINING mappers, exactly
                    # like the non-partitioned paths (Dataset::CheckAlign)
                    mappers = reference.mappers
                    plan = reference.bundle_plan
                else:
                    rng = np.random.RandomState(cfg.data_random_seed)
                    take = min(cfg.bin_construct_sample_cnt
                               // jax.process_count() + 1, max(n_local, 1))
                    samp = (np.sort(rng.choice(n_local, take,
                                               replace=False))
                            if n_local > 0 else np.zeros(0, np.int64))
                    # bundling is decided ONCE from the allgathered global
                    # sample: every rank derives the identical plan, so
                    # the sharded stores stay column-aligned
                    mappers, gsample = find_bin_mappers_distributed(
                        X[sl][samp], cfg, categorical=cats,
                        return_sample=True)
                    used0 = [i for i, m in enumerate(mappers)
                             if not m.is_trivial]
                    plan = _plan_bundles_from_sample(gsample, mappers,
                                                     used0, cfg)
                    _log_bundle_state(plan, len(used0), cfg)
                used = [i for i, m in enumerate(mappers) if not m.is_trivial]
                ds = Dataset._empty_from_mappers(
                    cfg, mappers, used, n_local, X.shape[1], x_names,
                    plan=plan)
                ds._bin_rows_into(X[sl], 0)
                ds._check_realized_conflicts()
                init_local = None
                if md.init_score is not None:
                    # init_score may be flattened [N * K] class-major
                    # (score_updater.py consumption): slice per class
                    n_all = len(y)
                    if md.init_score.size % n_all:
                        raise ValueError("init score size mismatch")
                    k = md.init_score.size // n_all
                    init_local = md.init_score.reshape(
                        k, n_all)[:, sl].reshape(-1)
                ds.metadata = Metadata(
                    label=np.asarray(y[sl], np.float32),
                    weights=(None if md.weights is None
                             else md.weights[sl]),
                    init_score=init_local)
                return ds

        ds = Dataset(X, y, cfg, reference=reference, metadata=md,
                     feature_names=x_names, categorical_feature=cats)
        return ds
