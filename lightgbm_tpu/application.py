"""CLI application: `python -m lightgbm_tpu key=value… [config=train.conf]`.

Mirrors the reference Application (/root/reference/src/application/
application.cpp:46-248, main.cpp): parse key=value argv + config file,
task=train → load data/valid sets, boost with per-iteration metric output
and wall-clock logging, save model; task=predict → batch-score a data file
to output_result; task=serve → online JSON-lines HTTP scoring
(lightgbm_tpu/serving/).  The reference examples' train.conf/predict.conf
run unmodified.
"""
from __future__ import annotations

import sys
import time
from typing import List, Optional

import numpy as np

from . import log
from .basic import Booster, Dataset, LightGBMError
from .boosting.gbdt import create_boosting
from .config import (Config, check_param_conflict, config_from_params,
                     parse_cli_args)
from .dataset import Dataset as RawDataset, parse_text_file


def _log(cfg: Config, msg: str) -> None:
    log.info(msg)


def _label_idx(cfg: Config) -> int:
    """label_column → column index (dataset_loader.cpp:22-157 semantics:
    a bare index, or `name:<col>` which needs a header)."""
    if not cfg.label_column:
        return 0
    if cfg.label_column.startswith("name:"):
        raise LightGBMError(
            "label_column=name:<col> requires has_header=true data; "
            "name-based selection is not supported for prediction input")
    try:
        return int(cfg.label_column)
    except ValueError:
        raise LightGBMError(
            f"invalid label_column: {cfg.label_column!r}") from None


class Application:
    def __init__(self, argv: List[str]):
        params = parse_cli_args(argv)
        if not params:
            raise LightGBMError(
                "no parameters given; usage: python -m lightgbm_tpu "
                "config=train.conf [key=value ...]")
        self.params = params
        self.config = config_from_params(params)
        check_param_conflict(self.config)

    def run(self) -> None:
        cfg = self.config
        from . import telemetry
        # the task IS the process role: spans from a trainer, a daemon
        # and a serving fleet sharing one telemetry_path stay
        # distinguishable (and land in separate chrome-trace pid lanes)
        telemetry.set_process(cfg.task)
        # standalone Prometheus /metrics for roles without their own
        # HTTP server; task=serve and task=route mount the same payload
        # on their own endpoints instead (serving/server.py,
        # router/server.py)
        metrics_srv = None
        if cfg.metrics_port and cfg.task not in ("serve", "serving",
                                                 "route", "router"):
            metrics_srv = telemetry.start_metrics_server(
                cfg.metrics_port, host=cfg.serve_host)
        try:
            if cfg.task == "train":
                self._train()
            elif cfg.task in ("predict", "prediction", "test"):
                self._predict()
            elif cfg.task in ("serve", "serving"):
                self._serve()
            elif cfg.task in ("route", "router"):
                self._route()
            elif cfg.task in ("online", "online_train"):
                self._online()
            elif cfg.task in ("refit", "refit_tree"):
                self._refit()
            else:
                raise LightGBMError(f"unknown task: {cfg.task}")
        finally:
            if metrics_srv is not None:
                metrics_srv.close()

    # ------------------------------------------------------------------
    def _train(self) -> None:
        cfg = self.config
        if not cfg.data:
            raise LightGBMError("no training data: set data=<file>")
        # multi-host bootstrap BEFORE any device use — the analog of the
        # reference's Network::Init at InitTrain (application.cpp:185-197)
        if cfg.num_machines > 1:
            from .distributed import maybe_init_from_config
            if maybe_init_from_config(cfg):
                import jax
                _log(cfg, f"initialized {cfg.num_machines}-process world, "
                          f"{len(jax.devices())} global devices")
        t0 = time.time()
        train_raw = RawDataset.from_file(cfg.data, cfg)
        if cfg.is_save_binary_file and not RawDataset._is_binary_file(
                cfg.data):
            train_raw.save_binary(cfg.data + ".bin")
            _log(cfg, f"saved binary dataset cache to {cfg.data}.bin")
        _log(cfg, f"finished loading data in {time.time() - t0:.6f} seconds")
        _log(cfg, f"number of data: {train_raw.num_data}, number of "
                  f"features: {train_raw.num_features}")

        # checkpoint resume: a prior run's snapshot replaces input_model
        # (its trees INCLUDE whatever input_model seeded that run with)
        from .boosting.gbdt import load_checkpoint
        resume = (load_checkpoint(cfg.checkpoint_path)
                  if cfg.checkpoint_path else None)
        gbdt = create_boosting(cfg, "" if resume else cfg.input_model)
        from .objectives import create_objective
        objective = create_objective(cfg)
        start_it = 0
        if resume is not None:
            start_it = gbdt.resume_from_checkpoint(resume, train_raw,
                                                   objective)
            _log(cfg, f"resumed from checkpoint {cfg.checkpoint_path}: "
                      f"iteration {start_it}, {gbdt.num_trees} trees")
        else:
            gbdt.reset_training_data(train_raw, objective)
        for i, vpath in enumerate(cfg.valid_data):
            vraw = RawDataset.from_file(vpath, cfg, reference=train_raw)
            gbdt.add_valid(vraw, f"valid_{i + 1}")

        checkpointing = bool(cfg.checkpoint_path
                             and cfg.checkpoint_interval > 0)
        # an early-stopped run already rolled back past its best
        # iteration; resuming its loop would just retrain the dropped
        # tail until early stopping fires again — and the marker must
        # survive a no-op rerun, or the rerun-after-that retrains it
        resumed_early_stop = (resume is not None
                              and resume.get("finished") == "early_stop")
        if resumed_early_stop:
            start_it = cfg.num_iterations
        stopped_early = resumed_early_stop
        start = time.time()
        for it in range(start_it, cfg.num_iterations):
            stop = gbdt.train_one_iter(None, None, is_eval=False)
            printing = (cfg.verbose >= 1 and cfg.metric_freq > 0
                        and (it + 1) % cfg.metric_freq == 0)
            valid_res = (gbdt.eval_valid()
                         if printing or cfg.early_stopping_round > 0 else [])
            if cfg.early_stopping_round > 0:
                stop = stop or gbdt.eval_and_check_early_stopping(valid_res)
            if printing:
                for name, metric_name, val, _ in (
                        gbdt.eval_train() if cfg.is_training_metric else []):
                    _log(cfg, f"Iteration:{it + 1}, {name} {metric_name} : "
                              f"{val:g}")
                for name, metric_name, val, _ in valid_res:
                    _log(cfg, f"Iteration:{it + 1}, {name} {metric_name} : "
                              f"{val:g}")
            _log(cfg, f"{time.time() - start:.6f} seconds elapsed, finished "
                      f"iteration {it + 1}")
            if checkpointing and (it + 1) % cfg.checkpoint_interval == 0:
                gbdt.save_checkpoint(cfg.checkpoint_path)
            if stop:
                _log(cfg, "early stopping")
                stopped_early = True
                break
        if checkpointing:
            # final snapshot so a rerun after completion is a no-op
            # resume instead of re-training the tail after the last
            # periodic snapshot (early_stop marks the rolled-back run)
            gbdt.save_checkpoint(cfg.checkpoint_path, extra={
                "finished": "early_stop" if stopped_early else "complete"})
        gbdt.save_model_to_file(cfg.output_model)
        _log(cfg, f"finished training, model saved to {cfg.output_model}")
        if cfg.serve_quantize != "raw":
            # ship the frozen-mapper sidecar beside the model so the
            # serving registry (and the online daemon, which adopts it)
            # can quantize requests against the model's OWN training
            # mappers — the refbin contract behind serve_quantize=binned
            try:
                train_raw.save_refbin(cfg.output_model + ".refbin")
                _log(cfg, "frozen bin mappers saved to "
                          f"{cfg.output_model}.refbin")
            except OSError as e:
                log.warning(f"could not save the refbin sidecar "
                            f"({type(e).__name__}: {e}); binned serving "
                            "of this model will fall back to raw")

    # ------------------------------------------------------------------
    def _predict(self) -> None:
        cfg = self.config
        if not cfg.data:
            raise LightGBMError("no prediction data: set data=<file>")
        if not cfg.input_model:
            raise LightGBMError("no model: set input_model=<file>")
        # one Booster + one compiled-predictor runtime for the whole
        # task: every file/chunk shares the stacked trees and the warm
        # executables instead of rebuilding the TreeStack per call
        bst = Booster(model_file=cfg.input_model)
        predictor = Predictor(bst, raw_score=cfg.is_predict_raw_score,
                              leaf_index=cfg.is_predict_leaf_index,
                              num_iteration=cfg.num_iteration_predict,
                              predict_kernel=cfg.predict_kernel,
                              serve_quantize=cfg.serve_quantize,
                              refbin=cfg.input_model + ".refbin")
        predictor.predict_file(cfg.data, cfg.output_result,
                               has_header=cfg.has_header,
                               label_idx=_label_idx(cfg))
        _log(cfg, f"finished prediction, results saved to "
                  f"{cfg.output_result}")

    # ------------------------------------------------------------------
    def _serve(self) -> None:
        from .serving.server import serve_from_config
        serve_from_config(self.config)

    # ------------------------------------------------------------------
    def _route(self) -> None:
        """task=route: the stdlib-only router tier fronting M backend
        task=serve processes (lightgbm_tpu/router/, docs/Router.md) —
        consistent-hash tenant placement, per-backend circuit breakers,
        fleet-aggregated /stats + /metrics."""
        from .router import route_from_config
        route_from_config(self.config)

    # ------------------------------------------------------------------
    def _online(self) -> None:
        """task=online: the continuous refresh daemon (online/trainer.py)
        — watch a labeled-traffic JSONL, refit/continue on trigger,
        publish generations to the registry path.  With `serve_models`
        set, one daemon per catalog tenant shares the traffic tail
        (keyed rows, keyed publish paths — docs/serving.md
        "Multi-tenant catalog")."""
        from .online.trainer import OnlineFleet, OnlineTrainer
        if self.config.serve_models:
            OnlineFleet.from_config(self.config).run_forever()
        else:
            OnlineTrainer.from_config(self.config).run_forever()

    # ------------------------------------------------------------------
    def _refit(self) -> None:
        """task=refit (reference task=refit_tree): one-shot leaf-value
        refit of input_model on a labeled data file, saved to
        output_model."""
        cfg = self.config
        if not cfg.data:
            raise LightGBMError("no refit data: set data=<file>")
        if not cfg.input_model:
            raise LightGBMError("no model: set input_model=<file>")
        from .online.refit import refit_gbdt
        ds = RawDataset.from_file(cfg.data, cfg)
        gbdt = create_boosting(cfg, cfg.input_model)
        # plain text files re-parse cheaply, so route on the RAW
        # feature values (exact, Booster.refit parity); binary stores
        # and selector-remapped files keep the binned fallback
        leaf = None
        if (not RawDataset._is_binary_file(cfg.data)
                and not cfg.use_two_round_loading
                and not (cfg.weight_column or cfg.group_column
                         or cfg.ignore_column)):
            label_idx = (int(cfg.label_column) if cfg.label_column
                         and not cfg.label_column.startswith("name:")
                         else 0)
            X, _, _ = parse_text_file(cfg.data, cfg.has_header, label_idx)
            if len(X) == ds.num_data:
                leaf = gbdt.predict_leaf_index(X)
        stats = refit_gbdt(gbdt, ds, leaf_idx=leaf)
        gbdt.save_model_to_file(cfg.output_model)
        _log(cfg, f"refit {stats['trees_refit']} of {stats['trees']} "
                  f"trees on {stats['rows']} rows "
                  f"(decay {stats['decay_rate']:g}); model saved to "
                  f"{cfg.output_model}")


class Predictor:
    """Batch file prediction (reference predictor.hpp:24-159): parse the
    input file, score every row, write one prediction per line.

    Value/raw scoring runs through a shared `serving.PredictorRuntime`,
    so the CLI batch path and the online server hit the same compiled-
    executable cache: chunks are padded to power-of-two row buckets and
    never retrace on a leftover shape.  Leaf-index output keeps the host
    walk (exact int semantics, no device analog yet)."""

    def __init__(self, booster: Booster, raw_score: bool = False,
                 leaf_index: bool = False, num_iteration: int = -1,
                 runtime=None, predict_kernel=None,
                 serve_quantize: str = "raw", refbin=None):
        self.booster = booster
        self.raw_score = raw_score
        self.leaf_index = leaf_index
        self.num_iteration = num_iteration
        gbdt = getattr(booster, "_gbdt", booster)
        gbdt._flush_pending()
        if runtime is None and not leaf_index and gbdt.models:
            # zero-tree models keep the host path: Booster.predict
            # returns the baseline score, nothing to compile.  Batch
            # prediction shares the serving runtime, so it shares the
            # serve_quantize dial too (resolve_runtime owns the
            # auto/binned/raw policy): binned requires the model's
            # .refbin mapper sidecar, auto falls back to raw without one
            from .serving.runtime import resolve_runtime
            runtime = resolve_runtime(
                booster, serve_quantize=serve_quantize, refbin=refbin,
                num_iteration=num_iteration, max_batch_rows=262_144,
                predict_kernel=predict_kernel)
        self.runtime = runtime

    def predict(self, X: np.ndarray) -> np.ndarray:
        if self.leaf_index:
            return self.booster.predict(X, num_iteration=self.num_iteration,
                                        pred_leaf=True)
        if self.runtime is not None:
            return self.runtime.predict(
                X, kind="raw" if self.raw_score else "value")
        return self.booster.predict(X, num_iteration=self.num_iteration,
                                    raw_score=self.raw_score)

    def predict_file(self, data_path: str, out_path: str,
                     has_header: bool = False, label_idx: int = 0,
                     chunk_rows: int = 262_144) -> None:
        """Streaming file prediction: CSV/TSV inputs are read in chunks
        and scored chunk-by-chunk through the fixed-shape device
        predictor, so the full float64 matrix never exists — the analog
        of the reference's pipelined double-buffered reader
        (predictor.hpp:80-159, pipeline_reader.h).  Peak host memory is
        one chunk (~60 MB at 28 features) instead of ~2.4 GB for an
        11M-row file.  LibSVM keeps the one-shot parse (same trade as
        training-side ingestion, dataset.load_file_two_round)."""
        with open(out_path, "w") as f:
            for X in _iter_predict_chunks(data_path, has_header, label_idx,
                                          chunk_rows):
                preds = self.predict(X)
                if preds.ndim == 1:
                    f.writelines(f"{v:.17g}\n" for v in preds)
                else:
                    f.writelines(
                        "\t".join(f"{v:.17g}" for v in row) + "\n"
                        for row in preds)


def _iter_predict_chunks(data_path: str, has_header: bool, label_idx: int,
                         chunk_rows: int):
    """Yield [chunk, F] float64 feature blocks from a prediction file.
    CSV/TSV stream through pandas chunked reads; LibSVM (ragged, rare at
    predict-file scale) falls back to the one-shot parser."""
    from .dataset import _detect_format

    with open(data_path, "r") as f:
        first = f.readline()
        if not first:
            raise ValueError(f"empty data file: {data_path}")
        if has_header:
            first = f.readline() or first
    fmt = _detect_format(first)
    if fmt == "libsvm":
        X, _, _ = parse_text_file(data_path, has_header, label_idx)
        yield X
        return
    import pandas as pd
    # same fmt->sep mapping and '#'-comment handling as the one-shot
    # np.loadtxt parser (dataset.py parse_text_file)
    sep = "," if fmt == "csv" else r"\s+"
    for ch in pd.read_csv(data_path, sep=sep, comment="#",
                          header=0 if has_header else None,
                          chunksize=chunk_rows, dtype=np.float64):
        arr = ch.to_numpy(dtype=np.float64)
        yield np.delete(arr, label_idx, axis=1)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        Application(argv).run()
    except LightGBMError as e:
        print(f"[LightGBM-TPU] [Fatal] {e}", file=sys.stderr)
        return 1
    return 0
