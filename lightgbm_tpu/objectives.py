"""Objective functions: jitted elementwise gradient/hessian kernels.

Parity with /root/reference/src/objective/ (factory objective_function.cpp:9-31):
regression (L2), regression_l1, huber, fair, poisson
(regression_objective.hpp), binary (binary_objective.hpp:45-113),
multiclass softmax / multiclassova (multiclass_objective.hpp), lambdarank
(rank_objective.hpp:19-242).

Scores and gradients are `[K, N]` float32 device arrays (K = trees per
iteration; the reference uses a flat class-major buffer, gbdt.cpp:648-656).
The reference's per-row OMP loops become one fused elementwise XLA program;
LambdaRank's per-query pairwise loop becomes a padded `[Q, D, D]` masked
computation chunked over queries (no sigmoid lookup table needed — the VPU
evaluates exp directly; rank_objective.hpp:173-199 is a CPU-ism).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .dataset import Metadata


def softmax(x, axis=-1):
    m = jnp.max(x, axis=axis, keepdims=True)
    e = jnp.exp(x - m)
    return e / jnp.sum(e, axis=axis, keepdims=True)


class Objective:
    """Base objective.  get_gradients: [K, N] score -> ([K, N], [K, N])."""

    name = "regression"
    num_tree_per_iteration = 1
    is_constant_hessian = False
    boost_from_average = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label, jnp.float32)
        self.weights = (None if metadata.weights is None
                        else jnp.asarray(metadata.weights, jnp.float32))

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def convert_output(self, score: np.ndarray) -> np.ndarray:
        """Raw score -> prediction output (reference ConvertOutput)."""
        return score

    def initial_score(self) -> float:
        """boost_from_average seed value (gbdt.cpp:333-355)."""
        return 0.0

    def to_string(self) -> str:
        return self.name

    def _apply_weights(self, g, h):
        if self.weights is None:
            return g, h
        w = self.weights[None, :]
        return g * w, h * w


class RegressionL2(Objective):
    name = "regression"
    boost_from_average = True

    @property
    def is_constant_hessian(self):
        return self.weights is None

    def init(self, metadata, num_data):
        super().init(metadata, num_data)

        @jax.jit
        def f(score, label, weights):
            g = score - label[None, :]
            h = jnp.ones_like(g)
            if weights is not None:
                g = g * weights[None, :]
                h = h * weights[None, :]
            return g, h
        self._f = f

    def get_gradients(self, score):
        return self._f(score, self.label, self.weights)

    def initial_score(self) -> float:
        lab = jax.device_get(self.label).astype(np.float64)
        if self.weights is not None:
            w = jax.device_get(self.weights).astype(np.float64)
            return float((lab * w).sum() / w.sum())
        return float(lab.mean())


class RegressionL1(Objective):
    name = "regression_l1"
    boost_from_average = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        eta = self.config.gaussian_eta

        @jax.jit
        def f(score, label, weights):
            lab = label[None, :]
            diff = score - lab
            w = jnp.ones_like(score) if weights is None else weights[None, :]
            g = jnp.where(diff >= 0.0, 1.0, -1.0) * w
            h = w * _gaussian_hessian(score, lab, g, eta, w)
            return g, h
        self._f = f

    def get_gradients(self, score):
        return self._f(score, self.label, self.weights)

    def initial_score(self) -> float:
        return float(np.median(jax.device_get(self.label).astype(np.float64)))


def _gaussian_hessian(y, t, g, eta, w):
    """Common::ApproximateHessianWithGaussian (common.h:436-445); the
    leading `w` factor is applied by the caller."""
    diff = y - t
    x = jnp.abs(diff)
    a = 2.0 * jnp.abs(g)  # w already folded into g by callers
    c = jnp.maximum((jnp.abs(y) + jnp.abs(t)) * eta, 1.0e-10)
    return jnp.exp(-x * x / (2.0 * c * c)) * a / (c * jnp.sqrt(2 * jnp.pi))


class RegressionHuber(Objective):
    name = "huber"
    boost_from_average = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        delta = self.config.huber_delta
        eta = self.config.gaussian_eta

        @jax.jit
        def f(score, label, weights):
            lab = label[None, :]
            diff = score - lab
            w = jnp.ones_like(score) if weights is None else weights[None, :]
            small = jnp.abs(diff) <= delta
            g = jnp.where(small, diff, jnp.sign(diff) * delta) * w
            h_small = w
            h_big = w * _gaussian_hessian(score, lab, jnp.sign(diff) * delta * w,
                                          eta, w)
            h = jnp.where(small, h_small, h_big)
            return g, h
        self._f = f

    def get_gradients(self, score):
        return self._f(score, self.label, self.weights)

    def initial_score(self) -> float:
        return float(np.mean(jax.device_get(self.label).astype(np.float64)))


class RegressionFair(Objective):
    name = "fair"
    boost_from_average = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        c = self.config.fair_c

        @jax.jit
        def f(score, label, weights):
            x = score - label[None, :]
            w = jnp.ones_like(score) if weights is None else weights[None, :]
            g = c * x / (jnp.abs(x) + c) * w
            h = c * c / ((jnp.abs(x) + c) ** 2) * w
            return g, h
        self._f = f

    def get_gradients(self, score):
        return self._f(score, self.label, self.weights)

    def initial_score(self) -> float:
        return float(np.mean(jax.device_get(self.label).astype(np.float64)))


class RegressionPoisson(Objective):
    name = "poisson"
    boost_from_average = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        mds = self.config.poisson_max_delta_step

        @jax.jit
        def f(score, label, weights):
            g = score - label[None, :]
            h = score + mds
            if weights is not None:
                g = g * weights[None, :]
                h = h * weights[None, :]
            return g, h
        self._f = f

    def get_gradients(self, score):
        return self._f(score, self.label, self.weights)

    def initial_score(self) -> float:
        return float(np.mean(jax.device_get(self.label).astype(np.float64)))


class BinaryLogloss(Objective):
    name = "binary"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sigmoid = config.sigmoid

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label)
        is_pos = lab > 0
        cnt_pos, cnt_neg = int(is_pos.sum()), int((~is_pos).sum())
        self.need_train = cnt_pos > 0 and cnt_neg > 0
        w_pos, w_neg = 1.0, 1.0
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.config.scale_pos_weight
        sigmoid = self.sigmoid

        @jax.jit
        def f(score, label, weights):
            is_p = label[None, :] > 0
            lbl = jnp.where(is_p, 1.0, -1.0)
            lw = jnp.where(is_p, w_pos, w_neg)
            response = -lbl * sigmoid / (1.0 + jnp.exp(lbl * sigmoid * score))
            absr = jnp.abs(response)
            g = response * lw
            h = absr * (sigmoid - absr) * lw
            if weights is not None:
                g = g * weights[None, :]
                h = h * weights[None, :]
            return g, h
        self._f = f

    def get_gradients(self, score):
        if not self.need_train:
            z = jnp.zeros_like(score)
            return z, z
        return self._f(score, self.label, self.weights)

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * score))

    def to_string(self):
        return f"binary sigmoid:{self.sigmoid:g}"


class MulticlassSoftmax(Objective):
    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_tree_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label).astype(np.int32)
        if lab.min() < 0 or lab.max() >= self.num_class:
            raise ValueError(
                f"Label must be in [0, {self.num_class}) for multiclass")
        self._label_int = jnp.asarray(lab)

        @jax.jit
        def f(score, label_int, weights):
            p = softmax(score, axis=0)                       # [K, N]
            onehot = (jax.lax.broadcasted_iota(jnp.int32, p.shape, 0)
                      == label_int[None, :])
            g = p - onehot.astype(p.dtype)
            h = 2.0 * p * (1.0 - p)
            if weights is not None:
                g = g * weights[None, :]
                h = h * weights[None, :]
            return g, h
        self._f = f

    def get_gradients(self, score):
        return self._f(score, self._label_int, self.weights)

    def convert_output(self, score):
        e = np.exp(score - score.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    def to_string(self):
        return f"multiclass num_class:{self.num_class}"


class MulticlassOVA(Objective):
    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_tree_per_iteration = config.num_class
        self.sigmoid = config.sigmoid

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label).astype(np.int32)
        self._label_int = jnp.asarray(lab)
        sigmoid = self.sigmoid

        @jax.jit
        def f(score, label_int, weights):
            is_p = (jax.lax.broadcasted_iota(jnp.int32, score.shape, 0)
                    == label_int[None, :])
            lbl = jnp.where(is_p, 1.0, -1.0)
            response = -lbl * sigmoid / (1.0 + jnp.exp(lbl * sigmoid * score))
            absr = jnp.abs(response)
            g = response
            h = absr * (sigmoid - absr)
            if weights is not None:
                g = g * weights[None, :]
                h = h * weights[None, :]
            return g, h
        self._f = f

    def get_gradients(self, score):
        return self._f(score, self._label_int, self.weights)

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * score))

    def to_string(self):
        return f"multiclassova num_class:{self.num_class} sigmoid:{self.sigmoid:g}"


class LambdarankNDCG(Objective):
    name = "lambdarank"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("Lambdarank tasks require query information")
        qb = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(qb) - 1
        sizes = np.diff(qb)
        D = int(sizes.max())
        Q = self.num_queries
        # padded doc-index matrix; pad slots point at sentinel N.
        # Vectorized construction — per-query Python loops cost minutes
        # at MS-LTR scale (~31k queries) on a small host
        j = np.arange(D)
        valid = j[None, :] < sizes[:, None]                     # [Q, D]
        doc_idx = np.where(valid, qb[:-1, None] + j[None, :],
                           num_data).astype(np.int32)
        gains = self.config.label_gain
        if not gains:
            gains = tuple(float(2 ** i - 1) for i in range(31))
        label_gain = np.asarray(gains, np.float64)
        lab = np.asarray(metadata.label).astype(np.int32)
        # inverse max DCG per query at max_position (rank_objective.hpp:60-69)
        k = self.config.max_position
        discount = 1.0 / np.log2(2.0 + np.arange(D))
        # sort LABELS descending (not gains): the reference's CalMaxDCG
        # does, and a custom label_gain table need not be monotonic
        lab_pad_np = np.concatenate([lab, [0]])
        lab_mat = np.where(valid, lab_pad_np[doc_idx], -1)
        lab_sorted = -np.sort(-lab_mat, axis=1)[:, :k]          # desc, top-k
        g_sorted = np.where(lab_sorted >= 0,
                            label_gain[np.maximum(lab_sorted, 0)], 0.0)
        md = (g_sorted * discount[None, : g_sorted.shape[1]]).sum(axis=1)
        inv_max_dcg = np.where(md > 0, 1.0 / np.maximum(md, 1e-300), 0.0)
        # chunk queries so the [q, D, D] pairwise block stays ~64MB.
        # Q is padded UP to a chunk multiple with all-sentinel queries
        # (empty mask -> zero lambdas) — requiring qc | Q would
        # degenerate to qc=1 (fully serial scan) whenever Q is prime
        sigmoid = self.config.sigmoid
        N = num_data
        qc = max(1, min(Q, (1 << 24) // max(D * D, 1)))
        Qp = qc * ((Q + qc - 1) // qc)
        if Qp > Q:
            doc_idx = np.pad(doc_idx, ((0, Qp - Q), (0, 0)),
                             constant_values=num_data)
            inv_max_dcg = np.pad(inv_max_dcg, (0, Qp - Q))
        self._doc_idx = jnp.asarray(doc_idx)
        self._mask = jnp.asarray(doc_idx < num_data)
        self._inv_max_dcg = jnp.asarray(inv_max_dcg, jnp.float32)
        self._label_gain = jnp.asarray(label_gain, jnp.float32)
        self._discount = jnp.asarray(discount, jnp.float32)
        self._lab_pad = jnp.asarray(np.concatenate([lab, [0]]).astype(jnp.int32))
        self._q_chunk = qc

        @jax.jit
        def f(score, lab_pad, doc_idx, mask, inv_max_dcg):
            s1 = score[0]
            s_pad = jnp.concatenate([s1, jnp.zeros(1, s1.dtype)])

            def one_chunk(carry, args):
                didx, msk, imd = args          # [qc, D], [qc, D], [qc]
                sc = s_pad[didx]               # [qc, D]
                lb = lab_pad[didx]             # [qc, D] int
                sc = jnp.where(msk, sc, -jnp.inf)
                order = jnp.argsort(-sc, axis=1)       # rank -> doc slot
                sc_s = jnp.take_along_axis(sc, order, axis=1)
                lb_s = jnp.take_along_axis(lb, order, axis=1)
                msk_s = jnp.take_along_axis(msk, order, axis=1)
                gain_s = self._label_gain[jnp.clip(lb_s, 0, label_gain.size - 1)]
                disc = self._discount[None, : sc_s.shape[1]]
                best = sc_s[:, 0]
                cnt = msk_s.sum(axis=1)
                worst = jnp.take_along_axis(
                    sc_s, jnp.maximum(cnt - 1, 0)[:, None], axis=1)[:, 0]
                # pairwise [qc, D(hi), D(lo)]
                ds = sc_s[:, :, None] - sc_s[:, None, :]
                valid = (msk_s[:, :, None] & msk_s[:, None, :]
                         & (lb_s[:, :, None] > lb_s[:, None, :]))
                dcg_gap = gain_s[:, :, None] - gain_s[:, None, :]
                Dq = sc_s.shape[1]
                paired_disc = jnp.abs(self._discount[None, :Dq, None]
                                      - self._discount[None, None, :Dq])
                delta = dcg_gap * paired_disc * imd[:, None, None]
                norm = jnp.where((best != worst)[:, None, None],
                                 0.01 + jnp.abs(ds), 1.0)
                delta = delta / norm
                p_lambda = 2.0 / (1.0 + jnp.exp(2.0 * sigmoid * ds))
                p_hess = p_lambda * (2.0 - p_lambda)
                p_lambda = jnp.where(valid, -p_lambda * delta, 0.0)
                p_hess = jnp.where(valid, p_hess * 2.0 * delta, 0.0)
                lam_s = p_lambda.sum(axis=2) - p_lambda.sum(axis=1)
                hes_s = p_hess.sum(axis=2) + p_hess.sum(axis=1)
                # unsort then scatter to flat [N]
                g_flat, h_flat = carry
                docs = jnp.take_along_axis(didx, order, axis=1)
                g_flat = g_flat.at[docs.reshape(-1)].add(
                    lam_s.reshape(-1), mode="drop")
                h_flat = h_flat.at[docs.reshape(-1)].add(
                    hes_s.reshape(-1), mode="drop")
                return (g_flat, h_flat), None

            g0 = jnp.zeros(N, s1.dtype)
            h0 = jnp.zeros(N, s1.dtype)
            Qn, D = doc_idx.shape
            nchunk = Qn // qc
            args = (doc_idx.reshape(nchunk, qc, D),
                    mask.reshape(nchunk, qc, D),
                    inv_max_dcg.reshape(nchunk, qc))
            (g, h), _ = jax.lax.scan(one_chunk, (g0, h0), args)
            if self.weights is not None:
                g = g * self.weights
                h = h * self.weights
            return g[None, :], h[None, :]

        self._f = f

    def get_gradients(self, score):
        return self._f(score, self._lab_pad, self._doc_idx, self._mask,
                       self._inv_max_dcg)


def create_objective(config: Config) -> Objective:
    table = {
        "regression": RegressionL2,
        "regression_l1": RegressionL1,
        "huber": RegressionHuber,
        "fair": RegressionFair,
        "poisson": RegressionPoisson,
        "binary": BinaryLogloss,
        "multiclass": MulticlassSoftmax,
        "multiclassova": MulticlassOVA,
        "lambdarank": LambdarankNDCG,
    }
    if config.objective not in table:
        raise ValueError(f"unknown objective: {config.objective}")
    return table[config.objective](config)


def objective_from_model_string(s: str, config: Config) -> Objective:
    """Recreate an objective from its model-file ToString() form
    (objective_function.cpp:33-57)."""
    toks = s.split()
    name = toks[0]
    kw = {}
    for t in toks[1:]:
        if ":" in t:
            k, v = t.split(":", 1)
            kw[k] = v
    cfg = config
    if "num_class" in kw:
        cfg = cfg.with_updates(num_class=int(kw["num_class"]))
    if "sigmoid" in kw:
        cfg = cfg.with_updates(sigmoid=float(kw["sigmoid"]))
    cfg = cfg.with_updates(objective=name)
    return create_objective(cfg)
