"""lightgbm_tpu: a TPU-native gradient boosting framework.

A from-scratch JAX/XLA/Pallas re-design of LightGBM (reference:
/root/reference, v2.0-era): binned leaf-wise histogram GBDT with
LightGBM-compatible parameters, model text format, and Python API —
histograms on the MXU, split scans on the VPU, distributed learners as
XLA collectives over a device mesh.
"""

__version__ = "0.3.0"

# Honor JAX_PLATFORMS even under TPU plugins that ignore the environment
# variable (the axon remote-TPU plugin does): a subprocess that asks for
# CPU must never open a TPU session — a second concurrent session can
# wedge the tunnel for the first.
import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    try:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:  # already initialized with a platform: leave it
        pass

from .config import Config, config_from_params, PARAM_ALIASES
from .dataset import Dataset as RawDataset, Metadata
from .tree import Tree
from .boosting.gbdt import GBDT, create_boosting
from .basic import Dataset, Booster, LightGBMError
from .engine import train, cv
from .callback import (early_stopping, print_evaluation, record_evaluation,
                       reset_parameter)
from .sklearn import LGBMModel, LGBMRegressor, LGBMClassifier, LGBMRanker
from .plotting import (plot_importance, plot_metric, plot_tree,
                       create_tree_digraph)

__all__ = [
    "Config", "config_from_params", "PARAM_ALIASES", "Metadata", "Tree",
    "GBDT", "create_boosting", "Dataset", "Booster", "LightGBMError",
    "train", "cv", "early_stopping", "print_evaluation", "record_evaluation",
    "reset_parameter", "LGBMModel", "LGBMRegressor", "LGBMClassifier",
    "LGBMRanker", "plot_importance", "plot_metric", "plot_tree",
    "create_tree_digraph", "serving", "online",
]


def __getattr__(name):
    # the online-prediction and online-learning subsystems are imported
    # on first use so the training/CLI import path stays free of server
    # and daemon machinery
    if name in ("serving", "online"):
        import importlib
        return importlib.import_module("." + name, __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
