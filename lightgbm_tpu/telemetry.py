"""Unified telemetry: structured span tracing + Prometheus exposition.

The reference proved where time went with the TIMETAG accumulators
(gbdt.cpp:20-29) and the GPU paper with per-kernel timing logs
(arXiv:1706.08359 §5); this package has outgrown both — five long-lived
process roles (trainer, online daemon, serving fleet, chip-queue
benches, multi-host pods) emit counters through `profiling` but nothing
correlates an event in one process with its cause in another.  This
module is the one telemetry layer they all share:

- **Structured spans** (`span(name, **attrs)`): a lock-guarded,
  stdlib-only context manager emitting one JSON line per span to the
  configured ``telemetry_path`` — trace-id/span-id/parent-id,
  monotonic-clock durations, wall-clock start timestamps, the process
  role and thread name.  Nesting is tracked per-thread; cross-thread
  and cross-process hops carry the ids explicitly (``trace_id=`` /
  ``parent_id=`` kwargs, `trace_context`, `call_in_context`), which is
  how one `/predict` request's trace id rides MicroBatcher → replica
  dispatch → the traffic log → the online daemon's window → refit →
  publish → registry hot-swap.  `scripts/trace_view.py` converts the
  JSONL to chrome://tracing / Perfetto ``trace_event`` JSON.
- **Point events** (`event(name, **attrs)`): zero-duration records in
  the same stream (per-iteration training records, breaker
  transitions, fault-injection firings).
- **Prometheus text exposition** (`prometheus_text()`): renders the
  `profiling` registry — monotone counters (every canonical constant
  always present), `observe()` reservoirs as summary quantiles — plus
  live gauges (process RSS/uptime, device memory where the backend
  reports it, caller-supplied serve gauges).  One scrape takes ONE
  locked snapshot of the registry, and pending `count_deferred` device
  totals are drained at the scrape — the caller pays the sync, the
  same contract as `profiling.counters()`.  `MetricsServer` serves it
  standalone on ``metrics_port`` for the trainer/daemon; the serving
  server mounts the same text at its own ``/metrics``.

Cost contract: with no ``telemetry_path`` configured, `span()` returns
ONE shared no-op singleton (no allocation) and `event()` returns after
a single cached boolean check — nothing is formatted, nothing is
written, no file is created.  Enabled, every record is host-side
formatting plus one locked file append: no device op, no host↔device
sync, so the BENCH_SANITIZE zero-retrace / zero-implicit-transfer
steady-state contract holds with telemetry on (tests/test_telemetry.py
pins it).  Enabling telemetry also forces the TIMETAG phase
accumulators on (`profiling.force_phases`) so per-iteration phase
wall-clock is available without the LIGHTGBM_TPU_TIMETAG env switch.

Configuration: ``telemetry_path`` Config key (aliases ``telemetry``,
``trace_path``, ``span_path``) or the ``LIGHTGBM_TPU_TELEMETRY`` env
var; ``metrics_port`` (aliases ``prometheus_port``,
``telemetry_port``).  docs/Observability.md has the span schema, the
propagation diagram, and the /metrics name table.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
import uuid
from typing import Callable, Dict, Iterator, Optional, Tuple

ENV_VAR = "LIGHTGBM_TPU_TELEMETRY"

_lock = threading.Lock()          # guards the sink (writes + swap)
_enabled = False                  # the ONE cached check of the off path
_path: Optional[str] = None
_sink = None                      # open append handle, under _lock
_process = "main"                 # role stamped into every record
_START_UNIX = time.time()
_START_MONO = time.monotonic()

_tls = threading.local()          # per-thread span context stack


# -- identity -----------------------------------------------------------


def new_trace_id() -> str:
    """A fresh 32-hex trace id (random; never derived from the clock)."""
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def _ctx_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current() -> Optional[Tuple[str, Optional[str]]]:
    """The calling thread's (trace_id, span_id) context, or None.  Hand
    it across threads with `call_in_context` / `trace_context` — thread
    locals do not follow work into executor pools."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def current_trace_id() -> Optional[str]:
    ctx = current()
    return ctx[0] if ctx else None


def current_span_id() -> Optional[str]:
    ctx = current()
    return ctx[1] if ctx else None


# -- enable / disable ---------------------------------------------------


def enabled() -> bool:
    return _enabled


def configure(path: str, process: Optional[str] = None) -> None:
    """Point the span sink at ``path`` (JSONL, append) and enable
    tracing.  Also forces the TIMETAG phase accumulators on so
    per-iteration phase wall-clock flows without the env switch."""
    global _enabled, _path, _sink
    if process is not None:
        set_process(process)
    with _lock:
        if _sink is None or _path != path:
            if _sink is not None:
                try:
                    _sink.close()
                except OSError:
                    pass
            _sink = open(path, "a", encoding="utf-8")
            _path = path
        # same-path reconfigure still re-enables: a sink write failure
        # degrades to disabled (_write), and an explicit configure()
        # must be able to bring telemetry back
        _enabled = True
    from . import profiling
    profiling.force_phases(True)


def set_process(role: str) -> None:
    """Stamp a process role (train/serve/online/...) into every record
    — the pid lane of the chrome-trace view."""
    global _process
    _process = str(role)


def reset() -> None:
    """Disable tracing and close the sink (tests call this so one
    test's telemetry config can never leak into the next)."""
    global _enabled, _path, _sink
    with _lock:
        if _sink is not None:
            try:
                _sink.close()
            except OSError:
                pass
        _sink = None
        _path = None
        _enabled = False
    from . import profiling
    profiling.force_phases(False)


def config_in_effect() -> Dict[str, object]:
    """What the /stats ``process`` block reports."""
    return {"enabled": _enabled, "path": _path, "process": _process}


# -- record sink --------------------------------------------------------


def _write(record: dict) -> None:
    global _enabled
    line = json.dumps(record, separators=(",", ":"), default=str)
    with _lock:
        sink = _sink
        if sink is None:
            return
        try:
            sink.write(line + "\n")
            sink.flush()
        except (OSError, ValueError):
            # a dead sink (disk full, closed fd) must degrade to
            # disabled, never take the serving/training loop down
            _enabled = False


# -- spans --------------------------------------------------------------


class _NoopSpan:
    """The disabled path: ONE module-level instance, handed out for
    every `span()` call — no allocation, no formatting, no file."""
    __slots__ = ()
    trace_id = None
    span_id = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "_t0", "_ts", "status", "error")

    def __init__(self, name: str, trace_id: Optional[str],
                 parent_id: Optional[str], attrs: dict):
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.span_id = _new_span_id()
        self.status = "ok"
        self.error = None

    def set(self, **attrs) -> None:
        """Attach attrs discovered mid-span (e.g. the resumed
        iteration, the swapped generation)."""
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        ctx = current()
        if self.trace_id is None:
            self.trace_id = ctx[0] if ctx else new_trace_id()
        if self.parent_id is None and ctx is not None:
            self.parent_id = ctx[1]
        _ctx_stack().append((self.trace_id, self.span_id))
        self._ts = time.time()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur_ms = (time.monotonic() - self._t0) * 1e3
        stack = _ctx_stack()
        if stack and stack[-1][1] == self.span_id:
            stack.pop()
        if exc is not None:
            self.status = "error"
            self.error = f"{exc_type.__name__}: {exc}"
        rec = {"kind": "span", "name": self.name, "trace": self.trace_id,
               "span": self.span_id, "parent": self.parent_id,
               "proc": _process,
               "thread": threading.current_thread().name,
               "ts": round(self._ts, 6), "dur_ms": round(dur_ms, 3),
               "status": self.status}
        if self.error:
            rec["error"] = self.error
        if self.attrs:
            rec["attrs"] = self.attrs
        _write(rec)
        return False


def span(name: str, *, trace_id: Optional[str] = None,
         parent_id: Optional[str] = None, **attrs):
    """A traced operation.  Use as a context manager::

        with telemetry.span("serve.request", rows=n) as sp:
            ...
            sp.set(generation=g)

    Trace id resolves: explicit ``trace_id=`` kwarg > the thread's
    current context > a fresh id.  Parent resolves: explicit
    ``parent_id=`` > the thread's current span.  Disabled: returns the
    shared no-op singleton (one cached check, zero allocation)."""
    if not _enabled:
        return _NOOP
    return _Span(name, trace_id, parent_id, attrs)


def event(name: str, *, trace_id: Optional[str] = None,
          parent_id: Optional[str] = None, **attrs) -> None:
    """A zero-duration record in the span stream (iteration records,
    breaker transitions, fault firings)."""
    if not _enabled:
        return
    ctx = current()
    if trace_id is None:
        trace_id = ctx[0] if ctx else new_trace_id()
    if parent_id is None and ctx is not None:
        parent_id = ctx[1]
    rec = {"kind": "event", "name": name, "trace": trace_id,
           "span": _new_span_id(), "parent": parent_id, "proc": _process,
           "thread": threading.current_thread().name,
           "ts": round(time.time(), 6), "dur_ms": 0.0}
    if attrs:
        rec["attrs"] = attrs
    _write(rec)


class _TraceContext:
    """Adopt an explicit (trace_id, span_id) as the thread's context —
    the cross-thread/cross-process propagation primitive."""
    __slots__ = ("_ctx",)

    def __init__(self, trace_id: str, span_id: Optional[str] = None):
        self._ctx = (trace_id, span_id)

    def __enter__(self):
        _ctx_stack().append(self._ctx)
        return self

    def __exit__(self, *exc) -> bool:
        stack = _ctx_stack()
        if stack and stack[-1] is self._ctx:
            stack.pop()
        return False


def trace_context(trace_id: str, span_id: Optional[str] = None):
    """``with trace_context(tid): ...`` — spans inside inherit ``tid``."""
    if not _enabled or trace_id is None:
        return _NOOP
    return _TraceContext(trace_id, span_id)


def call_in_context(ctx: Optional[Tuple[str, Optional[str]]],
                    fn: Callable, *args, **kwargs):
    """Run ``fn`` under a context captured on another thread with
    `current()` (executor-pool workers do not inherit thread locals)."""
    if ctx is None or not _enabled:
        return fn(*args, **kwargs)
    with _TraceContext(ctx[0], ctx[1]):
        return fn(*args, **kwargs)


# -- Prometheus text exposition -----------------------------------------

_METRIC_PREFIX = "lgbt_"
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_]")
_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))
# a labeled registry key (profiling.labeled): base{label="value",...}
_LABELED_KEY = re.compile(r"^(?P<base>[^{]+)\{(?P<labels>.*)\}$")


def sanitize_metric_name(name: str) -> str:
    """``serve.chunk_retries`` → ``lgbt_serve_chunk_retries`` (both the
    ``.`` and ``/`` spellings in the registry collapse to ``_``)."""
    s = _BAD_CHARS.sub("_", name).strip("_")
    s = re.sub(r"__+", "_", s)
    return _METRIC_PREFIX + s


def _split_labels(name: str) -> Tuple[str, str]:
    """Split a registry key into (base name, rendered label body).

    ``serve.requests{model="de"}`` → ``("serve.requests",
    'model="de"')``; label NAMES are sanitized to the Prometheus
    charset and VALUES get quote/backslash escaping, so one malformed
    key can never corrupt the whole exposition."""
    m = _LABELED_KEY.match(name)
    if m is None:
        return name, ""
    parts = []
    for pair in m.group("labels").split(","):
        k, _, v = pair.partition("=")
        v = v.strip().strip('"')
        v = v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")
        k = _BAD_CHARS.sub("_", k.strip()) or "label"
        parts.append(f'{k}="{v}"')
    return m.group("base"), ",".join(parts)


def _families(values: Dict[str, float]) -> "Dict[str, list]":
    """Group registry entries into metric families: {base name:
    [(label body, value), ...]} with unlabeled series first, so HELP
    and TYPE are emitted once per FAMILY even when a name exports both
    a fleet-wide series and per-model labeled series."""
    fams: Dict[str, list] = {}
    for name in values:
        base, labels = _split_labels(name)
        fams.setdefault(base, []).append((labels, values[name]))
    for series in fams.values():
        series.sort(key=lambda s: (s[0] != "", s[0]))
    return fams


def _fmt(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _current_rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None


def _peak_rss_bytes() -> Optional[int]:
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except (ImportError, OSError):
        return None


def _device_gauges() -> Dict[str, float]:
    """Device-memory gauges where the backend reports them (TPU/GPU;
    the CPU backend returns None/raises — silently absent).  Importing
    jax here is the scrape paying for device introspection, consistent
    with the deferred-counter drain."""
    out: Dict[str, float] = {}
    try:
        import jax
        devs = jax.local_devices()
        out["process.device_count"] = float(len(devs))
        stats = devs[0].memory_stats() if devs else None
        if stats:
            for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
                if stats.get(key) is not None:
                    out[f"device.{key}"] = float(stats[key])
    except Exception:  # noqa: BLE001 — a scrape must never raise
        pass
    return out


def process_gauges() -> Dict[str, float]:
    g: Dict[str, float] = {
        "process.uptime_seconds": round(time.monotonic() - _START_MONO, 3),
        "process.start_time_seconds": round(_START_UNIX, 3),
    }
    rss = _current_rss_bytes()
    if rss is not None:
        g["process.resident_memory_bytes"] = float(rss)
    peak = _peak_rss_bytes()
    if peak is not None:
        g["process.peak_resident_memory_bytes"] = float(peak)
    g.update(_device_gauges())
    return g


def prometheus_text(gauges: Optional[Dict[str, float]] = None) -> str:
    """The /metrics payload (Prometheus text exposition format 0.0.4).

    One locked snapshot of the profiling registry (counters incl. every
    canonical constant, reservoirs as summary quantiles) + live gauges.
    Pending `count_deferred` device totals drain here — the scrape pays
    the sync, the hot path never does."""
    from . import profiling
    from .diagnostics import sanitize
    counters, summaries = profiling.snapshot()
    for name in profiling.CANONICAL_COUNTERS:
        counters.setdefault(name, 0.0)
    # LockSanitizer counters (diagnostics/locksan.py) are canonical the
    # same way: a scrape always shows lgbt_sanitize_lock_cycles_total,
    # so "0" is an observed verdict, not a missing series
    for name in (sanitize.LOCK_ACQUIRES, sanitize.LOCK_WAITS,
                 sanitize.LOCK_CYCLES):
        counters.setdefault(name, 0.0)
    lines = []
    cfams = _families(counters)
    for base in sorted(cfams):
        m = sanitize_metric_name(base) + "_total"
        lines.append(f"# HELP {m} counter {base!r} (lightgbm_tpu profiling)")
        lines.append(f"# TYPE {m} counter")
        for labels, v in cfams[base]:
            suffix = f"{{{labels}}}" if labels else ""
            lines.append(f"{m}{suffix} {_fmt(max(v, 0.0))}")
    sfams = _families(summaries)
    for base in sorted(sfams):
        m = sanitize_metric_name(base)
        lines.append(f"# HELP {m} summary of {base!r} samples")
        lines.append(f"# TYPE {m} summary")
        for labels, s in sfams[base]:
            for q, key in _QUANTILES:
                if key in s:
                    qlab = (f'{labels},quantile="{q}"' if labels
                            else f'quantile="{q}"')
                    lines.append(f"{m}{{{qlab}}} {_fmt(s[key])}")
            suffix = f"{{{labels}}}" if labels else ""
            lines.append(f"{m}_count{suffix} {_fmt(s.get('count', 0))}")
    merged = process_gauges()
    merged.update(gauges or {})
    gfams = _families({k: v for k, v in merged.items() if v is not None})
    for base in sorted(gfams):
        m = sanitize_metric_name(base)
        lines.append(f"# HELP {m} gauge {base!r}")
        lines.append(f"# TYPE {m} gauge")
        for labels, v in gfams[base]:
            suffix = f"{{{labels}}}" if labels else ""
            lines.append(f"{m}{suffix} {_fmt(v)}")
    return "\n".join(lines) + "\n"


# -- standalone /metrics server (trainer / online daemon) ---------------


class MetricsServer:
    """A stdlib HTTP listener serving `prometheus_text()` at /metrics
    (plus /healthz) — the scrape surface for process roles that have no
    HTTP server of their own (``metrics_port`` Config key).  The
    serving fleet mounts the same payload on its own endpoint
    instead."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 gauges_fn: Optional[Callable[[], Dict[str, float]]] = None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        self.gauges_fn = gauges_fn
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            server_version = "lightgbm-tpu-metrics"

            def log_message(self, fmt, *args):
                pass                            # scrapes are chatty

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    try:
                        extra = outer.gauges_fn() if outer.gauges_fn else None
                        body = prometheus_text(extra).encode()
                    except Exception as e:  # noqa: BLE001
                        body = f"# scrape failed: {e}\n".encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/healthz":
                    body = b'{"status": "ok"}\n'
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="lgbt-metrics", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def start_metrics_server(port: int, host: str = "127.0.0.1",
                         gauges_fn: Optional[Callable[[], Dict[str, float]]]
                         = None) -> MetricsServer:
    """Build + start a MetricsServer; caller owns ``.close()``."""
    srv = MetricsServer(port, host=host, gauges_fn=gauges_fn).start()
    from . import log
    log.info(f"telemetry: /metrics on http://{srv.host}:{srv.port}")
    return srv


# -- /stats process block ----------------------------------------------


def process_info() -> Dict[str, object]:
    """The /stats ``process`` block: uptime, RSS high-water mark, jax
    backend + device kind/count, package version, telemetry config in
    effect."""
    info: Dict[str, object] = {
        "role": _process,
        "uptime_s": round(time.monotonic() - _START_MONO, 3),
        "pid": os.getpid(),
        "version": "unknown",
        "telemetry": config_in_effect(),
    }
    rss = _current_rss_bytes()
    info["rss_mb"] = round(rss / 1e6, 1) if rss is not None else 0.0
    peak = _peak_rss_bytes()
    info["peak_rss_mb"] = round(peak / 1e6, 1) if peak is not None else 0.0
    try:
        import lightgbm_tpu
        info["version"] = lightgbm_tpu.__version__
    except Exception:  # noqa: BLE001 — partial import during bootstrap
        pass
    try:
        import jax
        devs = jax.local_devices()
        info["backend"] = jax.default_backend()
        info["device_count"] = len(devs)
        info["device_kind"] = devs[0].device_kind if devs else "none"
    except Exception:  # noqa: BLE001 — jax not initialized yet
        info["backend"] = "uninitialized"
        info["device_count"] = 0
        info["device_kind"] = "none"
    return info


# env bootstrap: LIGHTGBM_TPU_TELEMETRY=<path> enables at import, the
# same pattern as profiling's LIGHTGBM_TPU_TIMETAG switch.  An
# unwritable path degrades to disabled with a warning — an env var must
# never make the package unimportable (the explicit `telemetry_path`
# config key, by contrast, raises: the user asked for a sink that
# cannot exist).
if os.environ.get(ENV_VAR):
    try:
        configure(os.environ[ENV_VAR])
    except OSError as _e:
        import sys as _sys
        print(f"[LightGBM-TPU] [Warning] telemetry disabled: cannot open "
              f"{ENV_VAR}={os.environ[ENV_VAR]!r} ({_e})",
              file=_sys.stderr)
