"""Stdlib-only JSON-lines HTTP scoring endpoint.

Endpoints:
- ``POST /predict`` — body is JSON lines, one row per line: a JSON array
  of feature values, or ``{"features": [...]}``.  A single JSON object
  ``{"rows": [[...], ...]}`` is also accepted.  Response is JSON lines,
  one prediction per input row (a number, or an array for multiclass).
  ``?raw_score=1`` returns raw margins.  On a multi-tenant catalog
  (docs/serving.md "Multi-tenant catalog") the request routes by model
  id — ``?model=<id>`` query param, ``"model"`` object-body field, or
  ``X-Model-Id`` header; no id = the default tenant (the single-model
  contract), an unknown id = 404.  The response names the tenant that
  scored it (``X-Model-Id``) and its generation
  (``X-Model-Generation``).  A trace id rides in via the
  ``X-Trace-Id`` header or a ``"trace_id"`` field in the object body
  (one is generated when telemetry is on and none arrives); the
  response echoes it as ``X-Trace-Id``, and the request's whole path —
  ingress span → batcher dispatch → replica execution — shares it
  (docs/Observability.md).
- ``GET /healthz`` — liveness + active model generation.
- ``GET /stats`` — request/row/batch counters, compiled-predictor cache
  hits/misses, latency percentiles, queue depth, swap history, the
  profiling phase totals, and the ``process`` block (uptime, RSS, jax
  backend/devices, version, telemetry config).
- ``GET /metrics`` — Prometheus text exposition of the profiling
  registry + serve gauges (telemetry.prometheus_text).

Wired into the CLI as ``task=serve`` (application.py): requests flow
HTTP handler → MicroBatcher → PredictorRuntime, with ModelRegistry
hot-swapping generations underneath.
"""
from __future__ import annotations

import json
import re
import threading
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler
from typing import Optional, Tuple

import numpy as np

from .. import log, profiling, telemetry
from ..diagnostics import locksan, sanitize
from ..httpd import SeveringHTTPServer
from ..config import MODEL_ID_RE, Config, parse_serve_models
from ..log import LightGBMError
from .batcher import ServerOverloadedError
from .catalog import ModelCatalog, UnknownModelError
from .registry import ModelRegistry
from .runtime import NoHealthyReplicaError


def _parse_predict_body(body: bytes) -> Tuple[np.ndarray, Optional[str],
                                              Optional[str]]:
    """Rows plus the optional ``trace_id`` and ``model`` fields of the
    object form (the body-level model id routes multi-tenant catalogs;
    JSON-lines bodies route via the query param / X-Model-Id header)."""
    text = body.decode("utf-8").strip()
    if not text:
        raise ValueError("empty request body")
    obj = None
    trace_id: Optional[str] = None
    model_id: Optional[str] = None
    if text.startswith("{"):
        try:                                 # whole-body object form,
            obj = json.loads(text)           # pretty-printed or not
        except json.JSONDecodeError:
            obj = None                       # fall through to JSON lines
    if obj is not None:
        tid = obj.get("trace_id")
        if tid:
            trace_id = str(tid)
        mid = obj.get("model")
        if mid:
            model_id = str(mid)
        if "rows" in obj:
            rows = obj["rows"]
        elif "features" in obj:
            rows = [obj["features"]]
        else:
            raise ValueError('object body needs "rows" or "features"')
    else:
        rows = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            item = json.loads(line)
            rows.append(item["features"] if isinstance(item, dict) else item)
    X = np.asarray(rows, dtype=np.float64)
    if X.ndim != 2:
        raise ValueError("rows must all have the same feature count")
    return X, trace_id, model_id


# client-supplied trace ids must be header-safe and bounded before they
# are echoed or persisted (see do_POST)
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._:-]{1,128}$")


class _Handler(BaseHTTPRequestHandler):
    server_version = "lightgbm-tpu-serve"
    protocol_version = "HTTP/1.1"
    # response headers + payload leave in separate small writes; with
    # Nagle on, that write-write pattern can stall a whole delayed-ACK
    # period (~40ms) per request at the tail — TCP_NODELAY is table
    # stakes for a latency-gated scoring endpoint
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):       # route per-request chatter
        log.debug(f"http {fmt % args}")      # away from stderr

    def _respond(self, code: int, payload: bytes,
                 content_type: str = "application/json",
                 headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _respond_json(self, code: int, obj,
                      headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        self._respond(code, (json.dumps(obj) + "\n").encode(),
                      headers=headers)

    def do_GET(self):
        srv: "PredictionServer" = self.server.prediction_server
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            # liveness PLUS swap freshness: live generations per tenant,
            # the published generation each model's .meta.json sidecar
            # names on disk, and the tenants whose on-disk model no
            # longer matches the loaded signature ("stale" — a pending
            # or refused swap).  The router tier's health probe
            # (lightgbm_tpu/router/) reads these to tell a
            # partially-swapped backend from a healthy one.
            models, published, stale = {}, {}, []
            for mid in srv.catalog.ids():
                reg = srv.catalog.get(mid).registry
                models[mid] = reg.generation
                meta = srv._read_json_sidecar(
                    reg.model_path + ".meta.json", "online meta")
                published[mid] = (meta or {}).get("generation")
                if reg.pending_publish():
                    stale.append(mid)
            self._respond_json(200, {
                "status": "ok",
                "generation": srv.registry.generation,
                "models": models,
                "published": published,
                "stale": stale,
                # co-stack group count: the router's health sweep
                # surfaces per-backend executable-sharing at /stats
                "groups": len(srv.catalog._groups),
                # per-tenant co-stack compatibility keys: the router's
                # co-stack-aware placement hashes THESE (not tenant
                # ids) so same-key tenants land on one backend and
                # actually group (docs/Router.md)
                "group_keys": srv.catalog.group_keys()})
        elif path == "/stats":
            self._respond_json(200, srv.stats())
        elif path == "/metrics":
            # Prometheus text exposition; the scrape drains any pending
            # deferred device counters (it pays the sync, by contract)
            self._respond(200, srv.metrics_text().encode(),
                          content_type="text/plain; version=0.0.4; "
                                       "charset=utf-8")
        else:
            self._respond_json(404, {"error": f"unknown path {path}"})

    def do_POST(self):
        srv: "PredictionServer" = self.server.prediction_server
        # drain the body FIRST: keep-alive (HTTP/1.1) would otherwise
        # parse leftover body bytes as the connection's next request
        # line after an early 404/400
        if "Content-Length" not in self.headers:
            self.close_connection = True     # unknown body length
            body = b""
        else:
            body = self.rfile.read(int(self.headers["Content-Length"]))
        path, _, query = self.path.partition("?")
        if path != "/predict":
            self._respond_json(404, {"error": f"unknown path {path}"})
            return
        trace_id = None
        try:
            from urllib.parse import parse_qs
            X, body_trace, body_model = _parse_predict_body(body)
            # trace ingress: header first, then the body field; with
            # telemetry on and neither present, this server MINTS the id
            # so the request is traceable end-to-end regardless of the
            # client.  Ids are VALIDATED at ingress: the body field is
            # attacker-shaped bytes echoed into the X-Trace-Id response
            # header (CR/LF there is header injection) and written into
            # spans/the traffic log — a malformed id is dropped, not
            # propagated.
            raw_tid = self.headers.get("X-Trace-Id") or body_trace
            trace_id = (raw_tid if raw_tid is not None
                        and _TRACE_ID_RE.match(raw_tid) else None)
            if trace_id is None and telemetry.enabled():
                trace_id = telemetry.new_trace_id()
            qs = parse_qs(query)
            raw = (qs["raw_score"][0] in ("1", "true")
                   if "raw_score" in qs else srv.default_raw)
            kind = "raw" if raw else "value"
            # model routing (multi-tenant catalog): query param > body
            # field > X-Model-Id header; absent = the default tenant.
            # Validated like trace ids — the id is echoed into a
            # response header and labels the per-model metric series.
            raw_mid = (qs["model"][0] if "model" in qs else None) \
                or body_model or self.headers.get("X-Model-Id")
            if raw_mid is not None and not MODEL_ID_RE.match(raw_mid):
                self._respond_json(400, {"error": (
                    "malformed model id (must match "
                    "[A-Za-z0-9._-]{1,64})")})
                return
            tenant = srv.catalog.get(raw_mid)
            model_id = tenant.model_id
            with telemetry.span("serve.request", trace_id=trace_id,
                                rows=int(X.shape[0]), kind=kind,
                                model=model_id) as sp:
                _tenant, fut = srv.catalog.submit(
                    X, kind=kind, model_id=model_id, trace_id=trace_id,
                    parent_id=sp.span_id)
                preds = fut.result(timeout=srv.request_timeout_s)
                # the generation that actually scored this batch
                # (pinned by the flusher), not whatever is live at
                # response time
                generation = getattr(fut, "generation",
                                     tenant.registry.generation)
                sp.set(generation=generation)
        except UnknownModelError as e:
            self._respond_json(404, {"error": str(e)})
            return
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._respond_json(400, {"error": str(e)})
            return
        except _FutureTimeout:               # serve_request_timeout_ms
            profiling.count("serve.timeouts")
            self._respond_json(504, {"error": (
                "request timed out after "
                f"{srv.request_timeout_s * 1e3:g} ms "
                "(serve_request_timeout_ms); the batch may still be "
                "scoring — retry with backoff")})
            return
        except (ServerOverloadedError, NoHealthyReplicaError) as e:
            # shed load: admission control or a fully circuit-broken
            # fleet — 503 tells the client to retry, unlike a raw 500,
            # and Retry-After paces router- and client-level backoff so
            # a recovering fleet is not hammered flat
            self._respond_json(503, {"error": str(e)},
                               headers=(("Retry-After", "1"),))
            return
        except LightGBMError as e:
            self._respond_json(400, {"error": str(e)})
            return
        except Exception as e:               # scoring/internal failure
            self._respond_json(500, {"error": str(e)})
            return
        lines = "".join(
            json.dumps(p.tolist() if isinstance(p, np.ndarray) else float(p))
            + "\n" for p in preds)
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonlines")
        self.send_header("X-Model-Generation", str(generation))
        self.send_header("X-Model-Id", model_id)
        if trace_id:
            self.send_header("X-Trace-Id", trace_id)
        out = lines.encode()
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)


class PredictionServer:
    """HTTP server + model catalog + model-poll thread, with clean
    teardown (context manager) so tests never leak a listener.

    Accepts either a single `ModelRegistry` (wrapped as a one-tenant
    catalog — the pre-catalog contract, bit-for-bit) or an explicit
    `ModelCatalog` for multi-tenant serving.  Each tenant owns its
    batcher (one flusher per predictor replica — continuous batching),
    admission budget, and swap/canary machinery."""

    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 catalog: Optional[ModelCatalog] = None,
                 host: str = "127.0.0.1",
                 port: int = 0, max_batch_rows: int = 4096,
                 flush_deadline_ms: float = 5.0,
                 model_poll_seconds: float = 10.0,
                 default_raw: bool = False, max_pending_rows: int = 0,
                 request_timeout_ms: float = 120000.0):
        if (registry is None) == (catalog is None):
            raise ValueError("PredictionServer needs exactly one of "
                             "registry= or catalog=")
        if catalog is None:
            catalog = ModelCatalog.from_registry(
                registry, max_batch_rows=max_batch_rows,
                flush_deadline_ms=flush_deadline_ms,
                max_pending_rows=max_pending_rows)
        self.catalog = catalog
        self.default_raw = default_raw
        self.model_poll_seconds = float(model_poll_seconds)
        # /predict waiters give up (HTTP 504) after this long; the
        # Config key is serve_request_timeout_ms
        self.request_timeout_s = max(float(request_timeout_ms), 1.0) / 1e3
        self._httpd = SeveringHTTPServer((host, port), _Handler)
        self._httpd.prediction_server = self
        self.host, self.port = self._httpd.server_address[:2]
        self._stop = threading.Event()
        self._threads = []

    # the single-model attribute surface (tests, benches, operators'
    # scripts) stays: `registry`/`batcher` are the DEFAULT tenant's
    @property
    def registry(self) -> ModelRegistry:
        return self.catalog.default().registry

    @property
    def batcher(self):
        return self.catalog.default().batcher

    @staticmethod
    def _read_json_sidecar(path: str, what: str):
        """Load an optional JSON sidecar.  Missing is normal (None); a
        file that EXISTS but does not parse is an operator-relevant
        failure — logged with the exception class/message and counted,
        never silently swallowed."""
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            profiling.count("registry/meta_failures")
            log.warning(f"unreadable {what} sidecar {path} "
                        f"({type(e).__name__}: {e})")
            return None

    @classmethod
    def _model_meta(cls, model_path: str):
        """The online trainer's ``<model>.meta.json`` sidecar (generation
        provenance: refresh mode, rows, publish time) merged with its
        ``.state.json`` daemon state (traffic offset/skip counters, last
        refresh outcome) under ``daemon`` — or None when the model is
        not published by an online loop."""
        meta = cls._read_json_sidecar(model_path + ".meta.json",
                                      "online meta")
        state = cls._read_json_sidecar(model_path + ".state.json",
                                       "online daemon state")
        if state is not None:
            meta = dict(meta or {})
            meta["daemon"] = state
        return meta

    def _serve_gauges(self) -> dict:
        """Live fleet gauges for the /metrics exposition — the state a
        counter cannot carry (current queue depth, healthy replicas,
        the generation in service).  The unlabeled gauges describe the
        DEFAULT tenant (the single-model contract); the catalog's
        per-model labeled series ride alongside."""
        runtime = self.registry.current()
        g = {
            "serve.queue_depth": self.batcher.queue_depth,
            "serve.pending_rows_cap": self.batcher.max_pending_rows,
            "serve.batch_workers": self.batcher.workers,
            "serve.replicas": getattr(runtime, "replica_count", 1),
            "serve.healthy_replicas": (runtime.healthy_count()
                                       if hasattr(runtime, "healthy_count")
                                       else 1),
            "serve.model_generation": self.registry.generation,
            "serve.swaps": self.registry.swaps,
        }
        g.update(self.catalog.gauges())
        return g

    def metrics_text(self) -> str:
        return telemetry.prometheus_text(self._serve_gauges())

    def stats(self) -> dict:
        """The operator view.  Top-level fields keep describing the
        DEFAULT tenant plus the fleet-wide counters (the pre-catalog
        contract); the ``models`` block carries per-tenant SLO
        accounting (requests/rows/p99/queue/rejections), swap + canary
        state, and executable-cache residency."""
        runtime = self.registry.current()
        return {
            "generation": self.registry.generation,
            "default_model": self.catalog.default_id,
            "models": self.catalog.tenant_stats(),
            # cross-model co-stack groups (docs/serving.md "Cross-model
            # batching"): which tenants share one compiled executable,
            # restack/compile churn, shared-fleet health
            "groups": self.catalog.group_stats(),
            # uptime / RSS / backend / version / telemetry config — the
            # operator's "which process is this" block
            "process": telemetry.process_info(),
            "model_path": self.registry.model_path,
            # generation metadata published by the task=online trainer
            # (lightgbm_tpu/online/trainer.py), when this model is one
            "online": self._model_meta(self.registry.model_path),
            "requests": profiling.counter_value("serve.requests"),
            "rows": profiling.counter_value("serve.rows"),
            "batches": profiling.counter_value("serve.batches"),
            "queue_depth": self.batcher.queue_depth,
            "cache_hits": profiling.counter_value("serve.cache_hit"),
            "cache_misses": profiling.counter_value("serve.cache_miss"),
            "compile_seconds": profiling.counter_value(
                "serve.compile_seconds"),
            "generation_cache": {
                "hits": runtime.cache_hits,
                "misses": runtime.cache_misses,
                "buckets": [list(k) for k in runtime.buckets_compiled()],
            },
            # the fleet view: replica count, per-replica dispatch
            # counters (least-loaded balance evidence), kernel in use,
            # and per-replica circuit-breaker health + failover counters
            "replicas": {
                "count": getattr(runtime, "replica_count", 1),
                "healthy": (runtime.healthy_count()
                            if hasattr(runtime, "healthy_count") else 1),
                "dispatches": (runtime.replica_dispatches()
                               if hasattr(runtime, "replica_dispatches")
                               else []),
                "health": (runtime.replica_health()
                           if hasattr(runtime, "replica_health") else []),
                "chunk_retries": getattr(runtime, "chunk_retries", 0),
                "broken_total": profiling.counter_value(
                    profiling.SERVE_REPLICA_BROKEN),
                "readmitted_total": profiling.counter_value(
                    profiling.SERVE_REPLICA_READMITTED),
                "predict_kernel": getattr(runtime, "predict_kernel",
                                          "walk"),
                # the request-path kernel variant actually in service
                # ("binned" = ingress quantization + integer traversal)
                "serve_quantize": getattr(runtime, "variant", "raw"),
            },
            "quantize_bytes_in": profiling.counter_value(
                profiling.SERVE_QUANTIZE_BYTES_IN),
            "binned_requests": profiling.counter_value(
                profiling.SERVE_BINNED_REQUESTS),
            "batch_workers": self.batcher.workers,
            "rejected": self.batcher.rejected,
            "timeouts": profiling.counter_value("serve.timeouts"),
            "latency_ms": profiling.summary("serve.latency_ms"),
            "queue_depth_seen": profiling.summary("serve.queue_depth"),
            "swaps": self.registry.swaps,
            "swap_failures": self.registry.swap_failures,
            "last_swap_error": self.registry.last_swap_error,
            "phase_totals_s": {k: round(v, 6)
                               for k, v in profiling.timings().items()
                               if k.startswith("serve/")},
            # LockSanitizer verdict (diagnostics/locksan.py): armed
            # under LIGHTGBM_TPU_LOCKSAN/BENCH_SANITIZE, lock_cycles
            # MUST stay 0 — a nonzero here is a latent ABBA deadlock
            # witnessed on this process's actual acquisitions
            "locksan": {
                "armed": locksan.armed(),
                "lock_acquires": profiling.counter_value(
                    sanitize.LOCK_ACQUIRES),
                "lock_waits": profiling.counter_value(
                    sanitize.LOCK_WAITS),
                "lock_cycles": profiling.counter_value(
                    sanitize.LOCK_CYCLES),
                "lock_hold_ms": profiling.summary(sanitize.LOCK_HOLD_MS),
                "cycles": locksan.cycles()[:4],
            },
        }

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "PredictionServer":
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="lgbt-serve-http", daemon=True)
        t.start()
        self._threads.append(t)
        if self.model_poll_seconds > 0:
            p = threading.Thread(target=self._poll_loop,
                                 name="lgbt-serve-poll", daemon=True)
            p.start()
            self._threads.append(p)
        log.info(f"serving on http://{self.host}:{self.port} "
                 f"(generation {self.registry.generation})")
        return self

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.model_poll_seconds):
            try:
                self.catalog.poll_once()     # every tenant's path
            except Exception as e:           # never kill the poll loop
                log.warning(f"model poll failed: {e}")

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        # sever established keep-alive connections so an in-process
        # stop looks like a process kill to clients holding pooled
        # connections (the router's breaker contract depends on it)
        self._httpd.close_client_connections()
        self._httpd.server_close()
        self.catalog.close()
        for t in self._threads:
            t.join(timeout=10)

    def __enter__(self) -> "PredictionServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def catalog_models_from_config(cfg: Config) -> "dict":
    """The ``{model id: path}`` map a config describes: `serve_models`
    entries, plus `input_model` as the ``default`` tenant when set
    (requests that name no model land there — the single-model
    contract).  With only `serve_models`, the FIRST entry is the
    default."""
    models = parse_serve_models(cfg.serve_models)
    if cfg.input_model:
        dup = models.get("default")
        if dup is not None and dup != cfg.input_model:
            # refusing beats silently serving the wrong file: both
            # sources claim the default tenant with different models
            raise LightGBMError(
                "input_model and a serve_models entry both name the "
                f"'default' tenant with different paths "
                f"({cfg.input_model!r} vs {dup!r}); rename the entry "
                "or drop input_model")
        merged = {"default": cfg.input_model}
        for mid, path in models.items():
            if mid != "default":
                merged[mid] = path
        return merged
    if not models:
        raise LightGBMError("task=serve needs a model: set "
                            "input_model=<file> and/or "
                            "serve_models=id=path,...")
    return models


def server_from_config(cfg: Config) -> PredictionServer:
    """Build (not start) a PredictionServer from CLI/config parameters:
    one catalog tenant per `serve_models` entry (plus `input_model` as
    the default tenant), shared serving knobs across tenants."""
    models = catalog_models_from_config(cfg)
    catalog = ModelCatalog(
        models, params={"verbose": cfg.verbose},
        default_id=next(iter(models)),
        cache_budget_mb=cfg.serve_cache_budget_mb,
        num_iteration=cfg.num_iteration_predict,
        max_batch_rows=cfg.max_batch_rows,
        min_bucket_rows=cfg.min_bucket_rows,
        flush_deadline_ms=cfg.flush_deadline_ms,
        max_pending_rows=cfg.max_pending_rows,
        predict_kernel=cfg.predict_kernel,
        replicas=cfg.serve_replicas,
        failure_threshold=cfg.replica_failure_threshold,
        serve_quantize=cfg.serve_quantize,
        shadow_fraction=cfg.serve_shadow_fraction,
        shadow_requests=cfg.serve_shadow_requests,
        shadow_max_divergence=cfg.serve_shadow_max_divergence,
        costack=cfg.serve_costack,
        costack_kernel=cfg.costack_kernel,
        costack_segment_trees=cfg.costack_segment_trees)
    return PredictionServer(
        catalog=catalog, host=cfg.serve_host, port=cfg.serve_port,
        model_poll_seconds=cfg.model_poll_seconds,
        request_timeout_ms=cfg.serve_request_timeout_ms,
        default_raw=cfg.is_predict_raw_score)


def serve_from_config(cfg: Config) -> None:
    """Blocking ``task=serve`` entry: serve until SIGINT/SIGTERM."""
    import signal

    server = server_from_config(cfg)
    server.catalog.install_sighup()
    done = threading.Event()

    def _on_term(_signum, _frame):
        done.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass
    with server:
        try:
            done.wait()
        except KeyboardInterrupt:
            pass
    log.info("serving stopped")
