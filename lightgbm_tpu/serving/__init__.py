"""lightgbm_tpu.serving — TPU-native online prediction.

Four layers, composed bottom-up:

- `runtime`  — PredictorRuntime: AOT-compiled executables cached per
  (model generation, row bucket, output kind); power-of-two bucketing +
  padding keeps every request on a warm executable.
- `batcher`  — MicroBatcher: coalesces concurrent requests up to
  `max_batch_rows` or a `flush_deadline_ms` deadline, scatters results
  back per request.
- `registry` — ModelRegistry: versioned atomic hot-swap (mtime poll or
  SIGHUP) with pre-swap warmup and rollback on a bad model.
- `server`   — PredictionServer: stdlib JSON-lines HTTP endpoint
  (/predict, /healthz, /stats), the `task=serve` CLI entry.
"""
from .runtime import PredictorRuntime, row_bucket
from .batcher import MicroBatcher
from .registry import ModelRegistry
from .server import PredictionServer, serve_from_config, server_from_config

__all__ = [
    "PredictorRuntime", "row_bucket", "MicroBatcher", "ModelRegistry",
    "PredictionServer", "serve_from_config", "server_from_config",
]
