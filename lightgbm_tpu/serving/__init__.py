"""lightgbm_tpu.serving — TPU-native online prediction.

Four layers, composed bottom-up:

- `runtime`  — PredictorRuntime: the model replicated across local
  devices (least-loaded dispatch), AOT-compiled executables cached per
  (replica, model generation, row bucket, output kind); power-of-two
  bucketing + padding keeps every request on a warm executable; the
  ensemble traversal is the `predict_kernel` dial (tensorized | walk,
  ops/predict.py).
- `batcher`  — MicroBatcher: continuous batching — admits concurrent
  requests into the forming batch up to `max_batch_rows` or a
  `flush_deadline_ms` deadline (monotonic clock), one flusher per
  replica, optional admission control (`max_pending_rows` → 503).
- `registry` — ModelRegistry: versioned atomic hot-swap (mtime poll or
  SIGHUP) with pre-swap warmup of every traffic bucket for BOTH output
  kinds, and rollback on a bad model.
- `server`   — PredictionServer: stdlib JSON-lines HTTP endpoint
  (/predict, /healthz, /stats), the `task=serve` CLI entry.
"""
from .runtime import (OUTPUT_KINDS, PredictorRuntime,
                      resolve_serve_replicas, row_bucket)
from .batcher import MicroBatcher, ServerOverloadedError
from .registry import ModelRegistry
from .server import PredictionServer, serve_from_config, server_from_config

__all__ = [
    "OUTPUT_KINDS", "PredictorRuntime", "resolve_serve_replicas",
    "row_bucket", "MicroBatcher", "ServerOverloadedError", "ModelRegistry",
    "PredictionServer", "serve_from_config", "server_from_config",
]
