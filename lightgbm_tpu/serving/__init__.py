"""lightgbm_tpu.serving — TPU-native online prediction.

Four layers, composed bottom-up:

- `runtime`  — PredictorRuntime: the model replicated across local
  devices (least-loaded dispatch), AOT-compiled executables cached per
  (replica, model generation, row bucket, output kind); power-of-two
  bucketing + padding keeps every request on a warm executable; the
  ensemble traversal is the `predict_kernel` dial (tensorized | walk,
  ops/predict.py).
- `batcher`  — MicroBatcher: continuous batching — admits concurrent
  requests into the forming batch up to `max_batch_rows` or a
  `flush_deadline_ms` deadline (monotonic clock), one flusher per
  replica, optional admission control (`max_pending_rows` → 503).
- `registry` — ModelRegistry: versioned atomic hot-swap (mtime poll or
  SIGHUP) with pre-swap warmup of every traffic bucket for BOTH output
  kinds, rollback on a bad model, and optional shadow-canary staging
  (`serve_shadow_fraction`: double-score a weighted fraction of live
  traffic on a staged candidate, log divergence, adopt or reject).
- `catalog`  — ModelCatalog: N keyed tenants (model id → registry +
  batcher) on one fleet — per-model routing/SLO accounting/admission
  budgets, LRU compiled-executable eviction under
  `serve_cache_budget_mb`, cross-tenant fault isolation.
- `superstack` — GroupRuntime: cross-model batched serving — tenants
  sharing (num_class, kernel variant, leaf tier) co-stack onto ONE
  padded super-stack scored by ONE compiled executable per (bucket,
  kind); mixed batches demux bitwise-identically to per-tenant
  dispatch (`serve_costack`, docs/serving.md "Cross-model batching").
- `server`   — PredictionServer: stdlib JSON-lines HTTP endpoint
  (/predict with `model` routing, /healthz, /stats, /metrics), the
  `task=serve` CLI entry.
"""
from .runtime import (OUTPUT_KINDS, PredictorRuntime,
                      resolve_serve_replicas, row_bucket)
from .batcher import MicroBatcher, ServerOverloadedError
from .registry import ModelRegistry
from .catalog import DEFAULT_MODEL_ID, ModelCatalog, UnknownModelError
from .superstack import GroupRuntime, costack_key
from .server import PredictionServer, serve_from_config, server_from_config

__all__ = [
    "OUTPUT_KINDS", "PredictorRuntime", "resolve_serve_replicas",
    "row_bucket", "MicroBatcher", "ServerOverloadedError", "ModelRegistry",
    "DEFAULT_MODEL_ID", "ModelCatalog", "UnknownModelError",
    "GroupRuntime", "costack_key",
    "PredictionServer", "serve_from_config", "server_from_config",
]
