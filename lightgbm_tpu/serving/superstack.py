"""Cross-model co-stacked serving: N tenants on ONE compiled executable.

The multi-tenant catalog (catalog.py) pays for tenant isolation with one
compiled executable and one traversal launch PER TENANT — a fleet of
hundreds of small CTR models burns the XLA compile cache and serializes
hundreds of tiny kernel launches.  The tensorized `EnsembleStack` walk
already proved the batched-traversal trick (ops/predict.py, the Booster
accelerator shape, arXiv:2011.02022): traversal cost is dominated by
launch/gather overhead, not node math, so packing MORE trees into the
one padded ``[T, nodes]`` launch is nearly free.  This module packs
trees across MODELS:

- `GroupRuntime` concatenates compatible tenants' ensembles into one
  SUPER-STACK (`ops.predict.stack_ensemble_group`) and scores a mixed
  batch in ONE launch.  The traversal is the ``costack_kernel`` dial
  (config.COSTACK_KERNELS): ``stacked`` walks every row through every
  stacked tree (free where launch overhead dominates), ``segment``
  gathers only the row's own tenant's tree segment per depth level
  (`predict_ensemble_grouped_segment*` — node math back to ~1x a solo
  tenant's on compute-bound tiers), and ``auto`` resolves per backend
  (`ops.predict.resolve_costack_kernel`).  Either way per-tenant
  reductions recover exactly the sums each tenant's solo stack would
  produce (bitwise-identical by construction), and a per-row
  tenant-id gather demuxes the answers.
- The tenant id rides as ONE extra trailing buffer column (exact in
  f32 below 2^24; fits the uint8/uint16 binned buffer for up to
  ``MAX_GROUP_TENANTS`` members), so the entire PredictorRuntime
  machinery — power-of-two row bucketing, padding, replica fleet,
  circuit breakers, AOT executable cache, warmup — is inherited
  untouched: pad rows carry tenant 0 and are sliced off like any
  other pad row.
- Grouping policy: tenants co-stack when they share
  ``(num_class, serve_quantize variant, leaf-budget tier)``
  (`costack_key`).  The leaf tier — next power of two of the widest
  tree — bounds padding waste: node records pad to the group's widest
  tree, so grouping a 4096-leaf model with 15-leaf models would pay a
  ~256x record-footprint tax on every small tenant's rows.  Tenants
  with ``costack=off`` or no same-key peer serve solo exactly as
  before; a group's replica fleet sizes to the MAX of its members'
  per-tenant ``replicas`` overrides (catalog._group_replicas).
- A member hot swap RESTACKS its group (catalog._restack): a new
  GroupRuntime is built from the members' current runtimes, and when
  the program signature is unchanged (same stack shapes/dtypes, same
  segments, same transforms — the common refit republish) the old
  group's compiled executables are transplanted onto the new stacks
  with ZERO recompiles; otherwise only THIS group warms anew.  Other
  groups' and solo tenants' executables are never touched.

Output-kind semantics match solo serving per member: members whose
objective has a fused device transform get it applied in-program behind
a per-row tenant mask; members without one get raw rows and the host
``convert_output`` after demux — the same split `PredictorRuntime`
makes globally.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import profiling, telemetry
from ..log import LightGBMError
from .runtime import OUTPUT_KINDS, PredictorRuntime, _Replica

# the tenant-id column must fit the narrowest binned buffer dtype
# (uint8): ids 0..255.  Groups larger than this split into chunks.
MAX_GROUP_TENANTS = 256


def leaf_tier(runtime: PredictorRuntime) -> int:
    """Next power of two >= the widest tree's leaf capacity — the
    padding-waste bound of the grouping policy."""
    widest = 2
    for trees in runtime._trees_by_class:
        for t in trees:
            widest = max(widest, int(t.max_leaves))
    tier = 2
    while tier < widest:
        tier <<= 1
    return tier


def costack_key(runtime: PredictorRuntime) -> Tuple[int, str, int]:
    """The compatibility key of the grouping policy: tenants co-stack
    iff they agree on (num_class, kernel variant, leaf tier)."""
    return (runtime.K, runtime.variant, leaf_tier(runtime))


def group_id_for(key: Tuple[int, str, int], chunk: int = 0) -> str:
    """Stable display id for a group — used as the ``group`` label of
    the ``lgbt_serve_group_*`` series and in /stats.  Starts with
    ``~`` (outside MODEL_ID_RE's charset) so it can never collide with
    a tenant id."""
    k, variant, tier = key
    base = f"~g.k{k}.{variant}.l{tier}"
    return base if chunk == 0 else f"{base}.{chunk}"


def _quantizer_signature(q) -> Optional[tuple]:
    """Content identity of a member's frozen ingress mapper set
    (quantize.FeatureQuantizer): two binned members share ingress
    quantization iff their signatures match — the same-refbin
    condition of the shared ingress quantizer.  Hashes the mapper
    TABLES, not the sidecar path, so two publishes of one refbin (or
    byte-identical copies) still dedup."""
    if q is None:
        return None
    import hashlib
    h = hashlib.sha1()
    h.update(np.asarray(q.used_features, np.int64).tobytes())
    for isnum, tbl in zip(q._numeric, q._tables):
        if isnum:
            h.update(b"n")
            h.update(np.ascontiguousarray(tbl).tobytes())
        else:
            cats, bins = tbl
            h.update(b"c")
            h.update(np.ascontiguousarray(cats).tobytes())
            h.update(np.ascontiguousarray(bins).tobytes())
    return (q.num_total_features, q.num_columns, str(np.dtype(q.dtype)),
            int(q.missing_bin), h.hexdigest())


def _value_signature(runtime: PredictorRuntime):
    """Hashable identity of a member's fused device transform — part of
    the group program signature (transplanting executables across a
    transform change would serve wrong values)."""
    if runtime._device_value is None:
        return None
    obj = runtime.objective
    return (getattr(obj, "name", ""),
            float(getattr(obj, "sigmoid", 0.0) or 0.0))


class GroupRuntime(PredictorRuntime):
    """One compiled executable serving N co-stacked tenants.

    Built FROM the members' solo runtimes (the catalog keeps those for
    shadow scoring and fallback; under co-stacking they are built
    unwarmed, so they hold stacks but no executables).  Inherits the
    whole replica/breaker/cache/warmup machinery from PredictorRuntime
    and overrides only the program body and the prediction entry point
    (`predict_mixed` — `predict` refuses, a group has no single-tenant
    interpretation).
    """

    def __init__(self, member_ids: Sequence[str],
                 runtimes: Sequence[PredictorRuntime], *,
                 group_id: str, generation: int = 1, replicas: int = 0,
                 failure_threshold: int = 3,
                 probe_after: Optional[int] = None,
                 costack_kernel: str = "auto",
                 costack_segment_trees: int = 0):
        from ..ops.predict import (resolve_costack_kernel,
                                   stack_ensemble_group)
        if len(member_ids) != len(runtimes) or not runtimes:
            raise LightGBMError("GroupRuntime needs aligned, non-empty "
                                "member ids and runtimes")
        if len(runtimes) > MAX_GROUP_TENANTS:
            raise LightGBMError(
                f"co-stack group exceeds {MAX_GROUP_TENANTS} tenants "
                "(the tenant-id buffer column is uint8-representable)")
        base = runtimes[0]
        for rt in runtimes[1:]:
            if rt.K != base.K:
                raise LightGBMError("co-stacked tenants must share "
                                    f"num_class ({rt.K} != {base.K})")
            if rt.variant != base.variant:
                raise LightGBMError("co-stacked tenants must share the "
                                    "serve_quantize variant "
                                    f"({rt.variant!r} != {base.variant!r})")
        self.member_ids: List[str] = list(member_ids)
        self.member_index: Dict[str, int] = {
            mid: g for g, mid in enumerate(self.member_ids)}
        self.members: List[PredictorRuntime] = list(runtimes)
        self.model_id = group_id
        self.generation = generation
        self.K = base.K
        self.variant = base.variant
        self.max_batch_rows = base.max_batch_rows
        self.min_bucket_rows = base.min_bucket_rows
        self.predict_kernel = "tensorized"
        # per-member output handling: the group program has no single
        # objective; members convert on the host after demux when their
        # solo runtime would (predict_mixed)
        self.objective = None
        self._quantizer = None          # per-member quantizers instead
        binned = self.variant == "binned"
        stack, gmeta = stack_ensemble_group(
            [rt._trees_by_class for rt in runtimes], binned=binned)
        self._gmeta = gmeta
        self._meta = None
        # grouped-traversal strategy, resolved ONCE per group build (the
        # dial is fleet-wide; the resolved value is part of the program
        # signature so a transplant can never cross segment<->stacked)
        self.costack_kernel = resolve_costack_kernel(
            costack_kernel, total_trees=int(gmeta.segments[-1][1]),
            segment_trees=int(costack_segment_trees))
        # the shared request buffer: every member's data columns padded
        # to the group-wide max, plus ONE trailing tenant-id column.  A
        # member's trees never gather beyond its own columns, and
        # wrong-tenant trees' gathers are discarded by the segment
        # demux, so zero-padding is routing-neutral.
        if binned:
            self._data_cols = max(rt._buf_cols for rt in runtimes)
            self._buf_dtype = (np.uint16 if any(
                np.dtype(rt._buf_dtype) == np.uint16 for rt in runtimes)
                else np.uint8)
        else:
            self._data_cols = max(rt.num_features for rt in runtimes)
            self._buf_dtype = np.float32
        self._buf_cols = self._data_cols + 1
        self.num_features = self._data_cols
        self._member_values = [rt._device_value for rt in runtimes]
        # non-None iff ANY member fuses a device transform — drives the
        # inherited _run_kind: with none, "value" shares the raw program
        # and every member converts on the host, exactly like solo
        self._device_value = next(
            (v for v in self._member_values if v is not None), None)
        # hashable program identity for executable transplants across
        # restacks (adopt_cache_from)
        # shared ingress quantizer (ROADMAP 2d): when every binned
        # member froze the SAME mapper set (same-refbin publish) with
        # the same feature-count contract, a mixed batch quantizes ONCE
        # against it instead of once per member job
        # (serve/group_quantize_shared counts the deduped rows)
        self._shared_quantizer = None
        if binned and len({rt.num_features for rt in runtimes}) == 1:
            sigs = {_quantizer_signature(rt._quantizer)
                    for rt in runtimes}
            if len(sigs) == 1 and None not in sigs:
                self._shared_quantizer = runtimes[0]._quantizer
        self._signature = (
            self.variant, self.costack_kernel,
            str(np.dtype(self._buf_dtype)), self._buf_cols,
            self._gmeta, tuple(_value_signature(rt) for rt in runtimes),
            self.K, self.min_bucket_rows, self.max_batch_rows,
            tuple((tuple(a.shape), str(a.dtype)) for a in stack),
        )
        self._init_fleet(stack, replicas, failure_threshold, probe_after)

    # -- program ---------------------------------------------------------

    def _program(self, kind: str):
        import jax.numpy as jnp
        from ..ops.predict import (
            predict_ensemble_grouped, predict_ensemble_grouped_binned,
            predict_ensemble_grouped_segment,
            predict_ensemble_grouped_segment_binned)
        meta = self._gmeta
        binned = self.variant == "binned"
        if self.costack_kernel == "segment":
            kernel = (predict_ensemble_grouped_segment_binned if binned
                      else predict_ensemble_grouped_segment)
        else:
            kernel = (predict_ensemble_grouped_binned if binned
                      else predict_ensemble_grouped)
        transforms = ([(g, v) for g, v in enumerate(self._member_values)
                       if v is not None] if kind == "value" else [])

        def fn(stacks, Xt):
            X = Xt[:, :-1]
            tids = Xt[:, -1].astype(jnp.int32)
            raw = kernel(stacks, X, tids, meta=meta)
            if transforms:
                # per-member fused transforms behind a row mask: the
                # transform is elementwise, so the selected rows carry
                # exactly the values the member's solo program computes
                out = raw
                for g, tf in transforms:
                    out = jnp.where((tids == g)[None, :], tf(raw), out)
                return out
            return raw
        return fn

    def _build(self, replica: _Replica, bucket: int, kind: str):
        compiled = super()._build(replica, bucket, kind)
        profiling.count(profiling.SERVE_GROUP_COMPILES)
        profiling.count(profiling.labeled(profiling.SERVE_GROUP_COMPILES,
                                          group=self.model_id))
        return compiled

    # -- restack transplant ----------------------------------------------

    def program_signature(self):
        return self._signature

    def adopt_cache_from(self, old: "GroupRuntime") -> bool:
        """Transplant the outgoing group's compiled executables onto
        this runtime's (new) stacks.  Valid only when the program
        signature is unchanged — the executables are functions of the
        stack AVALS (shapes/dtypes) and the traced body, not the leaf
        values, so a same-shape restack (the common refit republish)
        recompiles NOTHING.  Returns False (caller warms instead) on
        any mismatch."""
        if not isinstance(old, GroupRuntime):
            return False
        if old.program_signature() != self.program_signature():
            return False
        if len(old.replicas) != len(self.replicas):
            return False
        if any(m.device != o.device
               for m, o in zip(self.replicas, old.replicas)):
            return False
        with old._lock:
            snap = [(dict(r.compiled), dict(r.exe_bytes))
                    for r in old.replicas]
        with self._lock:
            for mine, (compiled, exe_bytes) in zip(self.replicas, snap):
                mine.compiled = compiled
                mine.exe_bytes = exe_bytes
        return True

    # -- prediction ------------------------------------------------------

    def predict(self, X, kind: str = "value"):
        raise LightGBMError(
            "GroupRuntime serves mixed batches via predict_mixed(jobs); "
            "single-tenant predict has no tenant id to route by")

    def _validate_member_rows(self, g: int, X: np.ndarray) -> np.ndarray:
        """One member's request rows validated against the MEMBER's
        width contract (solo semantics: wider trims, narrower errors)
        — float64, 2-D, contiguous; quantization not yet applied."""
        rt = self.members[g]
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.shape[1] > rt.num_features:
            X = np.ascontiguousarray(X[:, :rt.num_features])
        elif X.shape[1] < rt.num_features:
            raise LightGBMError(
                f"request has {X.shape[1]} features, model "
                f"{self.member_ids[g]!r} expects {rt.num_features}")
        return X

    def _prep_member_rows(self, g: int, X: np.ndarray) -> np.ndarray:
        """One member's request rows → group-buffer rows: validate the
        width, quantize with the member's OWN quantizer under the
        binned variant (the mixed-mapper path — same-refbin groups
        quantize once in `_mux_jobs` instead), zero-pad to the group
        data columns, stamp the tenant id into the trailing column."""
        rt = self.members[g]
        X = self._validate_member_rows(g, X)
        if rt._quantizer is not None:
            X = rt._quantizer.quantize(X)
            profiling.count(profiling.SERVE_QUANTIZE_BYTES_IN, X.nbytes)
        buf = np.zeros((X.shape[0], self._buf_cols), self._buf_dtype)
        buf[:, :X.shape[1]] = X
        buf[:, -1] = g
        return buf

    def _mux_jobs(self, jobs: Sequence[Tuple[int, np.ndarray]]
                  ) -> Tuple[Optional[np.ndarray], List[int]]:
        """Mixed jobs → (the [total, buf_cols] group buffer, per-job row
        counts); the buffer is None on an all-empty batch.  With a
        shared ingress quantizer (same-refbin binned group) the WHOLE
        mixed batch quantizes in ONE pass against the common mapper set
        instead of once per member job — pure host-CPU dedup, the bin
        ids are identical by construction (one quantizer, same rows)."""
        if self._shared_quantizer is None:
            bufs = [self._prep_member_rows(g, X) for g, X in jobs]
            counts = [b.shape[0] for b in bufs]
            if sum(counts) == 0:
                return None, counts
            return (bufs[0] if len(bufs) == 1
                    else np.concatenate(bufs, axis=0)), counts
        raws = [self._validate_member_rows(g, X) for g, X in jobs]
        counts = [r.shape[0] for r in raws]
        total = int(sum(counts))
        if total == 0:
            return None, counts
        Xcat = raws[0] if len(raws) == 1 else np.concatenate(raws, axis=0)
        q = self._shared_quantizer.quantize(Xcat)
        profiling.count(profiling.SERVE_QUANTIZE_BYTES_IN, q.nbytes)
        profiling.count(profiling.SERVE_GROUP_QUANTIZE_SHARED, total)
        profiling.count(profiling.labeled(
            profiling.SERVE_GROUP_QUANTIZE_SHARED,
            group=self.model_id), total)
        Xt = np.zeros((total, self._buf_cols), self._buf_dtype)
        Xt[:, :q.shape[1]] = q
        off = 0
        for (g, _X), n in zip(jobs, counts):
            Xt[off:off + n, -1] = g
            off += n
        return Xt, counts

    def predict_mixed(self, jobs: Sequence[Tuple[int, np.ndarray]],
                      kind: str = "value") -> List[np.ndarray]:
        """Score a mixed batch — ``jobs`` is ``[(member index, X)]``,
        one entry per request — in as few launches as the row count
        needs (one, below ``max_batch_rows``).  Returns one array per
        job in Booster.predict shapes, bitwise-identical to routing
        each job through its tenant's solo runtime."""
        if kind not in OUTPUT_KINDS:
            raise ValueError(
                f"unknown output kind {kind!r}; use one of {OUTPUT_KINDS}")
        Xt, counts = self._mux_jobs(jobs)
        total = int(sum(counts))
        if Xt is None:
            empty = np.zeros(0) if self.K == 1 else np.zeros((0, self.K))
            return [empty.copy() for _ in jobs]
        if self.variant == "binned":
            profiling.count(profiling.SERVE_BINNED_REQUESTS)
        run_kind = self._run_kind(kind)
        starts = range(0, total, self.max_batch_rows)
        with profiling.phase("serve/execute", force=True):
            if len(starts) == 1 or self._fanout is None:
                parts = [self._predict_chunk(Xt[a:a + self.max_batch_rows],
                                             run_kind)
                         for a in starts]
            else:
                ctx = telemetry.current()
                parts = list(self._fanout.map(
                    lambda a: telemetry.call_in_context(
                        ctx, self._predict_chunk,
                        Xt[a:a + self.max_batch_rows], run_kind),
                    starts))
        raw = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)
        outs: List[np.ndarray] = []
        off = 0
        for (g, _X), n in zip(jobs, counts):
            seg = raw[:, off:off + n]
            off += n
            out = seg[0] if self.K == 1 else seg.T
            rt = self.members[g]
            if (kind == "value" and self._member_values[g] is None
                    and rt.objective is not None):
                # this member's rows came out of the program raw (no
                # fused transform) — the solo host-side conversion
                out = rt.objective.convert_output(out)
            outs.append(out)
        profiling.count("serve.rows", total)
        # per-group demux row accounting by RESOLVED traversal kernel
        # (/stats groups block, /metrics, bench_serve_mt's A/B proof)
        rows_name = (profiling.SERVE_GROUP_SEGMENT_ROWS
                     if self.costack_kernel == "segment"
                     else profiling.SERVE_GROUP_STACKED_ROWS)
        profiling.count(rows_name, total)
        profiling.count(profiling.labeled(rows_name, group=self.model_id),
                        total)
        return outs
