"""Compiled-predictor runtime for online scoring.

The offline path (`application.Predictor` → `Booster.predict`) re-traces
the XLA walker for every new batch shape and rebuilds the TreeStack per
call.  Online traffic is the opposite workload: millions of small,
odd-shaped requests against one slowly-changing model.  This runtime
keeps the accelerator executable warm the way the GPU boosting serving
literature prescribes (arXiv:1806.11248 §5, arXiv:2011.02022):

- executables are AOT-compiled once per (model generation, row bucket,
  output kind) via ``jax.jit(...).lower(...).compile()`` and cached —
  a cache hit does zero tracing and zero compilation;
- request rows are bucketed to powers of two between
  ``min_bucket_rows`` and ``max_batch_rows`` and padded up, so every
  shape in the wild lands on one of O(log) warm executables;
- the per-request feature buffer is donated on accelerator backends, so
  XLA may reuse it for the output and skip one HBM round trip;
- the sigmoid/softmax output transform runs inside the compiled program
  ("value" kind) — the host only sees finished predictions.

Cache hits/misses, compile seconds, and executed rows are recorded
through the always-on `profiling` counters (exposed at the server's
/stats endpoint).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import profiling
from ..log import LightGBMError

OUTPUT_KINDS = ("value", "raw")


def row_bucket(n: int, min_bucket: int, max_bucket: int) -> int:
    """Smallest power-of-two bucket >= n within [min_bucket, max_bucket]."""
    b = max(1, min_bucket)
    while b < n and b < max_bucket:
        b <<= 1
    return min(b, max_bucket)


class PredictorRuntime:
    """Warm-executable predictor for one model generation.

    Immutable once built: hot swap creates a fresh runtime for the next
    generation and atomically replaces the reference (registry.py), so
    in-flight requests keep scoring against a consistent model.
    """

    def __init__(self, booster, *, num_iteration: int = -1,
                 max_batch_rows: int = 4096, min_bucket_rows: int = 16,
                 generation: int = 0):
        import jax
        import jax.numpy as jnp
        from ..ops.predict import stack_trees

        gbdt = booster._gbdt if hasattr(booster, "_gbdt") else booster
        gbdt._flush_pending()
        if not gbdt.models:
            raise LightGBMError("cannot build a PredictorRuntime from a "
                                "model with no trees")
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        min_bucket_rows = max(1, min(min_bucket_rows, max_batch_rows))
        self.generation = generation
        self.max_batch_rows = int(max_batch_rows)
        self.min_bucket_rows = int(min_bucket_rows)
        self.objective = gbdt.objective
        self.K = max(1, gbdt.K)
        self.num_features = gbdt.max_feature_idx + 1
        used = gbdt._num_used_models(num_iteration)
        # one stacked-tree pytree per class; None for a class that never
        # trained (its raw score stays 0, like GBDT._predict_raw_device)
        self._stacks: List = []
        self._depths: List[int] = []
        for k in range(self.K):
            trees = [gbdt.models[i] for i in range(used) if i % self.K == k]
            if not trees:
                self._stacks.append(None)
                self._depths.append(1)
                continue
            stack = stack_trees(trees, binned=False)
            self._stacks.append(jax.tree_util.tree_map(jax.device_put, stack))
            self._depths.append(
                max(max((t.max_depth_grown for t in trees), default=1), 1))
        self._device_value = self._device_value_fn()
        # X is donated only where donation is real; on CPU it would just
        # print an "unusable donated buffer" warning per call
        self._donate = jax.default_backend() in ("tpu", "gpu")
        self._compiled: Dict[Tuple[int, str], object] = {}
        self._lock = threading.Lock()
        self.cache_hits = 0
        self.cache_misses = 0

    # -- compiled-program construction ---------------------------------

    def _device_value_fn(self):
        """Device-side raw→prediction transform for the "value" output
        kind, or None when there is nothing to fuse: identity transforms
        share the raw program (compiling a byte-identical twin per
        bucket would double the cache for nothing), and objectives with
        no known device form fall back to the host transform on the raw
        program's result."""
        import jax
        from ..objectives import Objective

        obj = self.objective
        if obj is None or type(obj).convert_output is Objective.convert_output:
            return None                                  # identity: use raw
        name = getattr(obj, "name", "")
        if name in ("binary", "multiclassova"):
            sig = float(obj.sigmoid)
            return lambda raw: jax.nn.sigmoid(sig * raw)
        if name == "multiclass":
            return lambda raw: jax.nn.softmax(raw, axis=0)
        return None                                      # host fallback

    def _run_kind(self, kind: str) -> str:
        """The executable kind a request actually runs: "value" maps to
        the raw program whenever no device transform is fused."""
        return kind if kind == "raw" or self._device_value is not None \
            else "raw"

    def _build(self, bucket: int, kind: str):
        """AOT-compile the walker for one (bucket, kind) — the only
        place an XLA compilation can happen after the runtime is built."""
        import jax
        import jax.numpy as jnp
        from ..ops.predict import ensemble_raw

        depths = tuple(self._depths)
        device_value = self._device_value if kind == "value" else None

        def fn(stacks, X):
            raw = ensemble_raw(stacks, X, depths=depths)   # [K, bucket]
            if device_value is not None:
                raw = device_value(raw)
            return raw

        donate = (1,) if self._donate else ()
        t0 = time.perf_counter()
        compiled = (jax.jit(fn, donate_argnums=donate)
                    .lower(self._stacks,
                           jax.ShapeDtypeStruct((bucket, self.num_features),
                                                jnp.float32))
                    .compile())
        dt = time.perf_counter() - t0
        profiling.add("serve/compile", dt, force=True)
        profiling.count("serve.compile_seconds", dt)
        return compiled

    def _get_executable(self, bucket: int, kind: str):
        key = (bucket, kind)
        with self._lock:
            exe = self._compiled.get(key)
            if exe is not None:
                self.cache_hits += 1
                profiling.count("serve.cache_hit")
                return exe
        # compile outside the lock (minutes-long on big models); the
        # double-build race just wastes one compile, never corrupts
        exe = self._build(bucket, kind)
        with self._lock:
            winner = self._compiled.setdefault(key, exe)
            self.cache_misses += 1
            profiling.count("serve.cache_miss")
        return winner

    # -- introspection --------------------------------------------------

    def buckets_compiled(self) -> List[Tuple[int, str]]:
        with self._lock:
            return sorted(self._compiled)

    def warmup(self, buckets: Sequence[int] = (),
               kinds: Sequence[str] = ("value",)) -> None:
        """Compile + execute the given row buckets so the first real
        request after a (re)load never pays compile latency.  Used by
        ModelRegistry before a hot swap goes live."""
        buckets = sorted({row_bucket(b, self.min_bucket_rows,
                                     self.max_batch_rows)
                          for b in (buckets or (1,))})
        for b in buckets:
            for kind in kinds:
                zeros = np.zeros((b, self.num_features), np.float32)
                self._run_compiled(b, self._run_kind(kind), zeros)

    # -- prediction -----------------------------------------------------

    def _run_compiled(self, bucket: int, kind: str, Xpad: np.ndarray):
        import jax
        exe = self._get_executable(bucket, kind)
        # explicit device_put/device_get keeps the serving loop clean
        # under the sanitizer's transfer guard (BENCH_SANITIZE in
        # scripts/bench_serve.py): implicit conversions here would be
        # one h2d + one d2h violation per request
        out = exe(self._stacks,
                  jax.device_put(Xpad.astype(np.float32, copy=False)))
        return jax.device_get(out).astype(np.float64)    # [K, bucket]

    def _predict_chunk(self, X: np.ndarray, kind: str) -> np.ndarray:
        n = X.shape[0]
        bucket = row_bucket(n, self.min_bucket_rows, self.max_batch_rows)
        if n < bucket:
            X = np.pad(X, ((0, bucket - n), (0, 0)))
        return self._run_compiled(bucket, kind, X)[:, :n]

    def predict(self, X: np.ndarray, kind: str = "value") -> np.ndarray:
        """Score [n, F] rows; returns the same shapes as Booster.predict
        ([n] for K==1, [n, K] otherwise).

        Arbitrary n: full ``max_batch_rows`` slabs plus one bucketed
        remainder, so every executed shape hits the warm cache — the
        final partial chunk pads up instead of retracing.
        """
        if kind not in OUTPUT_KINDS:
            raise ValueError(
                f"unknown output kind {kind!r}; use one of {OUTPUT_KINDS} "
                "(leaf indices go through Booster.predict(pred_leaf=True))")
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.shape[1] > self.num_features:
            # wider input is legal (reference predictor semantics: extra
            # trailing columns are ignored; the walk only gathers
            # feature indices the model knows)
            X = np.ascontiguousarray(X[:, :self.num_features])
        elif X.shape[1] < self.num_features:
            raise LightGBMError(
                f"request has {X.shape[1]} features, model expects "
                f"{self.num_features}")
        n = X.shape[0]
        if n == 0:
            return (np.zeros(0) if self.K == 1
                    else np.zeros((0, self.K)))
        run_kind = self._run_kind(kind)
        with profiling.phase("serve/execute", force=True):
            parts = [self._predict_chunk(X[a:a + self.max_batch_rows],
                                         run_kind)
                     for a in range(0, n, self.max_batch_rows)]
        raw = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)
        out = raw[0] if self.K == 1 else raw.T
        if kind == "value" and run_kind == "raw" and self.objective is not None:
            out = self.objective.convert_output(out)
        profiling.count("serve.rows", n)
        return out
