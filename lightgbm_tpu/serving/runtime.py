"""Compiled-predictor runtime for online scoring.

The offline path (`application.Predictor` → `Booster.predict`) re-traces
the XLA walker for every new batch shape and rebuilds the TreeStack per
call.  Online traffic is the opposite workload: millions of small,
odd-shaped requests against one slowly-changing model.  This runtime
keeps the accelerator executable warm the way the GPU boosting serving
literature prescribes (arXiv:1806.11248 §5, arXiv:2011.02022):

- executables are AOT-compiled once per (replica, model generation, row
  bucket, output kind) via ``jax.jit(...).lower(...).compile()`` and
  cached — a cache hit does zero tracing and zero compilation;
- request rows are bucketed to powers of two between
  ``min_bucket_rows`` and ``max_batch_rows`` and padded up, so every
  shape in the wild lands on one of O(log) warm executables;
- the ensemble traversal itself is the ``predict_kernel`` dial
  (ops/predict.py): ``tensorized`` (the `auto` resolution) walks every
  tree of every class in ONE fused gather/select program — `depth` loop
  steps for the whole ensemble; ``walk`` keeps the per-class vmapped
  walk as the A/B baseline;
- the model is REPLICATED across local devices (`replicas`): each
  replica owns a device-resident copy of the stacked ensemble and its
  own executable cache, and requests dispatch to the least-loaded
  replica — every local chip serves, which is the fleet story behind
  "heavy traffic from millions of users";
- the per-request feature buffer is donated on accelerator backends, so
  XLA may reuse it for the output and skip one HBM round trip;
- with ``serve_quantize=binned`` (quantize="binned" + a refbin mapper
  set here), every chunk quantizes to uint8 bin ids at ingress and the
  traversal compares integer bins end-to-end (ops/predict.py
  predict_ensemble_quantized): the request buffer ships 4x smaller and
  scores stay bit-identical to the raw kernel by construction
  (lightgbm_tpu/quantize.py);
- the sigmoid/softmax output transform runs inside the compiled program
  ("value" kind) — the host only sees finished predictions.

Cache hits/misses, compile seconds, executed rows, and per-replica
dispatch counts are recorded through the always-on `profiling` counters
(exposed at the server's /stats endpoint).

Replica health (docs/Robustness.md): every dispatch failure counts
against its replica; after ``failure_threshold`` CONSECUTIVE failures
the replica's circuit breaker opens and it stops receiving traffic.  A
failed chunk is retried ONCE on the least-loaded healthy replica, so
one bad chip degrades capacity, not availability.  Broken replicas
readmit through a half-open probe: after ``probe_after`` dispatches
were routed around a broken replica, one live request probes it — a
success closes the breaker, a failure re-opens it for another
``probe_after`` window (deterministic, count-based — no wall clock).
With ZERO healthy replicas, dispatch raises `NoHealthyReplicaError`,
which the HTTP layer maps to 503 (retryable) instead of a raw 500.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from .. import log, profiling, telemetry
from ..diagnostics import faults, locksan
from ..log import LightGBMError

OUTPUT_KINDS = ("value", "raw")


class NoHealthyReplicaError(LightGBMError):
    """Every replica's circuit breaker is open — shed load (HTTP 503)."""


class _ReplicaFailure(Exception):
    """Internal: a dispatch failed on a specific replica (carries the
    replica index so the retry can exclude it)."""

    def __init__(self, replica_index: int, error: BaseException):
        super().__init__(f"replica {replica_index} failed: {error}")
        self.replica_index = replica_index
        self.error = error


def row_bucket(n: int, min_bucket: int, max_bucket: int) -> int:
    """Smallest power-of-two bucket >= n within [min_bucket, max_bucket]."""
    b = max(1, min_bucket)
    while b < n and b < max_bucket:
        b <<= 1
    return min(b, max_bucket)


def resolve_runtime(booster, *, serve_quantize: str = "auto",
                    refbin=None, **kw) -> "PredictorRuntime":
    """Build a PredictorRuntime honoring the ``serve_quantize`` dial —
    the ONE place the auto/binned/raw policy lives (ModelRegistry and
    the CLI batch Predictor both route through here).

    ``raw`` → raw-feature runtime.  ``binned`` → binned runtime; ANY
    failure (missing/invalid sidecar, unrepresentable thresholds,
    compile error) propagates.  ``auto`` → binned whenever the refbin
    source yields a valid mapper set, raw otherwise (one log line says
    why).  ``refbin`` may be a sidecar path, a Dataset, or a zero-arg
    callable returning either — the registry defers its sha-validated
    sidecar load into the try this way.
    """
    import os

    from ..config import SERVE_QUANTIZE_MODES
    if serve_quantize not in SERVE_QUANTIZE_MODES:
        raise ValueError(f"unknown serve_quantize: {serve_quantize!r}; "
                         f"use one of {SERVE_QUANTIZE_MODES}")
    if serve_quantize != "raw":
        try:
            rb = refbin() if callable(refbin) else refbin
            if rb is None:
                raise LightGBMError(
                    "no .refbin frozen-mapper sidecar (Dataset."
                    "save_refbin, or an online-published model)")
            if isinstance(rb, str) and not os.path.exists(rb):
                raise LightGBMError(f"no .refbin sidecar at {rb}")
            return PredictorRuntime(booster, quantize="binned",
                                    refbin=rb, **kw)
        except Exception as e:
            if serve_quantize == "binned":
                raise
            log.info("serve_quantize=auto: serving raw features "
                     f"({type(e).__name__}: {e})")
    return PredictorRuntime(booster, **kw)


def resolve_serve_replicas(replicas: int = 0) -> list:
    """The local devices a serving fleet replicates onto.

    ``0`` (auto) = every local device on accelerator backends, ONE on
    the CPU tier (the virtual host-platform devices jax carves out of
    one socket share the same cores — replicating executables there
    multiplies compile time, not throughput).  An explicit count is
    honored on any backend (tests and the CPU bench force it), capped
    at the local device count.
    """
    import jax
    devs = list(jax.local_devices())
    if replicas <= 0:
        return devs if jax.default_backend() in ("tpu", "gpu") else devs[:1]
    return devs[: min(replicas, len(devs))]


class _Replica:
    """One device's copy of the model: device-resident stacks plus its
    own executable cache and dispatch/health bookkeeping."""
    __slots__ = ("index", "device", "stacks", "compiled", "exe_bytes",
                 "inflight", "dispatches", "failures", "broken", "skips",
                 "probes")

    def __init__(self, index: int, device, stacks):
        self.index = index
        self.device = device
        self.stacks = stacks
        self.compiled: Dict[Tuple[int, str], object] = {}
        # estimated device bytes per compiled executable (same keys as
        # `compiled`) — what the catalog's serve_cache_budget_mb LRU
        # accounting sums
        self.exe_bytes: Dict[Tuple[int, str], int] = {}
        self.inflight = 0
        self.dispatches = 0
        self.failures = 0       # CONSECUTIVE dispatch failures
        self.broken = False     # circuit breaker open
        self.skips = 0          # dispatches routed around while broken
        self.probes = 0         # half-open probes attempted


class PredictorRuntime:
    """Warm-executable predictor for one model generation.

    Immutable once built: hot swap creates a fresh runtime for the next
    generation and atomically replaces the reference (registry.py), so
    in-flight requests keep scoring against a consistent model.
    """

    # dispatches routed AROUND a broken replica before one live request
    # probes it (half-open); count-based so chaos runs are deterministic
    PROBE_AFTER = 8

    def __init__(self, booster, *, num_iteration: int = -1,
                 max_batch_rows: int = 4096, min_bucket_rows: int = 16,
                 generation: int = 0, predict_kernel: Optional[str] = None,
                 replicas: int = 0, failure_threshold: int = 3,
                 probe_after: Optional[int] = None,
                 quantize: str = "raw", refbin=None,
                 model_id: Optional[str] = None):
        import jax
        from ..ops.predict import resolve_predict_kernel

        gbdt = booster._gbdt if hasattr(booster, "_gbdt") else booster
        gbdt._flush_pending()
        if not gbdt.models:
            raise LightGBMError("cannot build a PredictorRuntime from a "
                                "model with no trees")
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        min_bucket_rows = max(1, min(min_bucket_rows, max_batch_rows))
        self.generation = generation
        # catalog tenant id (None outside the multi-tenant catalog):
        # stamps replica spans and the per-model telemetry labels
        self.model_id = model_id
        self.max_batch_rows = int(max_batch_rows)
        self.min_bucket_rows = int(min_bucket_rows)
        self.objective = gbdt.objective
        self.K = max(1, gbdt.K)
        self.num_features = gbdt.max_feature_idx + 1
        if predict_kernel is None:
            # the model's own training config carries the dial when the
            # serving entry point does not pass one explicitly
            predict_kernel = getattr(getattr(gbdt, "config", None),
                                     "predict_kernel", "auto")
        self.predict_kernel = resolve_predict_kernel(predict_kernel)
        used = gbdt._num_used_models(num_iteration)
        # request-path quantization (docs/serving.md "Binned inference"):
        # "binned" rebins the model against the frozen refbin mapper set,
        # quantizes every chunk at ingress, and traverses integer bins
        # end-to-end — bit-identical scores, 4x smaller request buffer
        if quantize not in ("raw", "binned"):
            raise ValueError("PredictorRuntime quantize must be 'raw' or "
                             f"'binned', got {quantize!r} (the auto "
                             "resolution happens in resolve_runtime)")
        self._quantizer = None
        self.variant = quantize
        if quantize == "binned":
            from ..quantize import (FeatureQuantizer, load_refbin,
                                    rebin_models_for_serving)
            if refbin is None:
                raise LightGBMError(
                    "binned serving needs a refbin mapper set (a .refbin "
                    "sidecar path or a Dataset)")
            if isinstance(refbin, str):
                refbin = load_refbin(refbin)
            if refbin.num_total_features != self.num_features:
                raise LightGBMError(
                    f"refbin mapper set covers "
                    f"{refbin.num_total_features} features, the model "
                    f"expects {self.num_features}")
            rebin_models_for_serving(gbdt.models[:used], refbin)
            self._quantizer = FeatureQuantizer(refbin.mappers,
                                               refbin.used_features)
            if self.predict_kernel != "tensorized":
                log.info("serve_quantize=binned traverses the tensorized "
                         "binned stack; the predict_kernel="
                         f"{self.predict_kernel} dial applies to raw "
                         "serving only")
        host_stacks = self._build_host_stacks(gbdt, used)
        # the per-chunk device buffer: quantized uint8/uint16 bins over
        # the used features, or the raw f32 feature matrix
        if self._quantizer is not None:
            self._buf_dtype = self._quantizer.dtype
            self._buf_cols = self._quantizer.num_columns
        else:
            self._buf_dtype = np.float32
            self._buf_cols = self.num_features
        self._device_value = self._device_value_fn()
        self._init_fleet(host_stacks, replicas, failure_threshold,
                         probe_after)

    def _init_fleet(self, host_stacks, replicas: int,
                    failure_threshold: int,
                    probe_after: Optional[int]) -> None:
        """Replica fleet + dispatch bookkeeping, shared verbatim by the
        cross-model GroupRuntime (serving/superstack.py) — breaker
        semantics and cache accounting must not fork per runtime
        flavor."""
        import jax

        # X is donated only where donation is real; on CPU it would just
        # print an "unusable donated buffer" warning per call
        self._donate = jax.default_backend() in ("tpu", "gpu")
        # the fleet: one model copy + executable cache per local device
        self.replicas: List[_Replica] = [
            _Replica(i, dev, jax.device_put(host_stacks, dev))
            for i, dev in enumerate(resolve_serve_replicas(replicas))]
        # persistent chunk fan-out pool (threads spawn on demand): a
        # per-request executor would pay thread spawn/teardown inside
        # the serving hot path on every multi-chunk request.  Replicas
        # are the parallel resource, so the pool is sized to the fleet
        # and shared across concurrent requests; workers exit when the
        # runtime is garbage-collected after a hot swap.
        self._fanout = (ThreadPoolExecutor(
            max_workers=len(self.replicas),
            thread_name_prefix="lgbt-serve-fanout")
            if len(self.replicas) > 1 else None)
        self._lock = locksan.lock("serve.runtime")
        self._rr = 0                  # round-robin tie-break cursor
        self.cache_hits = 0
        self.cache_misses = 0
        # replica circuit breaker (module docstring): consecutive
        # failures to open, routed-around dispatches to half-open probe
        self.failure_threshold = max(1, int(failure_threshold))
        self.probe_after = max(1, int(self.PROBE_AFTER if probe_after
                                      is None else probe_after))
        self.chunk_retries = 0

    @property
    def replica_count(self) -> int:
        return len(self.replicas)

    def replica_dispatches(self) -> List[int]:
        """Per-replica dispatch counts (the /stats fleet view)."""
        with self._lock:
            return [r.dispatches for r in self.replicas]

    def replica_health(self) -> List[dict]:
        """Per-replica breaker state (the /stats `replicas.health`
        view: which chips carry traffic, which are circuit-broken and
        how close their half-open probe is)."""
        with self._lock:
            return [{"index": r.index,
                     "state": "broken" if r.broken else "healthy",
                     "consecutive_failures": r.failures,
                     "dispatches": r.dispatches,
                     "skips_since_broken": r.skips,
                     "probes": r.probes}
                    for r in self.replicas]

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas if not r.broken)

    # -- model stacking -------------------------------------------------

    def _build_host_stacks(self, gbdt, used: int):
        """Host-numpy ensemble stacks — device_put once per replica.

        binned (serve_quantize=binned): ONE stack over every class with
        thresholds in bin space — the PERFECT layout for shallow
        numerical ensembles (bin ids exact in the f32 lanes), the
        integer-record SoA (int16 lanes on TPU) otherwise; the same
        layout-auto predicate as the raw path, so the two variants
        always make the same layout choice for a given model.
        tensorized: ONE stack over every class (`self._meta` static).
        walk: one TreeStack per class (None for a never-trained class,
        its raw row stays 0 like GBDT._predict_raw_device).
        """
        from ..ops.predict import build_ensemble, stack_trees
        trees_by_class = [
            [gbdt.models[i] for i in range(used) if i % self.K == k]
            for k in range(self.K)]
        # retained for the cross-model co-stacking overlay
        # (serving/superstack.py): a GroupRuntime concatenates its
        # members' trees into one super-stack, and must stack exactly
        # the tree set this runtime scores solo (binned variants have
        # already been rebinned in place above)
        self._trees_by_class = trees_by_class
        if self.variant == "binned":
            stack, meta = build_ensemble(trees_by_class, binned=True)
            self._meta = meta
            return stack
        if self.predict_kernel == "tensorized":
            stack, meta = build_ensemble(trees_by_class, binned=False)
            self._meta = meta
            return stack
        self._meta = None
        stacks: List = []
        self._depths: List[int] = []
        for trees in trees_by_class:
            if not trees:
                stacks.append(None)
                self._depths.append(1)
                continue
            # stack_trees returns device arrays on the default device;
            # numpy round-trip keeps replica placement explicit
            stack = stack_trees(trees, binned=False)
            stacks.append(type(stack)(*map(np.asarray, stack)))
            self._depths.append(
                max(max((t.max_depth_grown for t in trees), default=1), 1))
        return stacks

    # -- compiled-program construction ---------------------------------

    def _device_value_fn(self):
        """Device-side raw→prediction transform for the "value" output
        kind, or None when there is nothing to fuse: identity transforms
        share the raw program (compiling a byte-identical twin per
        bucket would double the cache for nothing), and objectives with
        no known device form fall back to the host transform on the raw
        program's result."""
        import jax
        from ..objectives import Objective

        obj = self.objective
        if obj is None or type(obj).convert_output is Objective.convert_output:
            return None                                  # identity: use raw
        name = getattr(obj, "name", "")
        if name in ("binary", "multiclassova"):
            sig = float(obj.sigmoid)
            return lambda raw: jax.nn.sigmoid(sig * raw)
        if name == "multiclass":
            return lambda raw: jax.nn.softmax(raw, axis=0)
        return None                                      # host fallback

    def _run_kind(self, kind: str) -> str:
        """The executable kind a request actually runs: "value" maps to
        the raw program whenever no device transform is fused."""
        return kind if kind == "raw" or self._device_value is not None \
            else "raw"

    def _raw_fn(self):
        """The traced ensemble-traversal body, (stacks, X) -> [K, rows]."""
        if self.variant == "binned":
            from ..ops.predict import predict_ensemble_quantized
            meta = self._meta

            def fn(stacks, Xb):
                return predict_ensemble_quantized(stacks, Xb, meta=meta)
            return fn
        if self.predict_kernel == "tensorized":
            from ..ops.predict import predict_ensemble_any
            meta = self._meta

            def fn(stacks, X):
                return predict_ensemble_any(stacks, X, meta=meta)
            return fn
        from ..ops.predict import ensemble_raw
        depths = tuple(self._depths)

        def fn(stacks, X):
            return ensemble_raw(stacks, X, depths=depths)
        return fn

    def _program(self, kind: str):
        """The traceable program body for one output kind — (stacks, X)
        -> [K, rows].  GroupRuntime overrides this with the grouped
        traversal; everything downstream (_build's AOT compile, the
        executable cache, warmup, dispatch) is shared."""
        raw_fn = self._raw_fn()
        device_value = self._device_value if kind == "value" else None

        def fn(stacks, X):
            raw = raw_fn(stacks, X)                        # [K, bucket]
            if device_value is not None:
                raw = device_value(raw)
            return raw
        return fn

    def _build(self, replica: _Replica, bucket: int, kind: str):
        """AOT-compile the traversal for one (replica, bucket, kind) —
        the only place an XLA compilation can happen after the runtime
        is built."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding

        fn = self._program(kind)
        donate = (1,) if self._donate else ()
        x_spec = jax.ShapeDtypeStruct(
            (bucket, self._buf_cols), jnp.dtype(self._buf_dtype),
            sharding=SingleDeviceSharding(replica.device))
        t0 = time.perf_counter()
        compiled = (jax.jit(fn, donate_argnums=donate)
                    .lower(replica.stacks, x_spec)
                    .compile())
        dt = time.perf_counter() - t0
        profiling.add("serve/compile", dt, force=True)
        profiling.count("serve.compile_seconds", dt)
        return compiled

    def _exe_bytes(self, compiled, bucket: int) -> int:
        """Estimated device bytes one compiled executable keeps live —
        what the catalog's serve_cache_budget_mb accounting charges.
        XLA's own memory analysis where the backend reports it; the
        analytic request-buffer + output + temp-free floor otherwise."""
        try:
            ma = compiled.memory_analysis()
            total = int((getattr(ma, "argument_size_in_bytes", 0) or 0)
                        + (getattr(ma, "output_size_in_bytes", 0) or 0)
                        + (getattr(ma, "temp_size_in_bytes", 0) or 0))
            if total > 0:
                return total
        except Exception:  # noqa: BLE001 — estimate, never a failure
            pass
        in_bytes = bucket * self._buf_cols * np.dtype(self._buf_dtype).itemsize
        return int(in_bytes + self.K * bucket * 4)

    def _get_executable(self, replica: _Replica, bucket: int, kind: str):
        # the kernel VARIANT is part of the key: a binned and a raw
        # executable at the same (bucket, kind) are different programs
        # over different buffer dtypes and must never collide
        key = (bucket, kind, self.variant)
        with self._lock:
            exe = replica.compiled.get(key)
            if exe is not None:
                self.cache_hits += 1
                profiling.count("serve.cache_hit")
                return exe
        # compile outside the lock (minutes-long on big models); the
        # double-build race just wastes one compile, never corrupts
        exe = self._build(replica, bucket, kind)
        with self._lock:
            winner = replica.compiled.setdefault(key, exe)
            replica.exe_bytes.setdefault(key, self._exe_bytes(exe, bucket))
            self.cache_misses += 1
            profiling.count("serve.cache_miss")
        return winner

    # -- introspection --------------------------------------------------

    def buckets_compiled(self) -> List[Tuple[int, str]]:
        """Distinct (bucket, kind) pairs compiled on ANY replica (the
        kernel variant is uniform per runtime and elided — swap warmup
        carries buckets across variants)."""
        with self._lock:
            keys = set()
            for r in self.replicas:
                keys.update((b, k) for b, k, _v in r.compiled)
            return sorted(keys)

    def cache_bytes(self) -> int:
        """Estimated device bytes held by this runtime's compiled
        executables across every replica — the quantity the catalog's
        `serve_cache_budget_mb` LRU accounting sums per tenant."""
        with self._lock:
            return sum(sum(r.exe_bytes.values()) for r in self.replicas)

    def evict_executables(self) -> int:
        """Drop every compiled executable (every replica) — the
        catalog's LRU budget enforcement.  The model stacks stay
        device-resident, so the tenant keeps serving; its next request
        simply recompiles its bucket (counted as churn through
        serve/cache_evictions and the ordinary cache-miss counters).
        In-flight dispatches keep their own executable references and
        finish untouched."""
        with self._lock:
            n = sum(len(r.compiled) for r in self.replicas)
            for r in self.replicas:
                r.compiled.clear()
                r.exe_bytes.clear()
        if n:
            profiling.count(profiling.SERVE_CACHE_EVICTIONS, n)
            if self.model_id is not None:
                profiling.count(profiling.labeled(
                    profiling.SERVE_CACHE_EVICTIONS,
                    model=self.model_id), n)
            log.info(f"serving cache evicted {n} compiled executables"
                     + (f" (model {self.model_id})" if self.model_id
                        else "")
                     + " to honor serve_cache_budget_mb")
        return n

    def warmup(self, buckets: Sequence[int] = (),
               kinds: Sequence[str] = OUTPUT_KINDS) -> None:
        """Compile + execute the given row buckets on EVERY replica so
        the first real request after a (re)load never pays compile
        latency.  Defaults to BOTH output kinds: a value-only warmup
        used to leave the first "raw" request compiling on the request
        path (identity objectives share one program, so warming both is
        free there).  Used by ModelRegistry before a hot swap goes
        live."""
        buckets = sorted({row_bucket(b, self.min_bucket_rows,
                                     self.max_batch_rows)
                          for b in (buckets or (1,))})
        run_kinds = sorted({self._run_kind(k) for k in kinds})
        for replica in self.replicas:
            for b in buckets:
                for kind in run_kinds:
                    # bin 0 is a valid bin everywhere, so the all-zeros
                    # buffer warms the binned variant too
                    zeros = np.zeros((b, self._buf_cols), self._buf_dtype)
                    self._run_compiled(b, kind, zeros, replica=replica)

    # -- prediction -----------------------------------------------------

    def _pick_replica(self, exclude: FrozenSet[int] = frozenset(),
                      allow_probe: bool = True) -> _Replica:
        """Least-loaded HEALTHY dispatch with a round-robin tie-break.

        Broken replicas are routed around; each route-around bumps
        their skip counter, and once it reaches ``probe_after`` the
        next request becomes that replica's half-open probe (a probe
        failure retries on a healthy replica like any other failure,
        so the probing client is still served).  Raises
        NoHealthyReplicaError when no replica is dispatchable.
        """
        with self._lock:
            n = len(self.replicas)
            best = probe = None
            for off in range(n):
                r = self.replicas[(self._rr + off) % n]
                if r.index in exclude:
                    continue
                if r.broken:
                    r.skips += 1
                    if (allow_probe and probe is None
                            and r.skips >= self.probe_after
                            and r.inflight == 0):    # single-flight probe
                        probe = r
                    continue
                if best is None or r.inflight < best.inflight:
                    best = r
            if probe is not None:
                probe.skips = 0
                probe.probes += 1
                profiling.count(profiling.SERVE_REPLICA_PROBES)
                best = probe
            if best is None:
                raise NoHealthyReplicaError(
                    f"no healthy predictor replica ({n} total, "
                    f"{len(exclude)} excluded); retry later")
            self._rr = (best.index + 1) % n
            best.inflight += 1
            best.dispatches += 1
            return best

    def _note_success(self, replica: _Replica) -> None:
        readmitted = False
        with self._lock:
            replica.failures = 0
            if replica.broken:
                replica.broken = False
                replica.skips = 0
                readmitted = True
                profiling.count(profiling.SERVE_REPLICA_READMITTED)
        if readmitted:
            log.info(f"serving replica {replica.index} readmitted "
                     "(half-open probe succeeded)")
            telemetry.event("serve.breaker", replica=replica.index,
                            state="closed",
                            generation=self.generation)

    def _note_failure(self, replica: _Replica, error: BaseException) -> None:
        with self._lock:
            replica.failures += 1
            profiling.count(profiling.SERVE_REPLICA_FAILURES)
            opened = (not replica.broken
                      and replica.failures >= self.failure_threshold)
            reopened = replica.broken
            if opened:
                replica.broken = True
                replica.skips = 0
                profiling.count(profiling.SERVE_REPLICA_BROKEN)
            if reopened:
                replica.skips = 0     # probe failed: wait another window
        if opened:
            log.warning(
                f"serving replica {replica.index} circuit-broken after "
                f"{replica.failures} consecutive failures "
                f"({type(error).__name__}: {error}); traffic fails over "
                "to the surviving replicas")
        if opened or reopened:
            telemetry.event("serve.breaker", replica=replica.index,
                            state="open" if opened else "probe_failed",
                            error=f"{type(error).__name__}: {error}",
                            generation=self.generation)

    def _run_compiled(self, bucket: int, kind: str, Xpad: np.ndarray,
                      replica: Optional[_Replica] = None,
                      exclude: FrozenSet[int] = frozenset()):
        import jax
        pinned = replica is not None
        if replica is None:
            # a retry (non-empty exclude) must land on a HEALTHY replica:
            # routing it to a broken one's half-open probe could fail the
            # request while healthy replicas sit idle
            replica = self._pick_replica(exclude,
                                         allow_probe=not exclude)
        else:                          # warmup pins the replica itself
            with self._lock:
                replica.inflight += 1
                replica.dispatches += 1
        try:
            # the replica-level hop of a request's trace: which chip ran
            # this chunk, at which bucket/kind, under which generation
            # (and, in the multi-tenant catalog, for which model id)
            with telemetry.span("serve.replica", replica=replica.index,
                                bucket=bucket, kind=kind,
                                variant=self.variant,
                                generation=self.generation,
                                **({"model": self.model_id}
                                   if self.model_id is not None else {})):
                # chaos seams: a dispatch raising (any replica / THIS
                # replica) is the circuit breaker's trigger condition
                faults.check("serve.dispatch")
                faults.check(f"serve.dispatch.r{replica.index}")
                exe = self._get_executable(replica, bucket, kind)
                # explicit device_put/device_get keeps the serving loop
                # clean under the sanitizer's transfer guard
                # (BENCH_SANITIZE in scripts/bench_serve.py): implicit
                # conversions here would be one h2d + one d2h violation
                # per request
                out = exe(replica.stacks,
                          jax.device_put(Xpad.astype(self._buf_dtype,
                                                     copy=False),
                                         replica.device))
                res = jax.device_get(out).astype(np.float64)  # [K, bucket]
        except Exception as e:
            self._note_failure(replica, e)
            if pinned:                 # warmup: surface the raw error
                raise
            raise _ReplicaFailure(replica.index, e) from e
        else:
            self._note_success(replica)
            return res
        finally:
            with self._lock:
                replica.inflight -= 1

    def _predict_chunk(self, X: np.ndarray, kind: str) -> np.ndarray:
        if self._quantizer is not None:
            # ingress quantization: raw f64 rows → uint8/uint16 original
            # per-feature bins, host-side (numpy — thread-safe under the
            # chunk fan-out pool).  The device buffer shrinks 4x vs f32,
            # which is the bytes/row the canonical counter tracks.
            X = self._quantizer.quantize(X)
            profiling.count(profiling.SERVE_QUANTIZE_BYTES_IN, X.nbytes)
        n = X.shape[0]
        bucket = row_bucket(n, self.min_bucket_rows, self.max_batch_rows)
        if n < bucket:
            # pad rows carry bin 0 / feature 0.0 — sliced off below
            X = np.pad(X, ((0, bucket - n), (0, 0)))
        try:
            out = self._run_compiled(bucket, kind, X)
        except _ReplicaFailure as f:
            # retry ONCE on a healthy replica other than the one that
            # failed; its executable cache is as warm as the failed
            # one's (warmup covers every replica), so the retry never
            # compiles on the request path
            with self._lock:
                # chunks retry concurrently on the fan-out pool; this
                # read-modify-write needs the runtime lock
                self.chunk_retries += 1
            profiling.count(profiling.SERVE_CHUNK_RETRIES)
            try:
                out = self._run_compiled(bucket, kind, X,
                                         exclude=frozenset(
                                             {f.replica_index}))
            except NoHealthyReplicaError:
                if self.healthy_count() == 0:
                    raise              # total outage: 503, retryable
                # only the exclusion emptied the pool (single-replica
                # fleet, breaker not yet open): surface the real error
                raise f.error from f
            except _ReplicaFailure as f2:
                raise f2.error from f2
        return out[:, :n]

    def predict(self, X: np.ndarray, kind: str = "value") -> np.ndarray:
        """Score [n, F] rows; returns the same shapes as Booster.predict
        ([n] for K==1, [n, K] otherwise).

        Arbitrary n: full ``max_batch_rows`` slabs plus one bucketed
        remainder, so every executed shape hits the warm cache — the
        final partial chunk pads up instead of retracing.  Each chunk
        dispatches to the least-loaded replica independently — and
        concurrently on a multi-replica fleet — so one large request
        fans out across the fleet.
        """
        if kind not in OUTPUT_KINDS:
            raise ValueError(
                f"unknown output kind {kind!r}; use one of {OUTPUT_KINDS} "
                "(leaf indices go through Booster.predict(pred_leaf=True))")
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.shape[1] > self.num_features:
            # wider input is legal (reference predictor semantics: extra
            # trailing columns are ignored; the walk only gathers
            # feature indices the model knows)
            X = np.ascontiguousarray(X[:, :self.num_features])
        elif X.shape[1] < self.num_features:
            raise LightGBMError(
                f"request has {X.shape[1]} features, model expects "
                f"{self.num_features}")
        n = X.shape[0]
        if n == 0:
            return (np.zeros(0) if self.K == 1
                    else np.zeros((0, self.K)))
        if self._quantizer is not None:
            profiling.count(profiling.SERVE_BINNED_REQUESTS)
        run_kind = self._run_kind(kind)
        starts = range(0, n, self.max_batch_rows)
        with profiling.phase("serve/execute", force=True):
            if len(starts) == 1 or self._fanout is None:
                parts = [self._predict_chunk(X[a:a + self.max_batch_rows],
                                             run_kind)
                         for a in starts]
            else:
                # a multi-chunk request on a multi-replica fleet really
                # does fan out: chunks dispatch CONCURRENTLY (each
                # dispatch picks the least-loaded replica), so
                # wall-clock is ~chunks/replicas slabs, not a
                # sequential scan that merely rotates replicas.  The
                # caller's span context rides into the pool threads
                # explicitly (thread locals do not follow map work).
                ctx = telemetry.current()
                parts = list(self._fanout.map(
                    lambda a: telemetry.call_in_context(
                        ctx, self._predict_chunk,
                        X[a:a + self.max_batch_rows], run_kind),
                    starts))
        raw = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)
        out = raw[0] if self.K == 1 else raw.T
        if kind == "value" and run_kind == "raw" and self.objective is not None:
            out = self.objective.convert_output(out)
        profiling.count("serve.rows", n)
        return out
