"""Versioned model registry with atomic hot-swap.

Production serving replaces models under load.  The registry owns the
active (generation, PredictorRuntime) pair and swaps it atomically:

- `maybe_reload()` polls the model file's (mtime_ns, size) signature —
  driven by the server's poll thread every `model_poll_seconds`, or
  forced immediately via SIGHUP (`install_sighup()`);
- an incoming model is fully loaded AND warmed (every row bucket the
  outgoing runtime had compiled is re-compiled and executed for the new
  generation) BEFORE the reference flips, so the first request after a
  swap is as warm as the last one before it;
- a model that fails to load or compile is rolled back: the old runtime
  keeps serving, the bad file signature is remembered so the poll loop
  does not retry-spin on it, and `registry/swap_failures` is counted
  (exception class + message logged and kept as `last_swap_error`);
- under ``serve_quantize=binned`` the model's ``.refbin`` frozen-mapper
  sidecar is part of the swap: missing, torn, or sha1-mismatched (vs
  the publish meta's ``refbin_sha1``) sidecars REFUSE the swap through
  the same rollback path — the old generation keeps serving and the
  failure is /stats-visible.  ``auto`` falls back to raw-feature
  serving instead of refusing.

Readers never lock: `current()` is one attribute read; in-flight batches
that pinned the previous runtime finish on it untouched.
"""
from __future__ import annotations

import json
import os
import signal
import threading
from typing import Optional, Sequence, Tuple

from .. import log, profiling, telemetry
from ..log import LightGBMError
from .runtime import OUTPUT_KINDS, PredictorRuntime


def _file_signature(path: str) -> Tuple[int, int]:
    st = os.stat(path)
    return (st.st_mtime_ns, st.st_size)


class ModelRegistry:
    def __init__(self, model_path: str, params: Optional[dict] = None, *,
                 num_iteration: int = -1, max_batch_rows: int = 4096,
                 min_bucket_rows: int = 16,
                 warmup_buckets: Sequence[int] = (1,),
                 warmup_kinds: Sequence[str] = OUTPUT_KINDS,
                 predict_kernel: Optional[str] = None, replicas: int = 0,
                 failure_threshold: int = 3,
                 serve_quantize: str = "auto"):
        from ..config import SERVE_QUANTIZE_MODES
        self.model_path = model_path
        self.params = dict(params or {})
        self.num_iteration = num_iteration
        self.max_batch_rows = max_batch_rows
        self.min_bucket_rows = min_bucket_rows
        # BOTH output kinds warm by default: a value-only warmup left
        # the first raw request compiling on the request path
        self.warmup_kinds = tuple(warmup_kinds)
        self.predict_kernel = predict_kernel
        self.replicas = replicas
        self.failure_threshold = failure_threshold
        if serve_quantize not in SERVE_QUANTIZE_MODES:
            raise ValueError(f"unknown serve_quantize: {serve_quantize!r};"
                             f" use one of {SERVE_QUANTIZE_MODES}")
        self.serve_quantize = serve_quantize
        self.last_swap_error: Optional[str] = None
        self._lock = threading.Lock()       # serializes WRITERS only
        self._failed_sig: Optional[Tuple[int, int]] = None
        self._hup_pending = False
        # stat BEFORE loading (like maybe_reload): a file replaced during
        # a minutes-long load/warmup must look changed on the next poll
        self._sig = _file_signature(model_path)
        runtime = self._load(generation=1)
        runtime.warmup(warmup_buckets, self.warmup_kinds)
        self._runtime = runtime
        self.swaps = 0
        self.swap_failures = 0

    # -- reader side ----------------------------------------------------

    def current(self) -> PredictorRuntime:
        """The active runtime — a single atomic reference read."""
        return self._runtime

    @property
    def generation(self) -> int:
        return self._runtime.generation

    # -- writer side ----------------------------------------------------

    def _load(self, generation: int) -> PredictorRuntime:
        from ..basic import Booster
        from .runtime import resolve_runtime
        booster = Booster(model_file=self.model_path, params=self.params)
        # binned serving: the model's .refbin frozen-mapper sidecar is
        # loaded fresh at every swap (it may be republished with the
        # model) and validated — sha1 against the publish meta inside
        # _load_refbin, feature coverage / threshold representability
        # inside the runtime build.  serve_quantize=binned makes ANY
        # failure refuse the swap (maybe_reload keeps the old
        # generation serving); =auto falls back to the raw kernel.
        return resolve_runtime(
            booster, serve_quantize=self.serve_quantize,
            refbin=self._load_refbin,
            num_iteration=self.num_iteration,
            max_batch_rows=self.max_batch_rows,
            min_bucket_rows=self.min_bucket_rows,
            generation=generation,
            predict_kernel=self.predict_kernel,
            replicas=self.replicas,
            failure_threshold=self.failure_threshold)

    def _load_refbin(self):
        """The model's ``.refbin`` sidecar, checked against the publish
        meta's ``refbin_sha1`` fingerprint when the model was published
        by the online trainer (offline models carry no meta — the
        sidecar is then trusted on its own format/consistency checks).
        NOTE: a swap refused over a torn sidecar is remembered by the
        MODEL file's signature; republishing only the sidecar needs a
        SIGHUP (or the next model publish) to retry."""
        from ..quantize import load_refbin
        expected = None
        try:
            with open(self.model_path + ".meta.json") as f:
                expected = json.load(f).get("refbin_sha1")
        except (OSError, ValueError):
            expected = None
        return load_refbin(self.model_path + ".refbin",
                           expected_sha1=expected)

    def _publish_trace_id(self) -> Optional[str]:
        """The publishing refresh's trace id from the online trainer's
        ``.meta.json`` sidecar (None for models published any other
        way, or with telemetry off).  The sidecar is renamed AFTER the
        model file; a poll landing inside that window (or after a crash
        between the renames) would read the PREVIOUS refresh's sidecar
        — attributing this swap to the wrong trace — so a sidecar older
        than the model is not adopted."""
        if not telemetry.enabled():
            return None
        meta_path = self.model_path + ".meta.json"
        try:
            if (os.stat(meta_path).st_mtime_ns
                    < os.stat(self.model_path).st_mtime_ns):
                return None
            with open(meta_path) as f:
                tid = json.load(f).get("trace_id")
            return str(tid) if tid else None
        except (OSError, ValueError):
            return None

    def maybe_reload(self, force: bool = False) -> bool:
        """Swap in the model file if it changed; True iff a swap landed.

        Failure of ANY stage (read, parse, compile, warmup) keeps the
        old generation serving.
        """
        with self._lock:
            if self._hup_pending:
                self._hup_pending = False
                force = True
            try:
                sig = _file_signature(self.model_path)
            except OSError:
                # mid-replace; don't lose a SIGHUP-forced reload — the
                # next poll tick must retry with force
                self._hup_pending = self._hup_pending or force
                return False
            if not force and (sig == self._sig or sig == self._failed_sig):
                return False
            old = self._runtime
            try:
                # the swap span ADOPTS the publishing refresh's trace id
                # (the online trainer stamps it into the .meta.json
                # sidecar), closing the serve→train→serve loop: one
                # grep for that id finds traffic → window → refit →
                # publish → this hot-swap
                with telemetry.span(
                        "serve.swap", trace_id=self._publish_trace_id(),
                        generation=old.generation + 1,
                        model_path=self.model_path), \
                        profiling.phase("serve/swap", force=True):
                    runtime = self._load(generation=old.generation + 1)
                    # warm every bucket the outgoing generation served,
                    # for BOTH this registry's warmup kinds and whatever
                    # kinds actually saw traffic (so no post-swap request
                    # of either output kind compiles on the request path)
                    buckets = {b for b, _k in old.buckets_compiled()} or {1}
                    kinds = ({k for _b, k in old.buckets_compiled()}
                             | set(self.warmup_kinds))
                    runtime.warmup(sorted(buckets), sorted(kinds))
            except Exception as e:
                # a corrupt/torn candidate model must be LOUD and
                # visible at /stats, not a silent skip: exception class
                # + message into the log, the canonical
                # registry/swap_failures counter, and last_swap_error
                # for the stats endpoint (docs/Robustness.md)
                self.swap_failures += 1
                self._failed_sig = sig
                self.last_swap_error = f"{type(e).__name__}: {e}"
                profiling.count(profiling.REGISTRY_SWAP_FAILURES)
                log.warning(f"model hot-swap failed, keeping generation "
                            f"{old.generation} "
                            f"({self.last_swap_error})")
                return False
            self._runtime = runtime          # the atomic swap
            self._sig = sig
            self._failed_sig = None
            self.last_swap_error = None
            self.swaps += 1
            profiling.count("serve.swap")
            log.info(f"hot-swapped model to generation "
                     f"{runtime.generation} ({self.model_path})")
            return True

    # -- triggers -------------------------------------------------------

    def install_sighup(self) -> bool:
        """SIGHUP → force reload on the next poll tick.  Only possible
        from the main thread; returns False (mtime polling still works)
        otherwise."""
        if threading.current_thread() is not threading.main_thread():
            return False

        def _on_hup(_signum, _frame):
            self._hup_pending = True
            # reload off-thread immediately: SIGHUP must work even when
            # mtime polling is disabled, and the handler itself must not
            # block the main thread on a minutes-long compile
            threading.Thread(target=self.poll_once, daemon=True,
                             name="lgbt-serve-hup").start()

        try:
            signal.signal(signal.SIGHUP, _on_hup)
        except (ValueError, OSError, AttributeError):
            return False
        return True

    def poll_once(self) -> bool:
        # maybe_reload consumes _hup_pending itself, under the lock
        return self.maybe_reload()
