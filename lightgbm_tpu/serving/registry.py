"""Versioned model registry with atomic hot-swap.

Production serving replaces models under load.  The registry owns the
active (generation, PredictorRuntime) pair and swaps it atomically:

- `maybe_reload()` polls the model file's (mtime_ns, size, meta sha1)
  signature — driven by the server's poll thread every
  `model_poll_seconds`, or forced immediately via SIGHUP
  (`install_sighup()`; a forced reload also bypasses any shadow
  canary and discards a pending candidate — the operator's escape
  hatch);
- an incoming model is fully loaded AND warmed (every row bucket the
  outgoing runtime had compiled is re-compiled and executed for the new
  generation) BEFORE the reference flips, so the first request after a
  swap is as warm as the last one before it;
- a model that fails to load or compile is rolled back: the old runtime
  keeps serving, the bad file signature is remembered so the poll loop
  does not retry-spin on it, and `registry/swap_failures` is counted
  (exception class + message logged and kept as `last_swap_error`);
- under ``serve_quantize=binned`` the model's ``.refbin`` frozen-mapper
  sidecar is part of the swap: missing, torn, or sha1-mismatched (vs
  the publish meta's ``refbin_sha1``) sidecars REFUSE the swap through
  the same rollback path — the old generation keeps serving and the
  failure is /stats-visible.  ``auto`` falls back to raw-feature
  serving instead of refusing.

Readers never lock: `current()` is one attribute read; in-flight batches
that pinned the previous runtime finish on it untouched.
"""
from __future__ import annotations

import hashlib
import json
import os
import signal
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from .. import log, profiling, telemetry
from ..diagnostics import locksan
from ..log import LightGBMError
from .runtime import OUTPUT_KINDS, PredictorRuntime


def _file_signature(path: str) -> Tuple[int, int, Optional[str]]:
    """(mtime_ns, size, meta sha1) — the change detector of the poll.

    mtime alone cannot tell two publishes landing within one mtime tick
    apart, and (mtime_ns, size) still cannot when the republished model
    happens to be byte-size-identical (a leaf refit frequently is).
    The online trainer rewrites ``<model>.meta.json`` on EVERY publish
    (generation, timestamps), so hashing that small sidecar closes the
    same-second window; models published without a meta sidecar keep
    the (mtime_ns, size) resolution, documented in docs/serving.md."""
    st = os.stat(path)
    meta_sha: Optional[str] = None
    try:
        with open(path + ".meta.json", "rb") as f:
            meta_sha = hashlib.sha1(f.read()).hexdigest()
    except OSError:
        pass
    return (st.st_mtime_ns, st.st_size, meta_sha)


class ModelRegistry:
    def __init__(self, model_path: str, params: Optional[dict] = None, *,
                 num_iteration: int = -1, max_batch_rows: int = 4096,
                 min_bucket_rows: int = 16,
                 warmup_buckets: Sequence[int] = (1,),
                 warmup_kinds: Sequence[str] = OUTPUT_KINDS,
                 predict_kernel: Optional[str] = None, replicas: int = 0,
                 failure_threshold: int = 3,
                 serve_quantize: str = "auto",
                 model_id: Optional[str] = None,
                 shadow_fraction: float = 0.0,
                 shadow_requests: int = 32,
                 shadow_max_divergence: float = -1.0,
                 warm_initial: bool = True):
        from ..config import SERVE_QUANTIZE_MODES
        self.model_path = model_path
        self.params = dict(params or {})
        self.num_iteration = num_iteration
        self.max_batch_rows = max_batch_rows
        self.min_bucket_rows = min_bucket_rows
        # BOTH output kinds warm by default: a value-only warmup left
        # the first raw request compiling on the request path
        self.warmup_kinds = tuple(warmup_kinds)
        self.predict_kernel = predict_kernel
        self.replicas = replicas
        self.failure_threshold = failure_threshold
        if serve_quantize not in SERVE_QUANTIZE_MODES:
            raise ValueError(f"unknown serve_quantize: {serve_quantize!r};"
                             f" use one of {SERVE_QUANTIZE_MODES}")
        self.serve_quantize = serve_quantize
        # catalog tenant id (None for plain single-model registries):
        # rides into the runtime's spans and the per-model counters
        self.model_id = model_id
        # co-stacked tenant (serving/superstack.py): this registry's
        # solo runtime holds stacks but serves no direct traffic — the
        # catalog's GroupRuntime does — so warming its executables on
        # swaps would pay one compile per tenant and defeat the whole
        # point of grouping.  The catalog flips this after grouping and
        # warms the GROUP instead (restack path); shadow candidates
        # compile lazily on their first off-request-path comparison.
        self.costacked = False
        self.warmup_buckets = tuple(warmup_buckets)
        # shadow canary (docs/serving.md "Multi-tenant catalog"): with
        # fraction > 0, a republished model is STAGED as a candidate
        # and double-scored on 1/fraction of requests before adoption;
        # 0 keeps the immediate hot-swap
        self.shadow_fraction = float(shadow_fraction)
        self.shadow_requests = max(1, int(shadow_requests))
        self.shadow_max_divergence = float(shadow_max_divergence)
        self._candidate: Optional[PredictorRuntime] = None
        self._candidate_sig: Optional[Tuple[int, int, Optional[str]]] = None
        self._candidate_trace: Optional[str] = None
        self._shadow_lock = locksan.lock("serve.registry.shadow")  # shadow counters +
        # candidate identity.  Lock ORDER: _lock → _shadow_lock (the
        # staging branch and the verdict both nest that way; nothing
        # acquires _lock while holding _shadow_lock).  The hot
        # per-batch shadow path takes _shadow_lock ALONE, and the
        # verdict's _lock acquire is non-blocking, so a minutes-long
        # candidate load can never stall a flusher thread
        self._shadow_tick = 0
        self._shadow_scored = 0
        self._shadow_max_div = 0.0
        self.last_swap_error: Optional[str] = None
        self._lock = locksan.lock("serve.registry")  # serializes WRITERS only
        self._failed_sig: Optional[Tuple[int, int, Optional[str]]] = None
        self._hup_pending = False
        # stat BEFORE loading (like maybe_reload): a file replaced during
        # a minutes-long load/warmup must look changed on the next poll
        self._sig = _file_signature(model_path)
        runtime = self._load(generation=1)
        if warm_initial:
            runtime.warmup(warmup_buckets, self.warmup_kinds)
        self._runtime = runtime
        self.swaps = 0
        self.swap_failures = 0

    # -- reader side ----------------------------------------------------

    def current(self) -> PredictorRuntime:
        """The active runtime — a single atomic reference read."""
        return self._runtime

    @property
    def generation(self) -> int:
        return self._runtime.generation

    # -- writer side ----------------------------------------------------

    def _load(self, generation: int) -> PredictorRuntime:
        from ..basic import Booster
        from .runtime import resolve_runtime
        booster = Booster(model_file=self.model_path, params=self.params)
        # binned serving: the model's .refbin frozen-mapper sidecar is
        # loaded fresh at every swap (it may be republished with the
        # model) and validated — sha1 against the publish meta inside
        # _load_refbin, feature coverage / threshold representability
        # inside the runtime build.  serve_quantize=binned makes ANY
        # failure refuse the swap (maybe_reload keeps the old
        # generation serving); =auto falls back to the raw kernel.
        return resolve_runtime(
            booster, serve_quantize=self.serve_quantize,
            refbin=self._load_refbin,
            num_iteration=self.num_iteration,
            max_batch_rows=self.max_batch_rows,
            min_bucket_rows=self.min_bucket_rows,
            generation=generation,
            predict_kernel=self.predict_kernel,
            replicas=self.replicas,
            failure_threshold=self.failure_threshold,
            model_id=self.model_id)

    def _load_refbin(self):
        """The model's ``.refbin`` sidecar, checked against the publish
        meta's ``refbin_sha1`` fingerprint when the model was published
        by the online trainer (offline models carry no meta — the
        sidecar is then trusted on its own format/consistency checks).
        NOTE: a swap refused over a torn sidecar is remembered by the
        MODEL file's signature; republishing only the sidecar needs a
        SIGHUP (or the next model publish) to retry."""
        from ..quantize import load_refbin
        expected = None
        try:
            with open(self.model_path + ".meta.json") as f:
                expected = json.load(f).get("refbin_sha1")
        except (OSError, ValueError):
            expected = None
        return load_refbin(self.model_path + ".refbin",
                           expected_sha1=expected)

    def pending_publish(self) -> bool:
        """True when the model file on disk no longer matches the
        signature the live generation loaded — a publish has landed
        that this process has not swapped in yet (poll window), or
        refused (swap failure).  /healthz reports tenants in this state
        as ``stale`` so the router tier's health probes can tell a
        partially-swapped backend from a live one (docs/Router.md)."""
        return _file_signature(self.model_path) != self._sig

    def _publish_trace_id(self) -> Optional[str]:
        """The publishing refresh's trace id from the online trainer's
        ``.meta.json`` sidecar (None for models published any other
        way, or with telemetry off).  The sidecar is renamed AFTER the
        model file; a poll landing inside that window (or after a crash
        between the renames) would read the PREVIOUS refresh's sidecar
        — attributing this swap to the wrong trace — so a sidecar older
        than the model is not adopted."""
        if not telemetry.enabled():
            return None
        meta_path = self.model_path + ".meta.json"
        try:
            if (os.stat(meta_path).st_mtime_ns
                    < os.stat(self.model_path).st_mtime_ns):
                return None
            with open(meta_path) as f:
                tid = json.load(f).get("trace_id")
            return str(tid) if tid else None
        except (OSError, ValueError):
            return None

    def maybe_reload(self, force: bool = False) -> bool:
        """Swap in the model file if it changed; True iff a swap landed.

        Failure of ANY stage (read, parse, compile, warmup) keeps the
        old generation serving.
        """
        with self._lock:
            if self._hup_pending:
                self._hup_pending = False
                force = True
            try:
                sig = _file_signature(self.model_path)
            except OSError:
                # mid-replace; don't lose a SIGHUP-forced reload — the
                # next poll tick must retry with force
                self._hup_pending = self._hup_pending or force
                return False
            if not force and (sig == self._sig or sig == self._failed_sig):
                return False
            old = self._runtime
            # a FORCED reload (SIGHUP / poll_once(force=True)) is the
            # operator's escape hatch and swaps immediately — without
            # it, a low-traffic tenant's canary could stay staged
            # indefinitely (the quorum needs live requests) with no
            # way to promote a publish short of a restart
            shadow = self.shadow_fraction > 0.0 and not force
            trace_id = self._publish_trace_id()
            attrs = ({"model": self.model_id}
                     if self.model_id is not None else {})
            try:
                # the swap span ADOPTS the publishing refresh's trace id
                # (the online trainer stamps it into the .meta.json
                # sidecar), closing the serve→train→serve loop: one
                # grep for that id finds traffic → window → refit →
                # publish → this hot-swap
                with telemetry.span(
                        "serve.swap", trace_id=trace_id,
                        generation=old.generation + 1,
                        model_path=self.model_path,
                        **(dict(attrs, staged=True) if shadow
                           else attrs)), \
                        profiling.phase("serve/swap", force=True):
                    runtime = self._load(generation=old.generation + 1)
                    # warm every bucket the outgoing generation served,
                    # for BOTH this registry's warmup kinds and whatever
                    # kinds actually saw traffic (so no post-swap request
                    # of either output kind compiles on the request path).
                    # Co-stacked tenants skip this: their traffic runs on
                    # the group's executable, which the catalog restack
                    # warms (or cache-transplants) after this swap lands.
                    if not self.costacked:
                        buckets = ({b for b, _k in old.buckets_compiled()}
                                   or {1})
                        kinds = ({k for _b, k in old.buckets_compiled()}
                                 | set(self.warmup_kinds))
                        runtime.warmup(sorted(buckets), sorted(kinds))
            except Exception as e:
                # a corrupt/torn candidate model must be LOUD and
                # visible at /stats, not a silent skip: exception class
                # + message into the log, the canonical
                # registry/swap_failures counter, and last_swap_error
                # for the stats endpoint (docs/Robustness.md)
                self.swap_failures += 1
                self._failed_sig = sig
                self.last_swap_error = f"{type(e).__name__}: {e}"
                profiling.count(profiling.REGISTRY_SWAP_FAILURES)
                log.warning(f"model hot-swap failed, keeping generation "
                            f"{old.generation} "
                            f"({self.last_swap_error})")
                return False
            if shadow:
                # shadow canary: STAGE the warmed candidate instead of
                # swapping — stable keeps answering every client, and
                # maybe_shadow double-scores a weighted fraction of
                # traffic on the candidate until the verdict
                with self._shadow_lock:
                    replaced = self._candidate is not None
                    self._candidate = runtime
                    self._candidate_sig = sig
                    self._candidate_trace = trace_id
                    self._shadow_tick = 0
                    self._shadow_scored = 0
                    self._shadow_max_div = 0.0
                self._sig = sig
                self._failed_sig = None
                if replaced:
                    log.info("shadow canary: a newer publish replaced "
                             "the pending candidate before its verdict")
                log.info(f"staged candidate generation "
                         f"{runtime.generation} for shadow canary "
                         f"({self.model_path}): adoption after "
                         f"{self.shadow_requests} shadowed comparisons")
                telemetry.event("serve.shadow", trace_id=trace_id,
                                state="staged",
                                generation=runtime.generation, **attrs)
                return False
            with self._shadow_lock:
                # an immediate swap supersedes any pending candidate:
                # letting it linger would hand a stale generation to a
                # later canary verdict
                stale = self._candidate
                self._candidate = None
            if stale is not None:
                log.info("discarding pending shadow candidate "
                         "(superseded by a forced immediate swap)")
            self._runtime = runtime          # the atomic swap
            self._sig = sig
            self._failed_sig = None
            self.last_swap_error = None
            self.swaps += 1
            profiling.count("serve.swap")
            log.info(f"hot-swapped model to generation "
                     f"{runtime.generation} ({self.model_path})")
            return True

    # -- shadow canary --------------------------------------------------

    def _model_labels(self) -> dict:
        return ({"model": self.model_id}
                if self.model_id is not None else {})

    def cache_bytes(self) -> int:
        """Estimated executable bytes this MODEL holds on device:
        stable runtime plus any staged shadow candidate (warmed at
        staging — without counting it, a fleet of pending canaries
        could sit at ~2x the configured cache budget invisibly)."""
        total = self._runtime.cache_bytes()
        cand = self._candidate
        if cand is not None:
            total += cand.cache_bytes()
        return total

    def evict_executables(self) -> int:
        """Evict the stable runtime's AND any staged candidate's
        executable caches (the catalog's LRU budget enforcement).  An
        evicted tenant keeps serving — its next request, shadow
        comparison, or post-adoption request recompiles (churn)."""
        n = self._runtime.evict_executables()
        cand = self._candidate
        if cand is not None:
            n += cand.evict_executables()
        return n

    def shadow_state(self) -> Optional[dict]:
        """The /stats view of a pending canary, or None."""
        with self._shadow_lock:
            cand = self._candidate
            if cand is None:
                return None
            return {"generation": cand.generation,
                    "scored": self._shadow_scored,
                    "required": self.shadow_requests,
                    "fraction": self.shadow_fraction,
                    "max_divergence": self._shadow_max_div,
                    "divergence_gate": self.shadow_max_divergence}

    def maybe_shadow(self, X, kind: str, stable_preds,
                     requests: int = 1) -> None:
        """Post-result hook of the batcher's flush: double-score this
        batch on the staged candidate, log the per-request divergence,
        and deliver the canary verdict once ``shadow_requests``
        comparisons accumulated.  Sampling is REQUEST-weighted at
        batch granularity: the tick advances by the batch's request
        count, so ~``shadow_fraction`` of requests get their batch
        shadowed regardless of how many coalesce per flush (a pure
        per-batch tick would under-shadow by the batching factor).
        Runs AFTER the clients' futures resolved, so stable-path
        latency never includes the candidate's scoring.  No-op (one
        attribute read) without a pending candidate."""
        cand = self._candidate
        if cand is None:
            return
        with self._shadow_lock:
            if self._candidate is not cand:    # replaced underneath
                return
            period = max(1, int(round(1.0 / self.shadow_fraction)))
            self._shadow_tick += max(1, int(requests))
            if self._shadow_tick < period:
                return
            self._shadow_tick -= period
        try:
            cand_preds = cand.predict(X, kind=kind)
            div = (float(np.max(np.abs(np.asarray(cand_preds)
                                       - np.asarray(stable_preds))))
                   if len(X) else 0.0)
        except Exception as e:  # noqa: BLE001 — a candidate that
            # cannot score is the canary's whole point: reject it
            self._shadow_verdict(cand, adopt=False,
                                 reason=f"candidate scoring failed "
                                        f"({type(e).__name__}: {e})")
            return
        labels = self._model_labels()
        profiling.count(profiling.SERVE_SHADOW_SCORED)
        if labels:
            profiling.count(profiling.labeled(
                profiling.SERVE_SHADOW_SCORED, **labels))
        profiling.observe(profiling.labeled("serve.shadow_divergence",
                                            **labels), div)
        with self._shadow_lock:
            if self._candidate is not cand:
                return
            self._shadow_scored += 1
            self._shadow_max_div = max(self._shadow_max_div, div)
            scored = self._shadow_scored
            max_div = self._shadow_max_div
        telemetry.event("serve.shadow", trace_id=self._candidate_trace,
                        state="scored", generation=cand.generation,
                        rows=int(len(X)), kind=kind,
                        divergence=round(div, 9), scored=scored,
                        required=self.shadow_requests, **labels)
        if scored < self.shadow_requests:
            return
        gate = self.shadow_max_divergence
        if gate >= 0.0 and max_div > gate:
            self._shadow_verdict(cand, adopt=False,
                                 reason=f"max divergence {max_div:g} > "
                                        f"gate {gate:g} over "
                                        f"{scored} shadowed comparisons")
        else:
            self._shadow_verdict(cand, adopt=True)

    def _shadow_verdict(self, cand: PredictorRuntime, adopt: bool,
                        reason: str = "") -> None:
        """Promote or discard the candidate — exactly once per staged
        candidate, whichever thread's shadow request crossed the bar.
        The verdict runs under the WRITER lock (then re-checks the
        candidate under the shadow lock — same _lock→_shadow_lock
        order as maybe_reload's staging), so an adoption can never
        interleave with a concurrent reload: generation numbers stay
        unique per model, and the swap bookkeeping fields have one
        writer at a time.  The acquire is NON-blocking: a reload
        holding the lock can take minutes (load + warmup), and the
        flusher thread delivering this verdict must never stall behind
        it — a busy lock defers the verdict to the next shadowed
        comparison (the quorum only grows), or moots it entirely when
        that reload replaces the candidate."""
        labels = self._model_labels()
        if not self._lock.acquire(blocking=False):
            return                           # retry on the next shadow
        try:
            with self._shadow_lock:
                if self._candidate is not cand:
                    return                   # raced: verdict delivered
                self._candidate = None
                trace_id = self._candidate_trace
                scored = self._shadow_scored
                max_div = self._shadow_max_div
                sig = getattr(self, "_candidate_sig", None)
            if adopt:
                # re-stamp against the CURRENT stable (a forced swap
                # may have landed since staging) so generations stay
                # strictly increasing and unique
                cand.generation = self._runtime.generation + 1
                self._runtime = cand         # the atomic swap
                self.last_swap_error = None
                self.swaps += 1
            else:
                # the rejected file's signature is remembered so the
                # poll does not restage it every tick; a healed
                # republish (or SIGHUP force) retries
                self._failed_sig = sig
                self.swap_failures += 1
                self.last_swap_error = f"shadow canary rejected: {reason}"
            stable_gen = self._runtime.generation
        finally:
            self._lock.release()
        if adopt:
            profiling.count("serve.swap")
            profiling.count(profiling.SERVE_SHADOW_ADOPTIONS)
            if labels:
                profiling.count(profiling.labeled(
                    profiling.SERVE_SHADOW_ADOPTIONS, **labels))
            log.info(f"shadow canary adopted generation "
                     f"{cand.generation} after {scored} shadowed "
                     f"comparisons (max divergence {max_div:g}, "
                     f"{self.model_path})")
            telemetry.event("serve.shadow", trace_id=trace_id,
                            state="adopted", generation=cand.generation,
                            scored=scored,
                            max_divergence=round(max_div, 9), **labels)
        else:
            profiling.count(profiling.REGISTRY_SWAP_FAILURES)
            profiling.count(profiling.SERVE_SHADOW_REJECTIONS)
            if labels:
                profiling.count(profiling.labeled(
                    profiling.SERVE_SHADOW_REJECTIONS, **labels))
            log.warning(f"shadow canary REJECTED candidate generation "
                        f"{cand.generation} ({reason}); generation "
                        f"{stable_gen} keeps serving "
                        f"({self.model_path})")
            telemetry.event("serve.shadow", trace_id=trace_id,
                            state="rejected", generation=cand.generation,
                            scored=scored, reason=reason,
                            max_divergence=round(max_div, 9), **labels)

    # -- triggers -------------------------------------------------------

    def install_sighup(self) -> bool:
        """SIGHUP → force reload on the next poll tick (bypassing any
        shadow canary — the operator's escape hatch).  Only possible
        from the main thread; returns False (mtime polling still works)
        otherwise."""

        def _mark():
            self._hup_pending = True

        return install_sighup_handler(_mark, self.poll_once)

    def poll_once(self) -> bool:
        # maybe_reload consumes _hup_pending itself, under the lock
        return self.maybe_reload()


def install_sighup_handler(mark, reload_fn) -> bool:
    """Install the serving SIGHUP convention, shared by ModelRegistry
    and ModelCatalog: the handler runs ``mark()`` SYNCHRONOUSLY (the
    force flag must be set even if the reload thread never gets to
    run), then the possibly minutes-long reload off-thread — SIGHUP
    must work with mtime polling disabled, and the handler itself must
    never block the main thread on a compile.  Main thread only;
    returns False where signals cannot be installed (polling still
    works)."""
    if threading.current_thread() is not threading.main_thread():
        return False

    def _on_hup(_signum, _frame):
        mark()
        threading.Thread(target=reload_fn, daemon=True,
                         name="lgbt-serve-hup").start()

    try:
        signal.signal(signal.SIGHUP, _on_hup)
    except (ValueError, OSError, AttributeError):
        return False
    return True
