"""Micro-batching request queue with continuous admission.

Online traffic arrives as many small concurrent requests; the TPU wants
few large shape-stable batches.  The batcher bridges the two: requests
queue up and flusher threads coalesce them until either
``max_batch_rows`` are pending or the OLDEST request has waited
``flush_deadline_ms`` — the classic latency/throughput dial of
accelerator serving stacks.

Batching is CONTINUOUS, not coalesce-then-flush: a batch keeps admitting
arriving requests right up to the moment it is taken for dispatch, and
with ``workers > 1`` (one flusher per predictor replica) the next batch
forms and dispatches while earlier ones are still scoring — the fleet
never idles behind a single in-flight batch.  One runtime reference is
pinned per flush, so every request in a batch scores against a single
model generation even while a hot swap lands mid-flight.

Deadline math uses the injectable monotonic clock ``_now`` (defaults to
``time.monotonic``): wall-clock jumps (NTP steps, manual clock changes)
can neither stall a batch past its deadline nor double-flush one.

``max_pending_rows`` adds admission control: once that many rows are
queued, further ``submit``s shed load with ServerOverloadedError
instead of growing an unbounded queue (the HTTP layer maps it to 503;
a request below the high-water mark always admits, however large — the
runtime chunks it — so the queue is bounded by cap + one request).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Deque, List, Optional

import numpy as np

from .. import log, profiling, telemetry
from ..diagnostics import locksan
from ..log import LightGBMError

# monotonic clock for ALL deadline math — module-level and injectable so
# the regression test can drive it; time.time() here would let a wall
# clock stepping backwards park a batch forever
_now = time.monotonic


class ServerOverloadedError(LightGBMError):
    """Queue beyond max_pending_rows — shed load (HTTP 503)."""


class _Request:
    __slots__ = ("X", "kind", "future", "t_enqueue", "trace_id",
                 "parent_id", "model_id")

    def __init__(self, X: np.ndarray, kind: str,
                 trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 model_id: Optional[str] = None):
        self.X = X
        self.kind = kind
        self.future: Future = Future()
        self.t_enqueue = _now()
        # trace propagation across the queue: the flusher thread cannot
        # inherit the HTTP handler thread's span context, so the ids
        # ride the request object explicitly
        self.trace_id = trace_id
        self.parent_id = parent_id
        # originating tenant on a SHARED (cross-model) batcher: selects
        # the request's tree segment in the group runtime and charges
        # its labeled accounting series.  None on per-tenant batchers
        # (the batcher-level model_id covers every request).
        self.model_id = model_id


class MicroBatcher:
    """Coalesce concurrent predict requests into bucketed runtime calls.

    `source` is anything with a ``current()`` returning the active
    PredictorRuntime (a ModelRegistry), or a runtime itself.  `workers`
    is the number of concurrent flusher threads — size it to the
    runtime's replica count so every replica can have a batch in flight.
    """

    def __init__(self, source, *, max_batch_rows: int = 4096,
                 flush_deadline_ms: float = 5.0, workers: int = 1,
                 max_pending_rows: int = 0,
                 model_id: Optional[str] = None,
                 pending_caps: Optional[dict] = None):
        self._source = source
        self.max_batch_rows = max(1, int(max_batch_rows))
        self.flush_deadline_s = max(0.0, float(flush_deadline_ms)) / 1e3
        self.max_pending_rows = max(0, int(max_pending_rows))
        self.workers = max(1, int(workers))
        # catalog tenant id: when set, every fleet-wide counter this
        # batcher bumps also bumps its per-model labeled series (the
        # /metrics `{model="..."}` accounting), and max_pending_rows is
        # this tenant's OWN admission budget — one hot tenant sheds its
        # own load instead of starving the fleet
        self.model_id = model_id
        self._labels = ({"model": model_id} if model_id is not None
                        else None)
        # SHARED (cross-model) batcher: admission stays PER TENANT —
        # each tenant's pending rows are tracked separately and checked
        # against its own cap (``pending_caps`` override, else
        # ``max_pending_rows``), so a hot tenant saturating the shared
        # queue sheds ITS load while quiet neighbors keep admitting
        self.pending_caps = dict(pending_caps or {})
        self._pending_by_model: dict = {}
        self._cond = locksan.condition("serve.batcher")
        self._queue: Deque[_Request] = deque()
        self._rows_pending = 0
        self._closed = False
        self.batches_flushed = 0
        self.rejected = 0
        self._threads = [
            threading.Thread(target=self._loop,
                             name=f"lgbt-serve-batcher-{i}", daemon=True)
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    # -- client side ----------------------------------------------------

    def submit(self, X: np.ndarray, kind: str = "value",
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               model_id: Optional[str] = None) -> Future:
        """Enqueue one request; the Future resolves to its predictions
        (Booster.predict shapes) or raises the scoring error.
        ``trace_id``/``parent_id`` tie the request's dispatch records to
        the caller's span (the HTTP handler passes its ingress ids).
        ``model_id`` names the originating tenant on a shared
        cross-model batcher (admission and accounting stay per
        tenant)."""
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.ndim != 2 or X.shape[0] == 0:
            raise LightGBMError("predict request must be a non-empty "
                                "[rows, features] matrix")
        mid = model_id if model_id is not None else self.model_id
        labels = ({"model": mid} if mid is not None else None)
        cap = (self.pending_caps.get(mid, self.max_pending_rows)
               if mid is not None else self.max_pending_rows)
        req = _Request(X, kind, trace_id, parent_id, model_id)
        with self._cond:
            if self._closed:
                raise LightGBMError("batcher is closed")
            # high-water-mark check: reject only when the queue is
            # already at/over the cap, so a single request larger than
            # the cap still lands on an idle server (the runtime chunks
            # arbitrarily large batches); the queue stays bounded by
            # cap + one request.  On a shared batcher the check runs
            # against the TENANT's own pending rows.
            pending = (self._pending_by_model.get(mid, 0)
                       if model_id is not None else self._rows_pending)
            if cap and pending >= cap:
                self.rejected += 1
                profiling.count("serve.rejected")
                if labels:
                    profiling.count(profiling.labeled("serve.rejected",
                                                      **labels))
                raise ServerOverloadedError(
                    f"serving queue full ({pending} rows "
                    f"pending, cap {cap}"
                    + (f", model {mid}" if mid else "") + "); retry later")
            self._queue.append(req)
            self._rows_pending += X.shape[0]
            if model_id is not None:
                self._pending_by_model[model_id] = (
                    self._pending_by_model.get(model_id, 0) + X.shape[0])
            depth = len(self._queue)
            self._cond.notify_all()
        profiling.count("serve.requests")
        profiling.observe("serve.queue_depth", depth)
        if labels:
            profiling.count(profiling.labeled("serve.requests",
                                              **labels))
            profiling.count(profiling.labeled("serve.rows",
                                              **labels),
                            X.shape[0])
            profiling.observe(profiling.labeled("serve.queue_depth",
                                                **labels), depth)
        return req.future

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def pending_rows_for(self, model_id: str) -> int:
        """One tenant's pending rows on a shared batcher (its /stats
        queue view — the global queue_depth spans every tenant)."""
        with self._cond:
            return self._pending_by_model.get(model_id, 0)

    def cap_for(self, model_id: str) -> int:
        """One tenant's admission cap (override or the shared default)."""
        return self.pending_caps.get(model_id, self.max_pending_rows)

    def close(self) -> None:
        """Stop accepting work, flush what is queued, join the threads."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=30)

    # -- flusher side ---------------------------------------------------

    def _take_batch(self) -> Optional[List[_Request]]:
        """Block until a batch is due (rows cap reached, deadline hit, or
        close); None means closed-and-drained.  The batch admits every
        request that arrives before it is taken — admission closes at
        dispatch, not at first-request time."""
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                self._cond.wait()
            deadline = self._queue[0].t_enqueue + self.flush_deadline_s
            while (self._rows_pending < self.max_batch_rows
                   and not self._closed):
                remaining = deadline - _now()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
                if not self._queue:
                    # another worker (or close) drained it — go around
                    return None if self._closed else []
                # the oldest request may have changed under a concurrent
                # worker; recompute so this batch's deadline tracks ITS
                # oldest member, not a dispatched one's
                deadline = self._queue[0].t_enqueue + self.flush_deadline_s
            batch: List[_Request] = []
            rows = 0
            while self._queue:
                nxt = self._queue[0].X.shape[0]
                if batch and rows + nxt > self.max_batch_rows:
                    break
                req = self._queue.popleft()
                rows += req.X.shape[0]
                if req.model_id is not None:
                    left = (self._pending_by_model.get(req.model_id, 0)
                            - req.X.shape[0])
                    if left > 0:
                        self._pending_by_model[req.model_id] = left
                    else:
                        self._pending_by_model.pop(req.model_id, None)
                batch.append(req)
            self._rows_pending -= rows
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            if batch:
                self._flush(batch)

    def _flush(self, batch: List[_Request]) -> None:
        # pin ONE runtime for the whole batch: no request ever spans a
        # half-swapped model
        try:
            runtime = (self._source.current()
                       if hasattr(self._source, "current") else self._source)
        except Exception as e:                     # registry load failure
            for req in batch:
                req.future.set_exception(e)
            return
        if hasattr(runtime, "predict_mixed"):
            self._flush_mixed(batch, runtime)
            return
        with self._cond:
            # flusher threads race on this read-modify-write; the stats
            # endpoints read it live
            self.batches_flushed += 1
        profiling.count("serve.batches")
        # group by (kind, feature width) so a malformed request only
        # fails its own group, never the neighbors that batched with it
        groups: dict = {}
        for req in batch:
            groups.setdefault((req.kind, req.X.shape[1]), []).append(req)
        for (kind, _f), reqs in groups.items():
            X = (reqs[0].X if len(reqs) == 1
                 else np.concatenate([r.X for r in reqs], axis=0))
            # the batch span runs under the OLDEST member's trace (its
            # deadline shaped the flush); every member's own trace gets
            # a `serve.dispatch` event naming the batch trace below, so
            # any single trace id still reconstructs its whole path
            leader = reqs[0]
            try:
                with telemetry.span(
                        "serve.batch", trace_id=leader.trace_id,
                        parent_id=leader.parent_id, kind=kind,
                        rows=int(X.shape[0]), requests=len(reqs)):
                    preds = runtime.predict(X, kind=kind)
            except Exception as e:
                for req in reqs:
                    req.future.set_exception(e)
                continue
            now = _now()
            generation = getattr(runtime, "generation", 0)
            off = 0
            for req in reqs:
                n = req.X.shape[0]
                # stamp the scoring generation before set_result so a
                # waiter that wakes on result() always sees it
                req.future.generation = generation
                req.future.set_result(preds[off:off + n])
                off += n
                wait_ms = (now - req.t_enqueue) * 1e3
                profiling.observe("serve.latency_ms", wait_ms)
                if self._labels:
                    profiling.observe(
                        profiling.labeled("serve.latency_ms",
                                          **self._labels), wait_ms)
                telemetry.event(
                    "serve.dispatch", trace_id=req.trace_id,
                    parent_id=req.parent_id, rows=n, kind=kind,
                    generation=generation,
                    batch_trace=leader.trace_id,
                    batch_requests=len(reqs),
                    wait_ms=round(wait_ms, 3))
            # shadow canary (registry.maybe_shadow): double-score this
            # group on a staged candidate AFTER every client's future
            # resolved — stable-path latency never includes it.  One
            # attribute read when no candidate is pending.
            shadow = getattr(self._source, "maybe_shadow", None)
            if shadow is not None:
                try:
                    shadow(X, kind, preds, requests=len(reqs))
                except Exception as e:  # noqa: BLE001 — the canary
                    # must never take the flusher down
                    log.warning(f"shadow scoring failed: "
                                f"{type(e).__name__}: {e}")

    def _flush_mixed(self, batch: List[_Request], runtime) -> None:
        """Dispatch one CROSS-MODEL batch on a GroupRuntime: every
        request carries its tenant, the group scores the mixed rows in
        one launch per chunk, and the demuxed per-request answers are
        charged — latency, dispatch events, shadow comparisons — to
        each request's OWN tenant, never to the group."""
        with self._cond:
            # same read-modify-write race as _flush: workers > 1 means
            # concurrent mixed flushes
            self.batches_flushed += 1
        profiling.count("serve.batches")
        # group by kind only: member widths differ legitimately (each
        # request validates against its own tenant's feature contract
        # inside predict_mixed), so width is not a batching boundary
        groups: dict = {}
        for req in batch:
            groups.setdefault(req.kind, []).append(req)
        for kind, reqs in groups.items():
            jobs = []
            routable = []
            for req in reqs:
                g = runtime.member_index.get(req.model_id)
                if g is None:
                    # the tenant left this group between enqueue and
                    # flush (a restack regrouped it) — fail THIS
                    # request; the client's retry re-routes correctly
                    req.future.set_exception(LightGBMError(
                        f"model {req.model_id!r} is no longer served "
                        "by this co-stack group; retry"))
                    continue
                jobs.append((g, req.X))
                routable.append(req)
            if not jobs:
                continue
            rows = int(sum(X.shape[0] for _g, X in jobs))
            leader = routable[0]
            try:
                with telemetry.span(
                        "serve.batch", trace_id=leader.trace_id,
                        parent_id=leader.parent_id, kind=kind,
                        rows=rows, requests=len(routable),
                        group=runtime.model_id):
                    outs = runtime.predict_mixed(jobs, kind=kind)
            except Exception as e:
                for req in routable:
                    req.future.set_exception(e)
                continue
            now = _now()
            generation = getattr(runtime, "generation", 0)
            for req, out in zip(routable, outs):
                req.future.generation = generation
                req.future.set_result(out)
                wait_ms = (now - req.t_enqueue) * 1e3
                profiling.observe("serve.latency_ms", wait_ms)
                if req.model_id is not None:
                    profiling.observe(
                        profiling.labeled("serve.latency_ms",
                                          model=req.model_id), wait_ms)
                telemetry.event(
                    "serve.dispatch", trace_id=req.trace_id,
                    parent_id=req.parent_id, rows=req.X.shape[0],
                    kind=kind, generation=generation,
                    model=req.model_id, group=runtime.model_id,
                    batch_trace=leader.trace_id,
                    batch_requests=len(routable),
                    wait_ms=round(wait_ms, 3))
            # per-MEMBER shadow canaries, after every future resolved:
            # each tenant's staged candidate double-scores only its own
            # rows, against its own stable answers, on its own solo
            # candidate runtime — a neighbor's canary never sees this
            # tenant's traffic
            shadow = getattr(self._source, "shadow_member", None)
            if shadow is None:
                continue
            by_member: dict = {}
            for req, out in zip(routable, outs):
                by_member.setdefault(req.model_id, []).append(
                    (req.X, out))
            for mid, pairs in by_member.items():
                try:
                    Xm = (pairs[0][0] if len(pairs) == 1 else
                          np.concatenate([p[0] for p in pairs], axis=0))
                    pm = (pairs[0][1] if len(pairs) == 1 else
                          np.concatenate([p[1] for p in pairs], axis=0))
                    shadow(mid, Xm, kind, pm, requests=len(pairs))
                except Exception as e:  # noqa: BLE001 — the canary
                    # must never take the flusher down
                    log.warning(f"shadow scoring failed for {mid}: "
                                f"{type(e).__name__}: {e}")
