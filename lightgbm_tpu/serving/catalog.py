"""Keyed model catalog: N independent tenants on one serving fleet.

The reference C API serves any number of independent Booster handles
per process (``LGBM_BoosterCreate`` — one handle per model); the PR 1-12
serving stack assumed ONE hot-swapped model generation.  Production is
neither: dozens of models (per country, per surface, A/B arms) share a
fleet, each with its own SLO, publish cadence, and failure domain.  The
catalog generalizes `ModelRegistry`/`PredictorRuntime` from one
generation to N tenants:

- **Keyed routing** — every tenant id maps to its own `ModelRegistry`
  (atomic hot-swap, shadow canary, replica breakers) and an admission
  queue.  `/predict` routes by the ``model`` body field / query param /
  ``X-Model-Id`` header; requests that name no model go to the DEFAULT
  tenant, which preserves the single-model contract bitwise.
- **Cross-model co-stacking** (serving/superstack.py) — tenants that
  share ``(num_class, serve_quantize variant, leaf tier)`` are packed
  into ONE super-stack scored by ONE compiled executable per (bucket,
  kind): a mixed batch of many tenants' requests costs one launch
  instead of one per tenant, bitwise-identical to per-tenant dispatch.
  Groups share a MicroBatcher (admission and accounting stay per
  tenant); incompatible tenants, per-tenant ``replicas``/
  ``costack=off`` overrides, and tenants with no same-key peer serve
  solo exactly as before.  A member hot swap RESTACKS only its group —
  same-shape republishes transplant the compiled executables with zero
  recompiles, and other groups' warm caches are never touched.
- **Isolation by construction** — per-tenant registries, admission
  budgets, breakers, and (per group or solo tenant) executable caches
  mean a torn publish or a broken replica on tenant A cannot change a
  single bit of tenant B's answers, nor put a compile on B's request
  path (tests/test_catalog.py chaos suite, tests/test_costack.py).
- **LRU executable budget** (``serve_cache_budget_mb``) — compiled
  executables are the device-memory cost that scales with tenants x
  buckets x kinds; the catalog sums estimated executable bytes per
  EVICTION UNIT (a co-stack group, or a solo tenant) and, beyond the
  budget, evicts the least-recently-used units' caches (never the
  most recently used one).  A group evicts COHERENTLY — its one
  shared cache serves every member, so per-member eviction would be
  meaningless.  An evicted unit keeps serving — its next request
  recompiles, counted as churn through ``serve/cache_evictions``.
  0 = unlimited, and the single-tenant path never evicts.
- **Per-model accounting** — requests/rows/rejections/latency
  percentiles/queue depth per tenant ride the `profiling.labeled`
  series (``lgbt_serve_requests_total{model="..."}`` at /metrics) and
  the server's ``/stats`` ``models`` block — co-stacked batches are
  demuxed back to the ORIGINATING tenant before any series is
  charged.  Groups get their own ``lgbt_serve_group_*`` series.

One `OnlineTrainer` per tenant (online/trainer.py `OnlineFleet`)
shares the labeled-traffic tail — rows are keyed by the same model
ids, each daemon publishes to its tenant's model path, and the
catalog's per-tenant polls pick the publishes up — so trace ids still
reconstruct any single tenant's serve→train→serve loop.
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Tuple

from .. import log, profiling
from ..config import MODEL_ID_RE
from ..diagnostics import locksan
from ..log import LightGBMError
from .batcher import MicroBatcher
from .registry import ModelRegistry
from .runtime import OUTPUT_KINDS
from .superstack import (MAX_GROUP_TENANTS, GroupRuntime, costack_key,
                         group_id_for)

DEFAULT_MODEL_ID = "default"


class UnknownModelError(LightGBMError):
    """Request named a model id the catalog does not serve (HTTP 404)."""


class _Tenant:
    """One tenant's serving column: registry + batcher + LRU tick.
    ``batcher`` is the tenant's OWN MicroBatcher when solo, or its
    GROUP's shared one when co-stacked (``group`` is then set)."""
    __slots__ = ("model_id", "registry", "batcher", "last_used", "group")

    def __init__(self, model_id: str, registry: ModelRegistry,
                 batcher: Optional[MicroBatcher] = None):
        self.model_id = model_id
        self.registry = registry
        self.batcher = batcher
        self.last_used = 0
        self.group: Optional[_Group] = None


class _Group:
    """One co-stack group: the shared GroupRuntime + MicroBatcher and
    the member bookkeeping the restack path needs.  Doubles as the
    batcher's runtime source (`current`) and per-member shadow relay
    (`shadow_member`)."""
    __slots__ = ("group_id", "key", "member_ids", "registries",
                 "runtime", "batcher", "gen_vector", "restacks")

    def __init__(self, group_id: str, key, member_ids: List[str],
                 registries: Dict[str, ModelRegistry],
                 runtime: GroupRuntime):
        self.group_id = group_id
        self.key = key
        self.member_ids = list(member_ids)
        self.registries = dict(registries)
        self.runtime = runtime
        self.batcher: Optional[MicroBatcher] = None
        self.gen_vector: Tuple[int, ...] = tuple(
            registries[mid].generation for mid in member_ids)
        self.restacks = 0

    def current(self) -> GroupRuntime:
        """The batcher's runtime pin — one atomic reference read, same
        contract as ModelRegistry.current()."""
        return self.runtime

    def shadow_member(self, model_id: str, X, kind: str, preds,
                      requests: int = 1) -> None:
        """Relay one member's demuxed rows to ITS registry's shadow
        canary — each tenant's candidate only ever sees (and is judged
        on) its own traffic."""
        reg = self.registries.get(model_id)
        if reg is not None:
            reg.maybe_shadow(X, kind, preds, requests=requests)

    def cache_bytes(self) -> int:
        """The group UNIT's executable bytes: the shared super-stack
        cache plus every member's staged shadow candidate (members'
        stable solo runtimes hold no executables under co-stacking,
        and registry.cache_bytes counts both)."""
        return (self.runtime.cache_bytes()
                + sum(reg.cache_bytes() for reg in self.registries.values()))

    def evict_executables(self) -> int:
        """Coherent whole-group eviction (the catalog's LRU): the one
        shared cache serves every member, so the group evicts as a
        unit — plus any members' staged candidates."""
        n = self.runtime.evict_executables()
        for reg in self.registries.values():
            n += reg.evict_executables()
        return n


class ModelCatalog:
    """Keyed (model id → registry/batcher) serving catalog.

    ``models`` is an ordered ``{id: model path}`` or ``{id: (path,
    overrides)}`` mapping (config.parse_serve_models output).  Registry
    and batcher knobs are fleet-wide unless a tenant's entry overrides
    ``replicas``, ``serve_quantize``, ``max_pending_rows``, or
    ``costack`` (docs/serving.md "Cross-model batching");
    ``max_pending_rows`` always applies PER TENANT (it is an admission
    budget, so a hot tenant sheds its own load), and a co-stack group's
    replica fleet sizes to the MAX of its members' ``replicas``
    overrides (`_group_replicas`).
    """

    def __init__(self, models: Dict[str, object],
                 params: Optional[dict] = None, *,
                 default_id: Optional[str] = None,
                 cache_budget_mb: int = 0,
                 num_iteration: int = -1, max_batch_rows: int = 4096,
                 min_bucket_rows: int = 16,
                 flush_deadline_ms: float = 5.0,
                 max_pending_rows: int = 0,
                 predict_kernel: Optional[str] = None, replicas: int = 0,
                 failure_threshold: int = 3,
                 serve_quantize: str = "auto",
                 shadow_fraction: float = 0.0,
                 shadow_requests: int = 32,
                 shadow_max_divergence: float = -1.0,
                 warmup_buckets=(1,),
                 costack: bool = True,
                 costack_kernel: str = "auto",
                 costack_segment_trees: int = 0):
        if not models:
            raise LightGBMError("ModelCatalog needs at least one "
                                "model id=path entry")
        entries = {mid: _normalize_entry(mid, spec)
                   for mid, spec in models.items()}
        for mid in entries:
            if not MODEL_ID_RE.match(str(mid)):
                raise LightGBMError(
                    f"model id {mid!r} must match [A-Za-z0-9._-]{{1,64}}")
        default_id = (default_id if default_id is not None
                      else next(iter(entries)))
        if default_id not in entries:
            raise LightGBMError(
                f"default model id {default_id!r} is not in the "
                f"catalog ({sorted(entries)})")
        self._init_base(default_id, cache_budget_mb)
        self._replicas = replicas
        self._failure_threshold = failure_threshold
        self._max_batch_rows = max_batch_rows
        self._flush_deadline_ms = flush_deadline_ms
        self._max_pending_rows = max_pending_rows
        self._warmup_buckets = tuple(warmup_buckets)
        self._costack = bool(costack)
        self._costack_kernel = str(costack_kernel)
        self._costack_segment_trees = int(costack_segment_trees or 0)
        solo_forced: Dict[str, bool] = {}
        caps: Dict[str, int] = {}
        for mid, (path, ov) in entries.items():
            # per-tenant overrides: costack=off opts out of grouping; a
            # replicas override rides its group (the group fleet sizes
            # to the members' max — _group_replicas) AND sizes the
            # tenant's solo runtime for fallback
            t_replicas = int(ov.get("replicas", replicas))
            if "replicas" in ov:
                self._replica_ov[mid] = t_replicas
            solo_forced[mid] = not ov.get("costack", True)
            caps[mid] = int(ov.get("max_pending_rows", max_pending_rows))
            registry = ModelRegistry(
                path, params=params, num_iteration=num_iteration,
                max_batch_rows=max_batch_rows,
                min_bucket_rows=min_bucket_rows,
                predict_kernel=predict_kernel, replicas=t_replicas,
                failure_threshold=failure_threshold,
                serve_quantize=str(ov.get("serve_quantize",
                                          serve_quantize)),
                model_id=mid,
                shadow_fraction=shadow_fraction,
                shadow_requests=shadow_requests,
                shadow_max_divergence=shadow_max_divergence,
                warmup_buckets=warmup_buckets,
                # warm NOTHING yet: grouped tenants must never compile
                # solo executables (the group warms instead), and which
                # tenants group is only known once every model is
                # loaded — solo tenants warm explicitly below
                warm_initial=False)
            self._tenants[mid] = _Tenant(mid, registry)
        self._caps = caps
        self._form_groups(solo_forced)
        for tenant in self._tenants.values():
            if tenant.group is not None:
                continue
            rt = tenant.registry.current()
            rt.warmup(self._warmup_buckets, tenant.registry.warmup_kinds)
            tenant.batcher = MicroBatcher(
                tenant.registry, max_batch_rows=max_batch_rows,
                flush_deadline_ms=flush_deadline_ms,
                workers=getattr(rt, "replica_count", 1),
                max_pending_rows=caps[tenant.model_id],
                model_id=tenant.model_id)
        log.info(f"model catalog serving {len(self._tenants)} tenants "
                 f"({', '.join(self._tenants)}; default "
                 f"{self.default_id!r}"
                 + (f"; {len(self._groups)} co-stack groups"
                    if self._groups else "")
                 + (f", cache budget {self.cache_budget_mb} MiB"
                    if self.cache_budget_mb else "") + ")")
        self.enforce_budget()                # construction already warms

    def _init_base(self, default_id: str, cache_budget_mb: int) -> None:
        """Every non-tenant attribute of a catalog, in ONE place —
        `__init__` and the `from_registry` shim both build on this, so
        an attribute added here can never be missing on the shim
        path."""
        self.default_id = default_id
        self.cache_budget_mb = max(0, int(cache_budget_mb))
        self._lock = locksan.lock("serve.catalog")   # LRU ticks + eviction scan
        self._tick = itertools.count(1)
        self._miss_mark = -1                 # submit-path dirty check
        self._tenants: Dict[str, _Tenant] = {}
        self._groups: Dict[str, _Group] = {}
        self._costack = False                # overridden by __init__;
        self._costack_kernel = "auto"        # shim defaults otherwise
        self._costack_segment_trees = 0
        self._costack_opt_out: set = set()
        self._replica_ov: Dict[str, int] = {}

    # -- co-stack grouping ----------------------------------------------

    def _form_groups(self, solo_forced: Dict[str, bool]) -> None:
        """Partition tenants into co-stack groups by compatibility key
        (superstack.costack_key); singletons and opted-out tenants stay
        solo.  Runs once at construction — membership is stable until a
        member republish breaks compatibility (_restack drops it)."""
        self._costack_opt_out = {mid for mid, forced in solo_forced.items()
                                 if forced}
        if not self._costack:
            return
        by_key: Dict[tuple, List[str]] = {}
        for mid, tenant in self._tenants.items():
            if solo_forced.get(mid):
                continue
            key = costack_key(tenant.registry.current())
            by_key.setdefault(key, []).append(mid)
        for key, mids in by_key.items():
            if len(mids) < 2:
                continue
            for chunk_no, at in enumerate(range(0, len(mids),
                                                MAX_GROUP_TENANTS)):
                members = mids[at:at + MAX_GROUP_TENANTS]
                if len(members) < 2:
                    break                    # a trailing singleton: solo
                self._build_group(key, members, chunk_no)

    def _group_replicas(self, member_ids: List[str]) -> int:
        """A group's replica fleet size: the MAX of its members'
        per-tenant ``replicas`` overrides (the hottest member sizes the
        shared fleet — every member rides it), the fleet-wide
        ``serve_replicas`` when no member overrides."""
        ov = [self._replica_ov[mid] for mid in member_ids
              if mid in self._replica_ov]
        return max(ov) if ov else self._replicas

    def _build_group(self, key, member_ids: List[str],
                     chunk_no: int = 0) -> None:
        gid = group_id_for(key, chunk_no)
        registries = {mid: self._tenants[mid].registry
                      for mid in member_ids}
        runtime = GroupRuntime(
            member_ids,
            [registries[mid].current() for mid in member_ids],
            group_id=gid, replicas=self._group_replicas(member_ids),
            failure_threshold=self._failure_threshold,
            costack_kernel=self._costack_kernel,
            costack_segment_trees=self._costack_segment_trees)
        runtime.warmup(self._warmup_buckets, OUTPUT_KINDS)
        group = _Group(gid, key, member_ids, registries, runtime)
        group.batcher = MicroBatcher(
            group, max_batch_rows=self._max_batch_rows,
            flush_deadline_ms=self._flush_deadline_ms,
            workers=getattr(runtime, "replica_count", 1),
            max_pending_rows=self._max_pending_rows,
            pending_caps={mid: self._caps.get(mid, self._max_pending_rows)
                          for mid in member_ids})
        self._groups[gid] = group
        for mid in member_ids:
            tenant = self._tenants[mid]
            tenant.group = group
            tenant.batcher = group.batcher
            tenant.registry.costacked = True
        log.info(f"co-stacked {len(member_ids)} tenants onto one "
                 f"executable group {gid} "
                 f"({', '.join(member_ids)})")

    @classmethod
    def from_registry(cls, registry: ModelRegistry, *,
                      model_id: str = DEFAULT_MODEL_ID,
                      max_batch_rows: int = 4096,
                      flush_deadline_ms: float = 5.0,
                      max_pending_rows: int = 0,
                      cache_budget_mb: int = 0) -> "ModelCatalog":
        """Wrap an ALREADY-BUILT registry as a one-tenant catalog — the
        back-compat shim behind ``PredictionServer(registry)``.  The
        single-model server keeps its pre-catalog behavior: same
        routing (everything lands on the one tenant), no eviction
        unless a budget is set, no co-stacking (a one-tenant group is
        pointless); the per-model labeled series simply ride along
        under the default id."""
        self = cls.__new__(cls)
        self._init_base(model_id, cache_budget_mb)
        if registry.model_id is None:
            registry.model_id = model_id
            rt = registry.current()
            if getattr(rt, "model_id", None) is None:
                rt.model_id = model_id
        batcher = MicroBatcher(
            registry, max_batch_rows=max_batch_rows,
            flush_deadline_ms=flush_deadline_ms,
            workers=getattr(registry.current(), "replica_count", 1),
            max_pending_rows=max_pending_rows,
            model_id=registry.model_id)
        self._tenants[model_id] = _Tenant(model_id, registry, batcher)
        return self

    # -- lookup / routing ----------------------------------------------

    def ids(self) -> List[str]:
        return list(self._tenants)

    def get(self, model_id: Optional[str] = None) -> _Tenant:
        """The tenant for a request's model id (None = default)."""
        mid = self.default_id if model_id is None else model_id
        tenant = self._tenants.get(mid)
        if tenant is None:
            raise UnknownModelError(
                f"unknown model {mid!r}; this catalog serves "
                f"{sorted(self._tenants)}")
        return tenant

    def default(self) -> _Tenant:
        return self._tenants[self.default_id]

    def submit(self, X, kind: str = "value",
               model_id: Optional[str] = None,
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None):
        """Route one request: touch the tenant's LRU tick, enqueue on
        its (own or group-shared) batcher, keep the executable budget
        honored.  Returns the (tenant, future) pair — the caller reads
        the scoring generation off the future like before."""
        tenant = self.get(model_id)
        with self._lock:
            tenant.last_used = next(self._tick)
        if tenant.group is not None:
            fut = tenant.batcher.submit(X, kind=kind, trace_id=trace_id,
                                        parent_id=parent_id,
                                        model_id=tenant.model_id)
        else:
            fut = tenant.batcher.submit(X, kind=kind, trace_id=trace_id,
                                        parent_id=parent_id)
        if self.cache_budget_mb:
            # cheap dirty check on the hot path: cache totals only
            # move when something COMPILED, so the O(units) byte
            # scan (one lock per runtime) runs only after a cache
            # miss somewhere, not on every request
            marks = sum(rt.cache_misses for rt in self._scoring_runtimes())
            if marks != self._miss_mark:
                self._miss_mark = marks
                self.enforce_budget()
        return tenant, fut

    def _scoring_runtimes(self) -> List:
        """Every runtime that can COMPILE on the request path: group
        runtimes plus solo tenants' current runtimes."""
        out: List = [g.runtime for g in self._groups.values()]
        out.extend(t.registry.current() for t in self._tenants.values()
                   if t.group is None)
        return out

    # -- LRU executable budget -----------------------------------------

    def _units(self) -> List[tuple]:
        """(last_used, name, unit) eviction units: each co-stack group
        (coherent — one shared cache serves every member) and each solo
        tenant.  A group's recency is its most recently used member's."""
        units: List[tuple] = []
        grouped = set()
        for gid, group in self._groups.items():
            last = max((self._tenants[mid].last_used
                        for mid in group.member_ids), default=0)
            units.append((last, gid, group))
            grouped.update(group.member_ids)
        for mid, tenant in self._tenants.items():
            if mid not in grouped:
                units.append((tenant.last_used, mid, tenant.registry))
        return units

    def cache_bytes(self) -> Dict[str, int]:
        """Estimated executable bytes per eviction unit (group id or
        solo tenant id; stable runtime plus any staged shadow
        candidates)."""
        return {name: unit.cache_bytes()
                for _last, name, unit in self._units()}

    def enforce_budget(self) -> int:
        """Evict least-recently-used units' executable caches until
        the total fits ``serve_cache_budget_mb``.  The most recently
        used unit is NEVER evicted (a budget smaller than one unit's
        working set degrades to single-unit residency, not
        thrash-to-zero).  Co-stack groups evict whole (their one cache
        serves every member); staged shadow candidates count toward —
        and evict with — their unit.  Returns executables evicted."""
        if not self.cache_budget_mb:
            return 0
        budget = self.cache_budget_mb << 20
        with self._lock:
            order = sorted(self._units(), key=lambda u: u[0])  # LRU first
        total = sum(unit.cache_bytes() for _l, _n, unit in order)
        evicted = 0
        for _last, _name, unit in order[:-1]:  # MRU unit is protected
            if total <= budget:
                break
            if unit.cache_bytes() <= 0:
                continue
            evicted += unit.evict_executables()
            # recompute rather than subtract an estimate: eviction
            # frees exactly what the caches now report as gone
            total = sum(u.cache_bytes() for _l, _n, u in order)
        if total > budget and evicted:
            log.info(f"serve cache budget: still {total >> 20} MiB "
                     f"after eviction (budget {self.cache_budget_mb} "
                     "MiB covers less than the hottest unit)")
        return evicted

    # -- polling / swap -------------------------------------------------

    def poll_once(self) -> int:
        """Poll every tenant's model path; returns swaps landed.  A
        swap (or a shadow adoption since the last tick) on a co-stacked
        tenant shows up as a generation-vector change on its group and
        triggers a RESTACK of that group only.  Runs budget enforcement
        afterwards — a freshly warmed generation is exactly when totals
        can jump."""
        swaps = 0
        for tenant in self._tenants.values():
            try:
                if tenant.registry.poll_once():
                    swaps += 1
            except Exception as e:   # one tenant's poll failure must
                # not starve the others' reloads
                log.warning(f"model poll failed for "
                            f"{tenant.model_id}: {e}")
        for group in list(self._groups.values()):
            vector = tuple(group.registries[mid].generation
                           for mid in group.member_ids)
            if vector != group.gen_vector:
                try:
                    self._restack(group)
                except Exception as e:
                    log.warning(f"co-stack restack failed for "
                                f"{group.group_id}: {e}; the previous "
                                "super-stack keeps serving")
        if self.cache_budget_mb:
            self.enforce_budget()
        return swaps

    def _drop_to_solo(self, model_id: str) -> None:
        """Demote one tenant from its group to a solo serving column
        (its republish broke group compatibility): warm its solo
        runtime and give it its own batcher.  In-flight group requests
        for it fail fast with a retryable error."""
        tenant = self._tenants[model_id]
        reg = tenant.registry
        reg.costacked = False
        tenant.group = None
        rt = reg.current()
        rt.warmup(self._warmup_buckets, reg.warmup_kinds)
        tenant.batcher = MicroBatcher(
            reg, max_batch_rows=self._max_batch_rows,
            flush_deadline_ms=self._flush_deadline_ms,
            workers=getattr(rt, "replica_count", 1),
            max_pending_rows=self._caps.get(model_id,
                                            self._max_pending_rows),
            model_id=model_id)
        log.info(f"tenant {model_id} left its co-stack group "
                 "(republish changed its compatibility key); now solo")

    def _restack(self, group: _Group) -> None:
        """Rebuild one group's super-stack from its members' CURRENT
        runtimes after a member hot swap.  Members whose republish
        broke the compatibility key (num_class or kernel variant
        changed) drop to solo; the rest restack.  When the program
        signature is unchanged (the common refit republish) the old
        executables transplant — zero compiles; otherwise only THIS
        group warms.  Other groups are never touched."""
        stay: List[str] = []
        for mid in group.member_ids:
            rt = group.registries[mid].current()
            if rt.K == group.key[0] and rt.variant == group.key[1]:
                stay.append(mid)
            else:
                self._drop_to_solo(mid)
        old = group.runtime
        if len(stay) < 2:
            # the group dissolved: remaining members go solo too
            for mid in stay:
                self._drop_to_solo(mid)
            del self._groups[group.group_id]
            batcher = group.batcher
            if batcher is not None:
                threading.Thread(target=batcher.close, daemon=True,
                                 name="lgbt-serve-group-drain").start()
            log.info(f"co-stack group {group.group_id} dissolved")
            return
        group.member_ids = stay
        group.registries = {mid: self._tenants[mid].registry
                            for mid in stay}
        runtime = GroupRuntime(
            stay, [group.registries[mid].current() for mid in stay],
            group_id=group.group_id, generation=old.generation + 1,
            replicas=self._group_replicas(stay),
            failure_threshold=self._failure_threshold,
            costack_kernel=self._costack_kernel,
            costack_segment_trees=self._costack_segment_trees)
        if not runtime.adopt_cache_from(old):
            # program changed (tree shapes, transforms, membership):
            # warm every bucket/kind the outgoing group served before
            # going live, so no member's request compiles on the
            # request path
            buckets = ({b for b, _k in old.buckets_compiled()}
                       or set(self._warmup_buckets))
            kinds = ({k for _b, k in old.buckets_compiled()}
                     | set(OUTPUT_KINDS))
            runtime.warmup(sorted(buckets), sorted(kinds))
        group.runtime = runtime              # the atomic swap
        group.gen_vector = tuple(group.registries[mid].generation
                                 for mid in stay)
        group.restacks += 1
        if group.batcher is not None:
            group.batcher.pending_caps = {
                mid: self._caps.get(mid, self._max_pending_rows)
                for mid in stay}
        profiling.count(profiling.SERVE_GROUP_RESTACKS)
        profiling.count(profiling.labeled(profiling.SERVE_GROUP_RESTACKS,
                                          group=group.group_id))
        log.info(f"restacked co-stack group {group.group_id} "
                 f"({len(stay)} tenants, generation "
                 f"{runtime.generation})")

    def _mark_hup_all(self) -> None:
        for tenant in self._tenants.values():
            tenant.registry._hup_pending = True

    def force_reload_all(self) -> None:
        """SIGHUP semantics across the catalog: force-reload every
        tenant on this call (bypassing any pending shadow canaries —
        the registries' forced-reload escape hatch)."""
        self._mark_hup_all()
        self.poll_once()

    def install_sighup(self) -> bool:
        """SIGHUP → force-reload EVERY tenant (the shared serving
        SIGHUP convention — registry.install_sighup_handler).  Main
        thread only."""
        from .registry import install_sighup_handler
        return install_sighup_handler(self._mark_hup_all, self.poll_once)

    # -- stats ----------------------------------------------------------

    def tenant_stats(self) -> Dict[str, dict]:
        """The /stats ``models`` block: per-tenant SLO + fleet view."""
        out: Dict[str, dict] = {}
        for mid, t in self._tenants.items():
            reg = t.registry
            # the runtime actually SERVING this tenant's traffic: the
            # group's shared one when co-stacked, its solo one otherwise
            rt = t.group.runtime if t.group is not None else reg.current()
            labels = {"model": mid}
            grouped = t.group is not None
            out[mid] = {
                "generation": reg.generation,
                "model_path": reg.model_path,
                "default": mid == self.default_id,
                "group": t.group.group_id if grouped else None,
                "requests": profiling.counter_value(
                    profiling.labeled("serve.requests", **labels)),
                "rows": profiling.counter_value(
                    profiling.labeled("serve.rows", **labels)),
                "rejected": profiling.counter_value(
                    profiling.labeled("serve.rejected", **labels)),
                "latency_ms": profiling.summary(
                    profiling.labeled("serve.latency_ms", **labels)),
                "queue_depth": (t.batcher.pending_rows_for(mid) if grouped
                                else t.batcher.queue_depth),
                "pending_rows_cap": (t.batcher.cap_for(mid) if grouped
                                     else t.batcher.max_pending_rows),
                "batch_workers": t.batcher.workers,
                "swaps": reg.swaps,
                "swap_failures": reg.swap_failures,
                "last_swap_error": reg.last_swap_error,
                "shadow": reg.shadow_state(),
                "cache_bytes": (t.group.cache_bytes() if grouped
                                else reg.cache_bytes()),
                "evictions": profiling.counter_value(
                    profiling.labeled(profiling.SERVE_CACHE_EVICTIONS,
                                      **labels)),
                "replicas": {
                    "count": getattr(rt, "replica_count", 1),
                    "healthy": (rt.healthy_count()
                                if hasattr(rt, "healthy_count") else 1),
                },
                "serve_quantize": getattr(rt, "variant", "raw"),
            }
        return out

    def group_keys(self) -> Dict[str, str]:
        """Per-tenant co-stack compatibility key (the group-id base
        string) for every tenant that may group — the payload serving
        /healthz hands the router tier so its placement can co-locate
        same-key tenants onto one backend (co-stack-aware placement,
        docs/Router.md).  Tenants that opted out (``costack=off``) are
        omitted — they place by tenant id as before — as is everything
        when fleet-wide co-stacking is off."""
        out: Dict[str, str] = {}
        if not self._costack:
            return out
        for mid, t in self._tenants.items():
            if mid in self._costack_opt_out:
                continue
            out[mid] = group_id_for(costack_key(t.registry.current()))
        return out

    def group_stats(self) -> Dict[str, dict]:
        """The /stats ``groups`` block: per-group co-stack view."""
        out: Dict[str, dict] = {}
        for gid, group in self._groups.items():
            rt = group.runtime
            out[gid] = {
                "members": list(group.member_ids),
                "tenants": len(group.member_ids),
                "generation": rt.generation,
                "restacks": group.restacks,
                "compiles": profiling.counter_value(profiling.labeled(
                    profiling.SERVE_GROUP_COMPILES, group=gid)),
                "trees": int(rt._gmeta.segments[-1][1]),
                "depth": rt._gmeta.depth,
                "num_class": rt.K,
                "variant": rt.variant,
                "costack_kernel": rt.costack_kernel,
                "segment_rows": profiling.counter_value(profiling.labeled(
                    profiling.SERVE_GROUP_SEGMENT_ROWS, group=gid)),
                "stacked_rows": profiling.counter_value(profiling.labeled(
                    profiling.SERVE_GROUP_STACKED_ROWS, group=gid)),
                "quantize_shared_rows": profiling.counter_value(
                    profiling.labeled(
                        profiling.SERVE_GROUP_QUANTIZE_SHARED, group=gid)),
                "shared_quantizer": rt._shared_quantizer is not None,
                "cache_bytes": group.cache_bytes(),
                "queue_depth": (group.batcher.queue_depth
                                if group.batcher is not None else 0),
                "replicas": {
                    "count": rt.replica_count,
                    "healthy": rt.healthy_count(),
                },
            }
        return out

    def gauges(self) -> Dict[str, float]:
        """Per-model live gauges for /metrics (labeled series)."""
        g: Dict[str, float] = {}
        for mid, t in self._tenants.items():
            grouped = t.group is not None
            rt = t.group.runtime if grouped else t.registry.current()
            g[profiling.labeled("serve.model_generation", model=mid)] = (
                t.registry.generation)
            g[profiling.labeled("serve.queue_depth", model=mid)] = (
                t.batcher.pending_rows_for(mid) if grouped
                else t.batcher.queue_depth)
            g[profiling.labeled("serve.healthy_replicas", model=mid)] = (
                rt.healthy_count() if hasattr(rt, "healthy_count") else 1)
            g[profiling.labeled("serve.cache_bytes", model=mid)] = (
                t.group.cache_bytes() if grouped
                else t.registry.cache_bytes())
        for gid, group in self._groups.items():
            g[profiling.labeled("serve.group_tenants", group=gid)] = (
                len(group.member_ids))
            g[profiling.labeled("serve.group_cache_bytes", group=gid)] = (
                group.cache_bytes())
        g["serve.models"] = len(self._tenants)
        g["serve.groups"] = len(self._groups)
        g["serve.cache_budget_mb"] = self.cache_budget_mb
        return g

    # -- lifecycle ------------------------------------------------------

    def close(self) -> None:
        closed = set()
        for tenant in self._tenants.values():
            if tenant.batcher is not None and id(tenant.batcher) not in closed:
                closed.add(id(tenant.batcher))
                tenant.batcher.close()


def _normalize_entry(mid: str, spec) -> Tuple[str, dict]:
    """One catalog entry → (path, overrides).  Accepts a bare path
    string (the pre-override shape every existing caller passes), a
    (path, overrides) pair, or a config.ServeModelEntry."""
    # the overrides check must precede the plain-str one: a parsed
    # config.ServeModelEntry IS a str (the path) carrying overrides
    if hasattr(spec, "path") and hasattr(spec, "overrides"):
        return spec.path, dict(spec.overrides)
    if isinstance(spec, str):
        return spec, {}
    try:
        path, overrides = spec
        return str(path), dict(overrides)
    except (TypeError, ValueError):
        raise LightGBMError(
            f"catalog entry for {mid!r} must be a path or "
            f"(path, overrides), got {spec!r}")
