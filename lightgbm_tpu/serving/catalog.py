"""Keyed model catalog: N independent tenants on one serving fleet.

The reference C API serves any number of independent Booster handles
per process (``LGBM_BoosterCreate`` — one handle per model); the PR 1-12
serving stack assumed ONE hot-swapped model generation.  Production is
neither: dozens of models (per country, per surface, A/B arms) share a
fleet, each with its own SLO, publish cadence, and failure domain.  The
catalog generalizes `ModelRegistry`/`PredictorRuntime` from one
generation to N tenants:

- **Keyed routing** — every tenant id maps to its own `ModelRegistry`
  (atomic hot-swap, shadow canary, replica breakers) and its own
  `MicroBatcher` (continuous batching, per-tenant admission budget).
  `/predict` routes by the ``model`` body field / query param /
  ``X-Model-Id`` header; requests that name no model go to the DEFAULT
  tenant, which preserves the single-model contract bitwise.
- **Isolation by construction** — per-tenant registries, executable
  caches, batcher queues, and circuit breakers mean a torn publish or
  a broken replica on tenant A cannot change a single bit of tenant
  B's answers, nor put a compile on B's request path
  (tests/test_catalog.py chaos suite).
- **LRU executable budget** (``serve_cache_budget_mb``) — compiled
  executables are the device-memory cost that scales with tenants x
  buckets x kinds; the catalog sums each tenant's estimated executable
  bytes and, beyond the budget, evicts the least-recently-used
  tenants' caches (never the most recently used one).  An evicted
  tenant keeps serving — its next request recompiles, counted as
  churn through ``serve/cache_evictions`` (plus the per-model labeled
  series).  0 = unlimited, and the single-tenant path never evicts.
- **Per-model accounting** — requests/rows/rejections/latency
  percentiles/queue depth per tenant ride the `profiling.labeled`
  series (``lgbt_serve_requests_total{model="..."}`` at /metrics) and
  the server's ``/stats`` ``models`` block.

One `OnlineTrainer` per tenant (online/trainer.py `OnlineFleet`)
shares the labeled-traffic tail — rows are keyed by the same model
ids, each daemon publishes to its tenant's model path, and the
catalog's per-tenant polls pick the publishes up — so trace ids still
reconstruct any single tenant's serve→train→serve loop.
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional

from .. import log, profiling
from ..config import MODEL_ID_RE
from ..log import LightGBMError
from .batcher import MicroBatcher
from .registry import ModelRegistry

DEFAULT_MODEL_ID = "default"


class UnknownModelError(LightGBMError):
    """Request named a model id the catalog does not serve (HTTP 404)."""


class _Tenant:
    """One tenant's serving column: registry + batcher + LRU tick."""
    __slots__ = ("model_id", "registry", "batcher", "last_used")

    def __init__(self, model_id: str, registry: ModelRegistry,
                 batcher: MicroBatcher):
        self.model_id = model_id
        self.registry = registry
        self.batcher = batcher
        self.last_used = 0


class ModelCatalog:
    """Keyed (model id → registry/batcher) serving catalog.

    ``models`` is an ordered ``{id: model path}`` mapping
    (config.parse_serve_models output).  Every registry/batcher knob is
    shared across tenants — per-tenant knobs beyond the model path are
    deliberately out of scope until an operator needs them — except
    that ``max_pending_rows`` applies PER TENANT (it is an admission
    budget, so a hot tenant sheds its own load).
    """

    def __init__(self, models: Dict[str, str],
                 params: Optional[dict] = None, *,
                 default_id: Optional[str] = None,
                 cache_budget_mb: int = 0,
                 num_iteration: int = -1, max_batch_rows: int = 4096,
                 min_bucket_rows: int = 16,
                 flush_deadline_ms: float = 5.0,
                 max_pending_rows: int = 0,
                 predict_kernel: Optional[str] = None, replicas: int = 0,
                 failure_threshold: int = 3,
                 serve_quantize: str = "auto",
                 shadow_fraction: float = 0.0,
                 shadow_requests: int = 32,
                 shadow_max_divergence: float = -1.0,
                 warmup_buckets=(1,)):
        if not models:
            raise LightGBMError("ModelCatalog needs at least one "
                                "model id=path entry")
        for mid in models:
            if not MODEL_ID_RE.match(str(mid)):
                raise LightGBMError(
                    f"model id {mid!r} must match [A-Za-z0-9._-]{{1,64}}")
        default_id = (default_id if default_id is not None
                      else next(iter(models)))
        if default_id not in models:
            raise LightGBMError(
                f"default model id {default_id!r} is not in the "
                f"catalog ({sorted(models)})")
        self._init_base(default_id, cache_budget_mb)
        for mid, path in models.items():
            registry = ModelRegistry(
                path, params=params, num_iteration=num_iteration,
                max_batch_rows=max_batch_rows,
                min_bucket_rows=min_bucket_rows,
                predict_kernel=predict_kernel, replicas=replicas,
                failure_threshold=failure_threshold,
                serve_quantize=serve_quantize, model_id=mid,
                shadow_fraction=shadow_fraction,
                shadow_requests=shadow_requests,
                shadow_max_divergence=shadow_max_divergence,
                warmup_buckets=warmup_buckets)
            batcher = MicroBatcher(
                registry, max_batch_rows=max_batch_rows,
                flush_deadline_ms=flush_deadline_ms,
                workers=getattr(registry.current(), "replica_count", 1),
                max_pending_rows=max_pending_rows, model_id=mid)
            self._tenants[mid] = _Tenant(mid, registry, batcher)
        log.info(f"model catalog serving {len(self._tenants)} tenants "
                 f"({', '.join(self._tenants)}; default "
                 f"{self.default_id!r}"
                 + (f", cache budget {self.cache_budget_mb} MiB"
                    if self.cache_budget_mb else "") + ")")
        self.enforce_budget()                # construction already warms

    def _init_base(self, default_id: str, cache_budget_mb: int) -> None:
        """Every non-tenant attribute of a catalog, in ONE place —
        `__init__` and the `from_registry` shim both build on this, so
        an attribute added here can never be missing on the shim
        path."""
        self.default_id = default_id
        self.cache_budget_mb = max(0, int(cache_budget_mb))
        self._lock = threading.Lock()        # LRU ticks + eviction scan
        self._tick = itertools.count(1)
        self._miss_mark = -1                 # submit-path dirty check
        self._tenants: Dict[str, _Tenant] = {}

    @classmethod
    def from_registry(cls, registry: ModelRegistry, *,
                      model_id: str = DEFAULT_MODEL_ID,
                      max_batch_rows: int = 4096,
                      flush_deadline_ms: float = 5.0,
                      max_pending_rows: int = 0,
                      cache_budget_mb: int = 0) -> "ModelCatalog":
        """Wrap an ALREADY-BUILT registry as a one-tenant catalog — the
        back-compat shim behind ``PredictionServer(registry)``.  The
        single-model server keeps its pre-catalog behavior: same
        routing (everything lands on the one tenant), no eviction
        unless a budget is set; the per-model labeled series simply
        ride along under the default id."""
        self = cls.__new__(cls)
        self._init_base(model_id, cache_budget_mb)
        if registry.model_id is None:
            registry.model_id = model_id
            rt = registry.current()
            if getattr(rt, "model_id", None) is None:
                rt.model_id = model_id
        batcher = MicroBatcher(
            registry, max_batch_rows=max_batch_rows,
            flush_deadline_ms=flush_deadline_ms,
            workers=getattr(registry.current(), "replica_count", 1),
            max_pending_rows=max_pending_rows,
            model_id=registry.model_id)
        self._tenants[model_id] = _Tenant(model_id, registry, batcher)
        return self

    # -- lookup / routing ----------------------------------------------

    def ids(self) -> List[str]:
        return list(self._tenants)

    def get(self, model_id: Optional[str] = None) -> _Tenant:
        """The tenant for a request's model id (None = default)."""
        mid = self.default_id if model_id is None else model_id
        tenant = self._tenants.get(mid)
        if tenant is None:
            raise UnknownModelError(
                f"unknown model {mid!r}; this catalog serves "
                f"{sorted(self._tenants)}")
        return tenant

    def default(self) -> _Tenant:
        return self._tenants[self.default_id]

    def submit(self, X, kind: str = "value",
               model_id: Optional[str] = None,
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None):
        """Route one request: touch the tenant's LRU tick, enqueue on
        its batcher, keep the executable budget honored.  Returns the
        (tenant, future) pair — the caller reads the scoring generation
        off the future like before."""
        tenant = self.get(model_id)
        with self._lock:
            tenant.last_used = next(self._tick)
        fut = tenant.batcher.submit(X, kind=kind, trace_id=trace_id,
                                    parent_id=parent_id)
        if self.cache_budget_mb:
            # cheap dirty check on the hot path: cache totals only
            # move when something COMPILED, so the O(tenants) byte
            # scan (one lock per runtime) runs only after a cache
            # miss somewhere, not on every request
            marks = sum(t.registry.current().cache_misses
                        for t in self._tenants.values())
            if marks != self._miss_mark:
                self._miss_mark = marks
                self.enforce_budget()
        return tenant, fut

    # -- LRU executable budget -----------------------------------------

    def cache_bytes(self) -> Dict[str, int]:
        """Per-tenant estimated executable bytes (stable runtime plus
        any staged shadow candidate — registry.cache_bytes)."""
        return {mid: t.registry.cache_bytes()
                for mid, t in self._tenants.items()}

    def enforce_budget(self) -> int:
        """Evict least-recently-used tenants' executable caches until
        the total fits ``serve_cache_budget_mb``.  The most recently
        used tenant is NEVER evicted (a budget smaller than one
        tenant's working set degrades to single-tenant residency, not
        thrash-to-zero).  Staged shadow candidates count toward — and
        evict with — their tenant.  Returns executables evicted."""
        if not self.cache_budget_mb:
            return 0
        budget = self.cache_budget_mb << 20
        with self._lock:
            order = sorted(self._tenants.values(),
                           key=lambda t: t.last_used)   # LRU first
        total = sum(t.registry.cache_bytes() for t in order)
        evicted = 0
        for tenant in order[:-1]:            # MRU tenant is protected
            if total <= budget:
                break
            if tenant.registry.cache_bytes() <= 0:
                continue
            evicted += tenant.registry.evict_executables()
            # recompute rather than subtract an estimate: eviction
            # frees exactly what the caches now report as gone
            total = sum(t.registry.cache_bytes() for t in order)
        if total > budget and evicted:
            log.info(f"serve cache budget: still {total >> 20} MiB "
                     f"after eviction (budget {self.cache_budget_mb} "
                     "MiB covers less than the hottest tenant)")
        return evicted

    # -- polling / swap -------------------------------------------------

    def poll_once(self) -> int:
        """Poll every tenant's model path; returns swaps landed.  Runs
        budget enforcement afterwards — a freshly warmed generation is
        exactly when totals can jump."""
        swaps = 0
        for tenant in self._tenants.values():
            try:
                if tenant.registry.poll_once():
                    swaps += 1
            except Exception as e:   # one tenant's poll failure must
                # not starve the others' reloads
                log.warning(f"model poll failed for "
                            f"{tenant.model_id}: {e}")
        if self.cache_budget_mb:
            self.enforce_budget()
        return swaps

    def _mark_hup_all(self) -> None:
        for tenant in self._tenants.values():
            tenant.registry._hup_pending = True

    def force_reload_all(self) -> None:
        """SIGHUP semantics across the catalog: force-reload every
        tenant on this call (bypassing any pending shadow canaries —
        the registries' forced-reload escape hatch)."""
        self._mark_hup_all()
        self.poll_once()

    def install_sighup(self) -> bool:
        """SIGHUP → force-reload EVERY tenant (the shared serving
        SIGHUP convention — registry.install_sighup_handler).  Main
        thread only."""
        from .registry import install_sighup_handler
        return install_sighup_handler(self._mark_hup_all, self.poll_once)

    # -- stats ----------------------------------------------------------

    def tenant_stats(self) -> Dict[str, dict]:
        """The /stats ``models`` block: per-tenant SLO + fleet view."""
        out: Dict[str, dict] = {}
        for mid, t in self._tenants.items():
            reg, rt = t.registry, t.registry.current()
            labels = {"model": mid}
            out[mid] = {
                "generation": reg.generation,
                "model_path": reg.model_path,
                "default": mid == self.default_id,
                "requests": profiling.counter_value(
                    profiling.labeled("serve.requests", **labels)),
                "rows": profiling.counter_value(
                    profiling.labeled("serve.rows", **labels)),
                "rejected": profiling.counter_value(
                    profiling.labeled("serve.rejected", **labels)),
                "latency_ms": profiling.summary(
                    profiling.labeled("serve.latency_ms", **labels)),
                "queue_depth": t.batcher.queue_depth,
                "pending_rows_cap": t.batcher.max_pending_rows,
                "batch_workers": t.batcher.workers,
                "swaps": reg.swaps,
                "swap_failures": reg.swap_failures,
                "last_swap_error": reg.last_swap_error,
                "shadow": reg.shadow_state(),
                "cache_bytes": reg.cache_bytes(),
                "evictions": profiling.counter_value(
                    profiling.labeled(profiling.SERVE_CACHE_EVICTIONS,
                                      **labels)),
                "replicas": {
                    "count": getattr(rt, "replica_count", 1),
                    "healthy": (rt.healthy_count()
                                if hasattr(rt, "healthy_count") else 1),
                },
                "serve_quantize": getattr(rt, "variant", "raw"),
            }
        return out

    def gauges(self) -> Dict[str, float]:
        """Per-model live gauges for /metrics (labeled series)."""
        g: Dict[str, float] = {}
        for mid, t in self._tenants.items():
            rt = t.registry.current()
            g[profiling.labeled("serve.model_generation", model=mid)] = (
                t.registry.generation)
            g[profiling.labeled("serve.queue_depth", model=mid)] = (
                t.batcher.queue_depth)
            g[profiling.labeled("serve.healthy_replicas", model=mid)] = (
                rt.healthy_count() if hasattr(rt, "healthy_count") else 1)
            g[profiling.labeled("serve.cache_bytes", model=mid)] = (
                t.registry.cache_bytes())
        g["serve.models"] = len(self._tenants)
        g["serve.cache_budget_mb"] = self.cache_budget_mb
        return g

    # -- lifecycle ------------------------------------------------------

    def close(self) -> None:
        for tenant in self._tenants.values():
            tenant.batcher.close()
