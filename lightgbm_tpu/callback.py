"""Training callbacks (reference python-package/lightgbm/callback.py)."""
from __future__ import annotations

import collections
from typing import Callable, Dict, List

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score=None):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def callback(env: CallbackEnv) -> None:
        if (period > 0 and env.evaluation_result_list
                and (env.iteration + 1) % period == 0):
            result = "\t".join(_format_eval_result(x, show_stdv)
                               for x in env.evaluation_result_list)
            print(f"[{env.iteration + 1}]\t{result}")
    callback.order = 10
    return callback


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")
    eval_result.clear()

    def init(env: CallbackEnv) -> None:
        for data_name, eval_name, _, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def callback(env: CallbackEnv) -> None:
        if not eval_result:
            init(env)
        for data_name, eval_name, result, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)
    callback.order = 20
    return callback


def reset_parameter(**kwargs) -> Callable:
    """Reset parameters on a schedule: value is a list (per iteration) or a
    function iteration -> value (reference callback.py:117-155)."""
    def callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if key in ("num_class", "boosting_type", "metric"):
                raise RuntimeError(f"cannot reset {key} during training")
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"Length of list {key} has to equal "
                                     "num_boost_round")
                new_parameters[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_parameters[key] = value(env.iteration - env.begin_iteration)
        if new_parameters:
            env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)
    callback.before_iteration = True
    callback.order = 10
    return callback


def early_stopping(stopping_rounds: int, verbose: bool = True) -> Callable:
    """Client-side early stopping (reference callback.py:155-204 /
    engine.py:188-199)."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List = []
    cmp_op: List[Callable] = []

    def init(env: CallbackEnv) -> None:
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset and "
                             "eval metric is required for evaluation")
        if verbose:
            print(f"Training until validation scores don't improve for "
                  f"{stopping_rounds} rounds.")
        for _ in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            best_score.append(float("-inf"))
            cmp_op.append(lambda x, y: x > y)

    def callback(env: CallbackEnv) -> None:
        if not best_score:
            init(env)
        for i, (d_name, m_name, result, higher_better) in enumerate(
                env.evaluation_result_list):
            score = result if higher_better else -result
            if best_score_list[i] is None or score > best_score[i]:
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            elif env.iteration - best_iter[i] >= stopping_rounds:
                if d_name == "training":
                    continue
                env.model.best_iteration = best_iter[i] + 1
                if verbose:
                    print(f"Early stopping, best iteration is:\n"
                          f"[{best_iter[i] + 1}]\t"
                          + "\t".join(_format_eval_result(x)
                                      for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
    callback.order = 30
    return callback
