"""Configuration system.

TPU-native re-design of the reference config layer
(/root/reference/include/LightGBM/config.h:86-284 and src/io/config.cpp):
a single flat dataclass of typed parameters with LightGBM-compatible names,
defaults, and the full alias table (config.h:342-436).  Unlike the reference's
struct-per-layer split (IOConfig/TreeConfig/BoostingConfig/...), one frozen
dataclass is passed everywhere; jitted code receives it as a hashable static
argument.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

# the predict_kernel dial's legal values — defined here (stdlib-only
# module) so config validation and ops/predict.resolve_predict_kernel
# check against ONE tuple and can't drift
PREDICT_KERNELS = ("auto", "tensorized", "walk")

# the costack_kernel dial's legal values — grouped-traversal strategy
# of cross-model co-stacked serving (docs/serving.md "Cross-model
# batching"): "stacked" walks every stacked tree for every row (free
# where launch overhead dominates), "segment" gathers only the row's
# own tenant's tree segment per depth level (node math ~1x a solo
# tenant's on compute-bound tiers), "auto" resolves per backend
# (ops/predict.resolve_costack_kernel).  Both are bitwise-identical
# to per-tenant dispatch.
COSTACK_KERNELS = ("auto", "stacked", "segment")

# the serve_quantize dial's legal values — request-path feature
# quantization (docs/serving.md "Binned inference"): "binned" serves
# integer bins end-to-end against the model's .refbin frozen-mapper
# sidecar, "raw" keeps f32 feature traversal, "auto" picks binned
# whenever a valid sidecar is present
SERVE_QUANTIZE_MODES = ("auto", "binned", "raw")

# tenant ids of the multi-tenant serving catalog (`serve_models`
# entries, /predict `model` routing).  The charset is deliberately
# tight: ids are echoed into HTTP headers, Prometheus label values,
# telemetry attrs, and traffic-log records, so identifier-shaped ids
# need no escaping at any of those hops.
MODEL_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


class ServeModelEntry(str):
    """One parsed `serve_models` entry: the model PATH (this object IS
    the path — a str subclass, so every caller that treats catalog
    values as path strings keeps working) plus the tenant's validated
    per-tenant overrides dict (possibly empty)."""
    __slots__ = ("overrides",)

    def __new__(cls, path: str, overrides: Optional[dict] = None):
        self = super().__new__(cls, path)
        self.overrides = dict(overrides or {})
        return self

    @property
    def path(self) -> str:
        return str(self)


# the per-tenant keys a `serve_models` entry may override after its
# path (docs/serving.md "Cross-model batching"), normalized to the
# catalog's kwarg names; every alias of the fleet-wide parameter is
# accepted so `de=/m/de.txt;num_replicas=2` means what the operator
# expects
_SERVE_OVERRIDE_KEYS: Dict[str, str] = {
    "replicas": "replicas",
    "serve_replicas": "replicas",
    "serving_replicas": "replicas",
    "num_replicas": "replicas",
    "serve_quantize": "serve_quantize",
    "max_pending_rows": "max_pending_rows",
    "costack": "costack",
    "serve_costack": "costack",
    "cross_model_batching": "costack",
}

_BOOL_WORDS = {"true": True, "on": True, "1": True, "yes": True,
               "false": False, "off": False, "0": False, "no": False}


def _parse_serve_override(entry, key: str, value: str):
    """Validate + coerce ONE `;key=value` tenant override."""
    canon = _SERVE_OVERRIDE_KEYS.get(key)
    if canon is None:
        raise ValueError(
            f"serve_models entry {entry!r}: unknown per-tenant "
            f"override {key!r}; use one of "
            f"{sorted(set(_SERVE_OVERRIDE_KEYS.values()))}")
    if canon in ("replicas", "max_pending_rows"):
        try:
            n = int(value)
        except ValueError:
            raise ValueError(
                f"serve_models entry {entry!r}: {key}={value!r} "
                "is not an integer")
        if n < 0:
            raise ValueError(
                f"serve_models entry {entry!r}: {key} must be >= 0")
        return canon, n
    if canon == "serve_quantize":
        if value not in SERVE_QUANTIZE_MODES:
            raise ValueError(
                f"serve_models entry {entry!r}: serve_quantize="
                f"{value!r}; use one of {SERVE_QUANTIZE_MODES}")
        return canon, value
    b = _BOOL_WORDS.get(str(value).strip().lower())
    if b is None:
        raise ValueError(
            f"serve_models entry {entry!r}: {key}={value!r} is not "
            "a boolean (true/false/on/off/1/0)")
    return canon, b


def parse_serve_models(entries) -> Dict[str, "ServeModelEntry"]:
    """``("de=/models/de.txt", "fr=/models/fr.txt;replicas=2")`` →
    ordered ``{id: ServeModelEntry}`` (the value IS the model path — a
    str subclass — carrying a validated per-tenant ``overrides`` dict).
    The ONE place the `serve_models` grammar lives — config validation,
    `task=serve` catalog construction, and the `task=online` per-tenant
    daemon fleet all route through here.  Grammar per entry:
    ``id=path[;key=value]...`` with override keys ``replicas``,
    ``serve_quantize``, ``max_pending_rows``, ``costack`` (fleet-wide
    parameter aliases accepted).  Raises ValueError on a missing ``=``,
    an id outside MODEL_ID_RE, an empty path, a duplicate id, or a
    malformed override."""
    out: Dict[str, ServeModelEntry] = {}
    for entry in entries:
        mid, sep, rest = str(entry).partition("=")
        mid = mid.strip()
        path, *extras = rest.split(";")
        path = path.strip()
        if not sep or not path:
            raise ValueError(
                f"serve_models entry {entry!r} is not "
                "'id=path[;key=value]'")
        if not MODEL_ID_RE.match(mid):
            raise ValueError(
                f"serve_models id {mid!r} must match "
                "[A-Za-z0-9._-]{1,64}")
        if mid in out:
            raise ValueError(f"serve_models id {mid!r} appears twice")
        if path in out.values():
            # two tenants on one file would share publish/state/refbin
            # sidecars: their online daemons would clobber each other's
            # publishes and resume offsets
            raise ValueError(
                f"serve_models path {path!r} appears under two ids")
        overrides: Dict[str, object] = {}
        for extra in extras:
            k, ksep, v = extra.partition("=")
            k, v = k.strip(), v.strip()
            if not ksep or not k or not v:
                raise ValueError(
                    f"serve_models entry {entry!r}: override "
                    f"{extra!r} is not 'key=value'")
            canon, coerced = _parse_serve_override(entry, k, v)
            if canon in overrides:
                raise ValueError(
                    f"serve_models entry {entry!r}: override "
                    f"{canon!r} appears twice")
            overrides[canon] = coerced
        out[mid] = ServeModelEntry(path, overrides)
    return out


def parse_route_backends(entries) -> Tuple[Tuple[str, ...], Dict[str, str]]:
    """``("127.0.0.1:8081", "de=127.0.0.1:8082")`` →
    ``(backends, overrides)``.  The ONE place the `route_backends`
    grammar lives — config validation and the `task=route` router both
    route through here.  A bare ``host:port`` entry is a backend; an
    entry with ``=`` is an explicit placement override pinning a model
    id to one of the listed backends (it must appear as a bare entry
    too — an override may pin placement but never name a backend the
    health loop does not watch).  Raises ValueError on a malformed
    address, an id outside MODEL_ID_RE, a duplicate backend or
    override, or an override whose target is not a listed backend."""
    backends: List[str] = []
    overrides: Dict[str, str] = {}
    for entry in entries:
        mid, sep, addr = str(entry).partition("=")
        if not sep:
            mid, addr = "", mid
        mid, addr = mid.strip(), addr.strip()
        host, hsep, port = addr.rpartition(":")
        if not hsep or not host or not port.isdigit() or not (
                0 < int(port) <= 65535):
            raise ValueError(
                f"route_backends entry {entry!r} is not 'host:port' or "
                "'model_id=host:port'")
        if not mid:
            if addr in backends:
                raise ValueError(
                    f"route_backends backend {addr!r} appears twice")
            backends.append(addr)
        else:
            if not MODEL_ID_RE.match(mid):
                raise ValueError(
                    f"route_backends override id {mid!r} must match "
                    "[A-Za-z0-9._-]{1,64}")
            if mid in overrides:
                raise ValueError(
                    f"route_backends override for {mid!r} appears twice")
            overrides[mid] = addr
    for mid, addr in overrides.items():
        if addr not in backends:
            raise ValueError(
                f"route_backends override {mid}={addr} names a backend "
                "that is not listed as a bare host:port entry")
    return tuple(backends), overrides


# the sparse_store dial's legal values — binned-store layout
# (docs/Sparse.md): "csr" keeps per-row (store column, bin) nonzero
# entries and the histogram kernels iterate only stored entries;
# "dense" keeps the [F_eff, N] matrix; "auto" picks csr for wide
# stores whose zero-bin rate clears `sparse_threshold` (and only when
# `is_enable_sparse` is on — the reference's master sparse switch)
SPARSE_STORE_MODES = ("auto", "csr", "dense")

# Alias table: parity with reference config.h:342-436 (ParameterAlias).
PARAM_ALIASES: Dict[str, str] = {
    "config": "config_file",
    "nthread": "num_threads",
    "random_seed": "seed",
    "num_thread": "num_threads",
    "boosting": "boosting_type",
    "boost": "boosting_type",
    "application": "objective",
    "app": "objective",
    "train_data": "data",
    "train": "data",
    "model_output": "output_model",
    "model_out": "output_model",
    "model_input": "input_model",
    "model_in": "input_model",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "valid": "valid_data",
    "test_data": "valid_data",
    "test": "valid_data",
    "is_sparse": "is_enable_sparse",
    "enable_sparse": "is_enable_sparse",
    "pre_partition": "is_pre_partition",
    "tranining_metric": "is_training_metric",  # (sic) kept for parity
    "train_metric": "is_training_metric",
    "ndcg_at": "ndcg_eval_at",
    "eval_at": "ndcg_eval_at",
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "num_leaf": "num_leaves",
    "sub_feature": "feature_fraction",
    "colsample_bytree": "feature_fraction",
    "num_iteration": "num_iterations",
    "num_tree": "num_iterations",
    "num_round": "num_iterations",
    "num_trees": "num_iterations",
    "num_rounds": "num_iterations",
    "sub_row": "bagging_fraction",
    "subsample": "bagging_fraction",
    "subsample_freq": "bagging_freq",
    "shrinkage_rate": "learning_rate",
    "tree": "tree_learner",
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "two_round_loading": "use_two_round_loading",
    "two_round": "use_two_round_loading",
    "mlist": "machine_list_file",
    "is_save_binary": "is_save_binary_file",
    "save_binary": "is_save_binary_file",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "verbosity": "verbose",
    "header": "has_header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "query": "group_column",
    "query_column": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "categorical_feature": "categorical_column",
    "cat_column": "categorical_column",
    "cat_feature": "categorical_column",
    "predict_raw_score": "is_predict_raw_score",
    "predict_leaf_index": "is_predict_leaf_index",
    "raw_score": "is_predict_raw_score",
    "leaf_index": "is_predict_leaf_index",
    "min_split_gain": "min_gain_to_split",
    "topk": "top_k",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "num_classes": "num_class",
    "unbalanced_sets": "is_unbalance",
    # extra alias of this package
    "tree_learner_type": "tree_learner",
    # serving subsystem (task=serve)
    "serving_port": "serve_port",
    "predict_port": "serve_port",
    "serving_host": "serve_host",
    "serve_address": "serve_host",
    "batch_rows": "max_batch_rows",
    "serve_max_batch_rows": "max_batch_rows",
    "flush_deadline": "flush_deadline_ms",
    "serve_flush_deadline_ms": "flush_deadline_ms",
    "model_poll": "model_poll_seconds",
    "poll_seconds": "model_poll_seconds",
    "serving_replicas": "serve_replicas",
    "num_replicas": "serve_replicas",
    "request_timeout_ms": "serve_request_timeout_ms",
    "serve_timeout_ms": "serve_request_timeout_ms",
    "failure_threshold": "replica_failure_threshold",
    "serve_failure_threshold": "replica_failure_threshold",
    "serve_max_pending_rows": "max_pending_rows",
    "pending_rows_cap": "max_pending_rows",
    "prediction_kernel": "predict_kernel",
    "predict_engine": "predict_kernel",
    "serving_quantize": "serve_quantize",
    "quantized_serving": "serve_quantize",
    # multi-tenant serving catalog (docs/serving.md "Multi-tenant
    # catalog", lightgbm_tpu/serving/catalog.py)
    "serving_models": "serve_models",
    "model_catalog": "serve_models",
    "serve_cache_budget": "serve_cache_budget_mb",
    "cache_budget_mb": "serve_cache_budget_mb",
    "shadow_fraction": "serve_shadow_fraction",
    "canary_fraction": "serve_shadow_fraction",
    "shadow_requests": "serve_shadow_requests",
    "canary_requests": "serve_shadow_requests",
    "shadow_max_divergence": "serve_shadow_max_divergence",
    "canary_max_divergence": "serve_shadow_max_divergence",
    "costack": "serve_costack",
    "cross_model_batching": "serve_costack",
    "serve_costack_kernel": "costack_kernel",
    "cross_model_kernel": "costack_kernel",
    "group_kernel": "costack_kernel",
    "costack_segment_threshold": "costack_segment_trees",
    "segment_trees_threshold": "costack_segment_trees",
    # router tier (task=route, lightgbm_tpu/router/, docs/Router.md)
    "router_backends": "route_backends",
    "backends": "route_backends",
    "router_port": "route_port",
    "routing_port": "route_port",
    "router_health_interval_ms": "route_health_interval_ms",
    "route_health_ms": "route_health_interval_ms",
    "router_backend_timeout_ms": "route_backend_timeout_ms",
    "backend_timeout_ms": "route_backend_timeout_ms",
    "router_max_inflight": "route_max_inflight",
    "route_inflight_cap": "route_max_inflight",
    # online learning (task=online / task=refit, lightgbm_tpu/online/)
    "decay_rate": "refit_decay_rate",
    "refit_decay": "refit_decay_rate",
    "min_refit_rows": "refit_min_rows",
    "refit_min_data": "refit_min_rows",
    "online_trigger": "online_trigger_rows",
    "trigger_rows": "online_trigger_rows",
    "refresh_mode": "online_mode",
    # fault tolerance (task=train checkpoint/resume, docs/Robustness.md)
    "checkpoint": "checkpoint_path",
    "snapshot_path": "checkpoint_path",
    "checkpoint_freq": "checkpoint_interval",
    "snapshot_freq": "checkpoint_interval",
    # sparse binned store + adaptive bin budgets (docs/Sparse.md)
    "sparse_format": "sparse_store",
    "store_format": "sparse_store",
    "sparse_histogram": "sparse_store",
    "total_bin_budget": "bin_budget",
    "adaptive_bin_budget": "bin_budget",
    "adaptive_bins": "bin_budget",
    # exclusive feature bundling (EFB)
    "efb": "enable_bundle",
    "bundle": "enable_bundle",
    "enable_feature_bundle": "enable_bundle",
    "is_enable_bundle": "enable_bundle",
    "max_conflict": "max_conflict_rate",
    "bundle_conflict_rate": "max_conflict_rate",
    # row partition / ordered histograms (docs/Readme.md)
    "ordered_histograms": "hist_rows",
    "row_partition": "hist_rows",
    # data-parallel histogram exchange (docs/Readme.md "Histogram exchange")
    "histogram_reduce": "hist_exchange",
    "hist_exchange_threshold": "hist_exchange_min_bytes",
    "histogram_exchange_min_bytes": "hist_exchange_min_bytes",
    # pod-scale data plane (docs/Distributed-Data.md, lightgbm_tpu/sharded/)
    "bin_finding": "bin_find",
    "distributed_bin_find": "bin_find",
    "quantile_sketch_eps": "sketch_eps",
    "sketch_epsilon": "sketch_eps",
    "stream_chunk_size": "stream_chunk_rows",
    "ingest_chunk_rows": "stream_chunk_rows",
    # observability (docs/Observability.md, lightgbm_tpu/telemetry.py)
    "telemetry": "telemetry_path",
    "trace_path": "telemetry_path",
    "span_path": "telemetry_path",
    "prometheus_port": "metrics_port",
    "telemetry_port": "metrics_port",
}

# objective name aliases (reference config.cpp GetObjectiveType handling)
OBJECTIVE_ALIASES: Dict[str, str] = {
    "mean_squared_error": "regression",
    "mse": "regression",
    "regression_l2": "regression",
    "l2": "regression",
    "mean_absolute_error": "regression_l1",
    "mae": "regression_l1",
    "l1": "regression_l1",
    "softmax": "multiclass",
}

_TRUE = {"true", "1", "yes", "on", "+", "t"}
_FALSE = {"false", "0", "no", "off", "-", "f"}


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in _TRUE:
        return True
    if s in _FALSE:
        return False
    raise ValueError(f"cannot parse boolean value: {v!r}")


def _parse_int_list(v: Any) -> Tuple[int, ...]:
    if v is None:
        return tuple()
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    s = str(v).strip()
    if not s:
        return tuple()
    return tuple(int(x) for x in s.replace(",", " ").split())


def _parse_str_list(v: Any) -> Tuple[str, ...]:
    if v is None:
        return tuple()
    if isinstance(v, (list, tuple)):
        return tuple(str(x) for x in v)
    s = str(v).strip()
    if not s:
        return tuple()
    return tuple(x for x in s.replace(",", " ").split())


@dataclasses.dataclass(frozen=True)
class Config:
    """All training / IO / network parameters (LightGBM-compatible names).

    Defaults match the reference (config.h:86-284).
    """

    # -- task / overall (config.h:256-284)
    task: str = "train"
    objective: str = "regression"
    boosting_type: str = "gbdt"
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_class: int = 1
    seed: int = 0
    num_threads: int = 0
    verbose: int = 1
    device_type: str = "tpu"  # reference: cpu|gpu; here: tpu (cpu = jax-cpu)

    # -- IO (config.h:86-137)
    max_bin: int = 255
    data_random_seed: int = 1
    data: str = ""
    output_model: str = "LightGBM_model.txt"
    input_model: str = ""
    output_result: str = "LightGBM_predict_result.txt"
    valid_data: Tuple[str, ...] = tuple()
    is_enable_sparse: bool = True
    use_two_round_loading: bool = False
    is_save_binary_file: bool = False
    enable_load_from_binary_file: bool = True
    is_predict_raw_score: bool = False
    is_predict_leaf_index: bool = False
    has_header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_column: str = ""
    is_pre_partition: bool = False
    bin_construct_sample_cnt: int = 200000
    sparse_threshold: float = 0.8
    min_data_in_bin: int = 3
    # distributed / out-of-core bin finding (docs/Distributed-Data.md):
    # "allgather" derives mappers from the process-allgathered global
    # sample (the validated PR-era path); "sketch" merges per-host (and
    # per-chunk) mergeable quantile sketches so no host ever
    # materializes the global sample — boundaries hold an eps rank
    # guarantee (`sketch_eps`).  "auto" = the exact allgather path while
    # the global sample fits `bin_construct_sample_cnt`, sketch beyond.
    bin_find: str = "auto"
    # rank-error knob of the mergeable quantile sketch: each sketch
    # keeps O(1/eps) weighted entries per feature; smaller eps = tighter
    # boundaries, bigger summaries.  Tight enough that the summary holds
    # every distinct value, the sketch is EXACT (bitwise the allgather
    # boundaries).
    sketch_eps: float = 0.001
    # row-chunk size of streamed dataset construction
    # (Dataset.from_stream / use_two_round_loading): peak host memory of
    # ingestion scales with this, not with the dataset length.
    stream_chunk_rows: int = 262144
    # Exclusive Feature Bundling: pack mutually-exclusive features into
    # shared histogram columns (docs/Bundling.md).  max_conflict_rate is
    # the tolerated fraction of rows where two bundled features are both
    # non-default (0.0 = only provably exclusive features bundle).
    enable_bundle: bool = True
    max_conflict_rate: float = 0.0
    # sparse binned store (docs/Sparse.md): "csr" packs the store as
    # per-row (column id, bin) nonzero entries — implicit zeros bin to
    # each column's known zero bin and are reconstructed from per-leaf
    # totals, so histogram compute and bytes scale with nnz instead of
    # F x N (the wide one-hot/hashed CTR regime, arXiv:1706.08359's
    # sparse histogram kernel).  "auto" picks csr when the rounds
    # growth schedule is already in play (tree_growth resolves rounds —
    # the TPU default), the store is wide (>= 128 columns), and its
    # estimated zero-bin rate is at least `sparse_threshold`; dense
    # otherwise, so stock CPU configs are unchanged.
    # `is_enable_sparse=false` (the reference's master sparse switch)
    # keeps the AUTO resolution dense; an explicit csr/dense pins the
    # layout outright.
    sparse_store: str = "auto"
    # adaptive per-feature bin budgets (docs/Sparse.md, the Vectorized
    # Adaptive Histograms allocation, arXiv:2603.00326): a GLOBAL bin
    # budget shared by all features, allocated by per-feature
    # distinct-value/mass share (weight sqrt(distinct x nonzero_mass),
    # floor 2, cap 255) so high-cardinality features get resolution
    # where the mass is and one-hot columns stop wasting uniform
    # max_bin slots.  0 = off (uniform max_bin per feature).  Mappers
    # stay ordinary frozen BinMappers, so refbin/serving/binary-cache
    # contracts are untouched.
    bin_budget: int = 0

    # -- objective params (config.h:140-174)
    is_unbalance: bool = False
    sigmoid: float = 1.0
    huber_delta: float = 1.0
    fair_c: float = 1.0
    gaussian_eta: float = 1.0
    poisson_max_delta_step: float = 0.7
    scale_pos_weight: float = 1.0
    max_position: int = 20
    label_gain: Tuple[float, ...] = tuple()

    # -- metric (config.h:160-174)
    metric: Tuple[str, ...] = tuple()
    metric_freq: int = 1
    is_training_metric: bool = False
    ndcg_eval_at: Tuple[int, ...] = (1, 2, 3, 4, 5)

    # -- tree (config.h:177-207)
    num_leaves: int = 31
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    feature_fraction: float = 1.0
    feature_fraction_seed: int = 2
    histogram_pool_size: float = -1.0
    top_k: int = 20
    # gpu params kept for config compatibility (ignored on tpu)
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False

    # -- boosting (config.h:210-242)
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    early_stopping_round: int = 0
    drop_rate: float = 0.1
    skip_drop: float = 0.5
    max_drop: int = 50
    uniform_drop: bool = False
    xgboost_dart_mode: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    tree_learner: str = "serial"
    # TPU extension: growth scheduling. "exact" = one split at a time
    # (reference leaf-wise semantics); "rounds" = batched rounds (all
    # splittable leaves per round, top-gain-capped — the MXU-efficient
    # schedule); "auto" = rounds on TPU, exact elsewhere.
    tree_growth: str = "auto"
    # histogram matmul operand precision: float32 (exact, 3-pass MXU) or
    # bfloat16 (fast).  The reference GPU learner has the same dial as
    # gpu_use_dp (config.h:206, single vs double) with single the default.
    histogram_dtype: str = "float32"
    # row feed of the batched-rounds histogram passes: "masked" streams
    # the full [F, N] bin store every pass; "gathered" keeps a
    # device-resident row partition (the reference's DataPartition +
    # ordered-gradients design, data_partition.hpp) and histograms only
    # the leaf-contiguous segments each round needs — bagged/GOSS-dropped
    # rows never enter the permutation.  "auto" = gathered on TPU
    # (single-device AND data-parallel shard-map — the partition is
    # per-shard local state), masked on the CPU tier.
    hist_rows: str = "auto"
    # data-parallel histogram exchange: "psum" all-reduces the full
    # [K, F, 3, B] histogram onto every device; "psum_scatter"
    # reduce-scatters over the feature axis so each device owns only its
    # F/ndev slice, split-searches that slice, and all_gathers the tiny
    # per-leaf best-split records (the reference's Network::ReduceScatter
    # design, data_parallel_tree_learner.cpp:118-160) — comms volume and
    # split-search work per device both drop ~ndev x.  "auto" =
    # psum_scatter when the per-pass payload is large enough to pay for
    # the extra record exchange, psum for small payloads (the reference's
    # allgather-vs-halving switch).
    hist_exchange: str = "auto"
    # `hist_exchange=auto` switches to psum_scatter only when the
    # per-pass reduced-histogram payload is at least this many bytes
    # (below it the full psum is cheaper than reduce-scatter + the
    # per-leaf record allgather).  -1 = the built-in default (1 MiB, or
    # the LGBT_HIST_EXCHANGE_MIN_BYTES env override for on-chip tuning);
    # >= 0 pins the crossover explicitly.
    hist_exchange_min_bytes: int = -1

    # -- network (config.h:245-252)
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_file: str = ""

    # -- tpu-specific knobs (new in this framework)
    hist_dtype: str = "float32"      # accumulation dtype for histograms
    hist_input_dtype: str = "bfloat16"  # MXU input dtype for one-hot matmul
    fused_tree: bool = False         # force fully-jitted tree builder
    mesh_shape: Tuple[int, ...] = tuple()  # override device mesh
    boost_from_average: bool = True

    # prediction
    num_iteration_predict: int = -1
    # ensemble-traversal kernel for device prediction (ops/predict.py):
    # "walk" = per-class vmapped tree walk (the original shape);
    # "tensorized" = every tree of every class in ONE padded SoA, all
    # rows x all trees advance one depth level per step (the Booster
    # accelerator layout, arXiv:2011.02022) — also used for whole-model
    # replay onto validation scores.  "auto" = tensorized.
    predict_kernel: str = "auto"

    # -- online serving (task=serve, lightgbm_tpu/serving/)
    serve_host: str = "127.0.0.1"
    serve_port: int = 8080
    max_batch_rows: int = 4096        # micro-batch coalescing cap
    flush_deadline_ms: float = 5.0    # max wait before a partial flush
    model_poll_seconds: float = 10.0  # hot-swap mtime poll (0 = off)
    min_bucket_rows: int = 16         # smallest padded row bucket
    # serving fleet size: replicate compiled predictors across local
    # devices with least-loaded dispatch.  0 = auto (every local device
    # on accelerator backends, 1 on the CPU tier); N caps at the local
    # device count.
    serve_replicas: int = 0
    # admission control: once this many rows are queued, further
    # requests shed load with HTTP 503 instead of growing an unbounded
    # queue (high-water mark — a single over-cap request on an idle
    # server still admits).  0 = unbounded.
    max_pending_rows: int = 0
    # a /predict request whose batch has not scored within this window
    # answers HTTP 504 (the batch keeps scoring; only the waiter gives
    # up) — the client-visible bound on a wedged or overloaded fleet.
    serve_request_timeout_ms: float = 120000.0
    # replica circuit breaker: after this many CONSECUTIVE dispatch
    # failures a replica stops receiving traffic; a periodic half-open
    # probe readmits it once it answers again (docs/Robustness.md).
    replica_failure_threshold: int = 3
    # request-path feature quantization (docs/serving.md "Binned
    # inference"): "binned" quantizes each request chunk against the
    # model's .refbin frozen-mapper sidecar at ingress and traverses
    # integer bins end-to-end — bit-identical scores to the raw kernel,
    # a 4x smaller device request buffer — refusing to serve/swap when
    # the sidecar is missing, torn, or sha1-mismatched vs the publish
    # meta; "raw" keeps f32 feature traversal; "auto" picks binned
    # whenever a valid sidecar is present and falls back to raw
    # otherwise.
    serve_quantize: str = "auto"
    # multi-tenant catalog (docs/serving.md "Multi-tenant catalog"):
    # `id=path` entries, one independent model per tenant id — requests
    # route by the `model` field/query param/X-Model-Id header, each
    # tenant gets its own registry, batcher (admission budget), replica
    # breakers, and /stats / /metrics accounting.  Empty = single-model
    # serving with `input_model` as the default tenant; with entries,
    # `input_model` (when set) still serves requests that name no model.
    # Also consumed by task=online: one refresh daemon per entry, each
    # filtering the shared traffic log by its tenant id and publishing
    # to its own path.
    serve_models: Tuple[str, ...] = tuple()
    # device-memory budget (MiB) for the catalog's compiled-executable
    # caches across ALL tenants: beyond it, the least-recently-used
    # tenants' executables are evicted (their next request recompiles —
    # serve/cache_evictions counts the churn).  The most recently used
    # tenant is never evicted.  0 = unlimited.
    serve_cache_budget_mb: int = 0
    # cross-model batched serving (docs/serving.md "Cross-model
    # batching"): co-stack catalog tenants that share (num_class,
    # serve_quantize variant, leaf tier) onto ONE padded super-stack
    # scored by ONE compiled executable per (bucket, kind) — a mixed
    # batch of many tenants costs one device launch, bitwise-identical
    # to per-tenant dispatch.  Off = every tenant keeps its own
    # executables (the PR 15 layout).  Tenants opt out individually
    # with a `;costack=off` entry override; a group's replica fleet
    # sizes to the MAX of its members' `;replicas=` overrides.
    serve_costack: bool = True
    # grouped-traversal strategy for co-stacked executables
    # (COSTACK_KERNELS): "stacked" walks all T_total stacked trees per
    # row, "segment" gathers only the row's own tenant's tree segment
    # per depth level — same ONE launch per (bucket, kind), node math
    # back to ~1x.  "auto" picks segment on compute-bound backends
    # (CPU, or very deep stacks on accelerators) and stacked where
    # launch overhead dominates (ops/predict.resolve_costack_kernel).
    costack_kernel: str = "auto"
    # costack_kernel=auto's accelerator switch point: total stacked
    # trees at which even a launch-bound backend goes compute-bound on
    # the walk-all traversal and `auto` picks "segment".  The
    # LIGHTGBM_TPU_COSTACK_SEGMENT_TREES env override (read at resolve
    # time) still wins for fleet-wide emergency retunes without a
    # config rollout.
    costack_segment_trees: int = 4096
    # shadow-canary publishes: with a fraction > 0, a republished model
    # is STAGED as a candidate instead of swapped live — this fraction
    # of requests is double-scored on it (stable still answers the
    # client), per-request divergence is logged, and the candidate is
    # adopted only after `serve_shadow_requests` comparisons (rejected
    # if any divergence exceeds `serve_shadow_max_divergence`, when
    # >= 0; < 0 = log-only, always adopt).  0 = swap immediately (the
    # pre-catalog behavior).
    serve_shadow_fraction: float = 0.0
    serve_shadow_requests: int = 32
    serve_shadow_max_divergence: float = -1.0

    # -- router tier (task=route, lightgbm_tpu/router/, docs/Router.md)
    # the backend fleet the router fronts: bare `host:port` entries are
    # backend serving processes; `model_id=host:port` entries are
    # explicit placement overrides pinning a tenant to one of the
    # listed backends (parse_route_backends is the grammar).  Unpinned
    # tenants place by consistent hash of their model id, so adding or
    # removing one backend moves only that backend's tenants.
    route_backends: Tuple[str, ...] = tuple()
    # listen port of the router's own HTTP front (task=route).
    route_port: int = 8180
    # period of the router's backend health probes (GET /healthz on
    # every backend).  A probe answering readmits an open-breaker
    # backend exactly like a successful proxied request.  0 = no
    # background probing (count-based half-open probes on live traffic
    # still readmit — the chaos-deterministic path).
    route_health_interval_ms: float = 1000.0
    # per-attempt socket timeout for proxied backend requests AND
    # health probes; a timeout counts as a breaker failure.
    route_backend_timeout_ms: float = 30000.0
    # router-wide in-flight request cap: beyond it new requests shed
    # load with HTTP 503 + Retry-After instead of stacking threads on
    # slow backends.  0 = unbounded.
    route_max_inflight: int = 0
    # co-stack-aware placement spread: tenants whose backends report a
    # co-stack group key (serving /healthz "group_keys") hash to
    # backends BY THAT KEY, so same-key tenants land on one backend and
    # actually group.  Values > 1 salt the key with the tenant id into
    # this many shards — a very large same-key cohort spreads over up
    # to `route_group_spread` backends (each shard's tenants still
    # co-locate and group).  1 = strict co-location (the
    # grouping-maximizing default).
    route_group_spread: int = 1

    # -- fault tolerance (task=train checkpoint/resume, docs/Robustness.md)
    # when set, training snapshots (model + iteration + early-stopping +
    # sampler RNG state) to this path every `checkpoint_interval`
    # iterations (atomic tmp + rename), and a rerun pointing at an
    # existing checkpoint resumes mid-run instead of starting over.
    checkpoint_path: str = ""
    checkpoint_interval: int = 0      # iterations between snapshots (0 = off)

    # -- online learning (task=online / task=refit, lightgbm_tpu/online/)
    # leaf-value refit blends the Newton leaf output computed on fresh
    # labeled traffic with the old value: new = decay * old + (1 - decay)
    # * computed (reference refit_decay_rate semantics; 0 = replace,
    # 1 = freeze).
    refit_decay_rate: float = 0.9
    # leaves with fewer fresh rows than this keep their old value (a
    # starved leaf's Newton step is noise); floors at 1 row.
    refit_min_rows: int = 20
    # the OnlineTrainer daemon refreshes the model once this many new
    # labeled rows accumulated in the traffic window.
    online_trigger_rows: int = 4096
    # what a refresh does: "refit" reweights the existing tree
    # structures (cheap — ~one traversal + one scan); "continue" appends
    # num_iterations new trees on the fresh window via continued
    # boosting (reset_training_data replay).
    online_mode: str = "refit"

    # -- observability (lightgbm_tpu/telemetry.py, docs/Observability.md)
    # structured span tracing: when set, every process role appends
    # JSONL span/event records (trace-id/span-id/parent-id, monotonic
    # durations) to this path — the serve→train→serve loop becomes
    # reconstructable from trace ids alone, and
    # `scripts/trace_view.py` converts the file to chrome://tracing
    # JSON.  Empty = tracing off (the hot paths pay one cached check).
    telemetry_path: str = ""
    # standalone Prometheus /metrics listener for process roles without
    # their own HTTP server (trainer, online daemon, batch predict):
    # the profiling counters/reservoirs + process/device gauges in text
    # exposition format.  0 = off.  task=serve always exposes the same
    # payload at its own /metrics endpoint instead.
    metrics_port: int = 0

    # fields that are parsed but unused on TPU (accepted for compat)
    config_file: str = ""
    output_freq: int = 1

    def n_classes_for_trees(self) -> int:
        return self.num_class if self.objective == "multiclass" else max(
            1, self.num_class if self.objective == "multiclassova" else 1)

    @property
    def num_tree_per_iteration(self) -> int:
        if self.objective in ("multiclass", "multiclassova"):
            return max(1, self.num_class)
        return 1

    def with_updates(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


_FIELD_TYPES = {f.name: f.type for f in dataclasses.fields(Config)}
_TUPLE_INT_FIELDS = {"ndcg_eval_at", "mesh_shape"}
_TUPLE_FLOAT_FIELDS = {"label_gain"}
_TUPLE_STR_FIELDS = {"valid_data", "metric", "serve_models",
                     "route_backends"}


def apply_aliases(params: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve aliases; explicit canonical keys win (reference config.h:426-434)."""
    out: Dict[str, Any] = {}
    aliased: Dict[str, Any] = {}
    for k, v in params.items():
        k2 = k.strip().lower()
        if k2 in PARAM_ALIASES:
            aliased[PARAM_ALIASES[k2]] = v
        else:
            out[k2] = v
    for k, v in aliased.items():
        out.setdefault(k, v)
    return out


def _coerce(name: str, value: Any) -> Any:
    if name in _TUPLE_INT_FIELDS:
        return _parse_int_list(value)
    if name in _TUPLE_FLOAT_FIELDS:
        if isinstance(value, (list, tuple)):
            return tuple(float(x) for x in value)
        s = str(value).strip()
        return tuple(float(x) for x in s.replace(",", " ").split()) if s else tuple()
    if name in _TUPLE_STR_FIELDS:
        return _parse_str_list(value)
    ftype = str(_FIELD_TYPES[name])
    if "bool" in ftype:
        return _parse_bool(value)
    if "int" in ftype:
        return int(float(str(value)))
    if "float" in ftype:
        return float(value)
    return str(value)


def config_from_params(params: Dict[str, Any], **overrides) -> Config:
    """Build a Config from a LightGBM-style param dict (Python-API entry).

    Unknown keys are ignored with a record in `Config` creation (reference
    behavior: unknown params are silently dropped by ConfigBase::Set).
    """
    merged = dict(params or {})
    merged.update(overrides)
    resolved = apply_aliases(merged)
    # objective aliases
    if "objective" in resolved:
        obj = str(resolved["objective"]).strip().lower()
        resolved["objective"] = OBJECTIVE_ALIASES.get(obj, obj)
    kwargs = {}
    for k, v in resolved.items():
        if k in _FIELD_TYPES:
            kwargs[k] = _coerce(k, v)
    cfg = Config(**kwargs)
    check_param_conflict(cfg)
    # the package-wide log level follows the most recently parsed config
    # (reference: Log verbosity set once from config, log.h:38)
    from . import log
    log.configure(cfg.verbose)
    # span tracing enables at the first config that names a sink (and
    # only enables — a later config without the key must not silently
    # disable a running daemon's telemetry)
    if cfg.telemetry_path:
        from . import telemetry
        telemetry.configure(cfg.telemetry_path)
    return cfg


def check_param_conflict(cfg: Config) -> None:
    """Sanity checks (reference src/io/config.cpp CheckParamConflict)."""
    if cfg.num_leaves < 2:
        raise ValueError("num_leaves must be >= 2")
    if cfg.max_bin < 2:
        raise ValueError("max_bin must be >= 2")
    if not (0.0 < cfg.feature_fraction <= 1.0):
        raise ValueError("feature_fraction must be in (0, 1]")
    if not (0.0 < cfg.bagging_fraction <= 1.0):
        raise ValueError("bagging_fraction must be in (0, 1]")
    if cfg.objective in ("multiclass", "multiclassova") and cfg.num_class < 2:
        raise ValueError("num_class must be >= 2 for multiclass objectives")
    if cfg.boosting_type == "goss" and cfg.top_rate + cfg.other_rate > 1.0:
        raise ValueError("top_rate + other_rate must be <= 1.0 for GOSS")
    if cfg.tree_learner not in ("serial", "feature", "data", "voting",
                                "data2d"):
        raise ValueError(f"unknown tree_learner: {cfg.tree_learner}")
    if cfg.tree_growth not in ("auto", "exact", "rounds"):
        raise ValueError(f"unknown tree_growth: {cfg.tree_growth}")
    if cfg.hist_rows not in ("auto", "gathered", "masked"):
        raise ValueError(f"unknown hist_rows: {cfg.hist_rows}")
    if cfg.hist_exchange not in ("auto", "psum", "psum_scatter"):
        raise ValueError(f"unknown hist_exchange: {cfg.hist_exchange}")
    if cfg.hist_exchange_min_bytes < -1:
        raise ValueError("hist_exchange_min_bytes must be >= 0, or -1 "
                         "for the built-in default")
    if cfg.bin_find not in ("auto", "allgather", "sketch"):
        raise ValueError(f"unknown bin_find: {cfg.bin_find}; "
                         "use auto, allgather or sketch")
    if not (0.0 < cfg.sketch_eps < 0.5):
        raise ValueError("sketch_eps must be in (0, 0.5)")
    if cfg.stream_chunk_rows < 1:
        raise ValueError("stream_chunk_rows must be >= 1")
    if not (0 <= cfg.serve_port <= 65535):
        raise ValueError("serve_port must be in [0, 65535]")
    if cfg.max_batch_rows < 1:
        raise ValueError("max_batch_rows must be >= 1")
    if cfg.min_bucket_rows < 1:
        raise ValueError("min_bucket_rows must be >= 1")
    if cfg.flush_deadline_ms < 0:
        raise ValueError("flush_deadline_ms must be >= 0")
    if cfg.model_poll_seconds < 0:
        raise ValueError("model_poll_seconds must be >= 0")
    if cfg.serve_replicas < 0:
        raise ValueError("serve_replicas must be >= 0 (0 = auto)")
    if cfg.max_pending_rows < 0:
        raise ValueError("max_pending_rows must be >= 0 (0 = unbounded)")
    if cfg.serve_request_timeout_ms <= 0:
        raise ValueError("serve_request_timeout_ms must be > 0")
    if cfg.replica_failure_threshold < 1:
        raise ValueError("replica_failure_threshold must be >= 1")
    if cfg.checkpoint_interval < 0:
        raise ValueError("checkpoint_interval must be >= 0 (0 = off)")
    if cfg.predict_kernel not in PREDICT_KERNELS:
        raise ValueError(f"unknown predict_kernel: {cfg.predict_kernel}")
    if cfg.serve_quantize not in SERVE_QUANTIZE_MODES:
        raise ValueError(f"unknown serve_quantize: {cfg.serve_quantize}; "
                         f"use one of {SERVE_QUANTIZE_MODES}")
    if cfg.costack_kernel not in COSTACK_KERNELS:
        raise ValueError(f"unknown costack_kernel: {cfg.costack_kernel}; "
                         f"use one of {COSTACK_KERNELS}")
    if cfg.costack_segment_trees < 1:
        raise ValueError("costack_segment_trees must be >= 1")
    if cfg.serve_models:
        parse_serve_models(cfg.serve_models)   # id=path shape + id charset
    if cfg.serve_cache_budget_mb < 0:
        raise ValueError("serve_cache_budget_mb must be >= 0 "
                         "(0 = unlimited)")
    if not (0.0 <= cfg.serve_shadow_fraction <= 1.0):
        raise ValueError("serve_shadow_fraction must be in [0, 1]")
    if cfg.serve_shadow_requests < 1:
        raise ValueError("serve_shadow_requests must be >= 1")
    if cfg.route_backends:
        parse_route_backends(cfg.route_backends)  # host:port + override shape
    if not (0 <= cfg.route_port <= 65535):
        raise ValueError("route_port must be in [0, 65535]")
    if cfg.route_health_interval_ms < 0:
        raise ValueError("route_health_interval_ms must be >= 0 (0 = "
                         "probe only on live traffic)")
    if cfg.route_backend_timeout_ms <= 0:
        raise ValueError("route_backend_timeout_ms must be > 0")
    if cfg.route_max_inflight < 0:
        raise ValueError("route_max_inflight must be >= 0 (0 = unbounded)")
    if cfg.route_group_spread < 1:
        raise ValueError("route_group_spread must be >= 1 (1 = strict "
                         "same-key co-location)")
    if not (0.0 <= cfg.refit_decay_rate <= 1.0):
        raise ValueError("refit_decay_rate must be in [0, 1]")
    if cfg.refit_min_rows < 0:
        raise ValueError("refit_min_rows must be >= 0")
    if cfg.online_trigger_rows < 1:
        raise ValueError("online_trigger_rows must be >= 1")
    if cfg.online_mode not in ("refit", "continue"):
        raise ValueError(f"unknown online_mode: {cfg.online_mode}; "
                         "use refit or continue")
    if not (0.0 <= cfg.max_conflict_rate < 1.0):
        raise ValueError("max_conflict_rate must be in [0, 1)")
    if cfg.sparse_store not in SPARSE_STORE_MODES:
        raise ValueError(f"unknown sparse_store: {cfg.sparse_store}; "
                         f"use one of {SPARSE_STORE_MODES}")
    if not (0.0 < cfg.sparse_threshold <= 1.0):
        raise ValueError("sparse_threshold must be in (0, 1]")
    if cfg.bin_budget < 0:
        raise ValueError("bin_budget must be >= 0 (0 = uniform max_bin)")
    if not (0 <= cfg.metrics_port <= 65535):
        raise ValueError("metrics_port must be in [0, 65535] (0 = off)")


def parse_config_file(path: str) -> Dict[str, str]:
    """Parse a LightGBM `key = value` config file (application.cpp:46-102)."""
    params: Dict[str, str] = {}
    with open(path, "r") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            params[k.strip()] = v.strip()
    return params


def parse_cli_args(argv: List[str]) -> Dict[str, str]:
    """Parse `key=value` command line tokens (application.cpp:46-70)."""
    params: Dict[str, str] = {}
    for tok in argv:
        if "=" in tok:
            k, v = tok.split("=", 1)
            params[k.strip()] = v.strip()
    resolved = apply_aliases(params)
    if "config_file" in resolved and resolved["config_file"]:
        file_params = parse_config_file(resolved["config_file"])
        for k, v in file_params.items():
            params.setdefault(k, v)
    return params


def default_metric_for_objective(objective: str) -> str:
    return {
        "regression": "l2",
        "regression_l1": "l1",
        "huber": "huber",
        "fair": "fair",
        "poisson": "poisson",
        "binary": "binary_logloss",
        "multiclass": "multi_logloss",
        "multiclassova": "multi_logloss",
        "lambdarank": "ndcg",
    }.get(objective, "l2")
