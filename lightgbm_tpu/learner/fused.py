"""Fused SPMD leaf-wise tree builder — the distributed tree learner.

One fully-jitted device program grows a whole tree with `lax.fori_loop`,
replacing the reference's three network-parallel learners
(/root/reference/src/treelearner/{data,feature,voting}_parallel_tree_learner.cpp)
with a single SPMD formulation over a 2-D `(data, feature)` mesh:

- rows sharded on the `data` axis: local masked histograms are summed with
  `lax.psum` — the TPU analog of the reference's histogram ReduceScatter
  (data_parallel_tree_learner.cpp:148-161) with the byte-level reducer
  replaced by a typed collective (SURVEY.md §2.8 "TPU mapping").
- features sharded on the `feature` axis: each shard scans only its block
  of the histogram, then the per-shard best splits are `all_gather`ed and
  argmax-reduced — the analog of FeatureParallel's 2×SplitInfo Allreduce
  with MaxReducer (feature_parallel_tree_learner.cpp:53-75).
- both axes compose; pure data-parallel is `feature`-axis size 1 and
  vice versa.  The reference's per-machine row/feature ownership tables
  (dataset_loader.cpp:554-659, feature sharding at
  feature_parallel_tree_learner.cpp:31-50) become mesh shardings.

Unlike the host-loop SerialTreeLearner (learner/serial.py) — which gathers
each leaf's rows so per-split cost shrinks with the leaf — this builder is
mask-based with static shapes everywhere, so the entire tree (and the whole
boosting step) compiles to one XLA program: the design SURVEY.md §3.3 calls
for ("the whole split loop becomes a jitted/pallas program").
"""
from __future__ import annotations

import functools
import math
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset, nnz_capacity_tier
from ..sharded.mesh import (check_scatter_divisible, check_tree_divergence,
                            make_mesh, mesh_axes, pad_cols_to_ndev,
                            resolve_hist_exchange)
from .common import (make_split_kw, padded_bin_count, sentinel_bins_t,
                     use_parent_hist_cache)
from ..jaxutil import bag_mask_dev, pad_rows_dev, slice_rows_dev
from ..ops.histogram import histogram_full_masked, histogram_full_sparse
from ..ops.predict import sparse_bin_lookup
from ..ops.split import (best_split, bundle_predicate_params,
                         combine_sharded_records, identity_feat_table,
                         leaf_output, maybe_unbundle, sharded_slice_search,
                         store_go_left)
from ..tree import Tree, NUMERICAL_DECISION, CATEGORICAL_DECISION
from ..binning import CATEGORICAL

NEG_INF = -jnp.inf


class TreeArrays(NamedTuple):
    """Device tree in the reference's flat-node layout (tree.h:161-196):
    internal nodes 0..n-2, leaves as ~leaf in child arrays."""
    split_feature: jax.Array    # [L-1] int32 inner (used-feature) index
    threshold_bin: jax.Array    # [L-1] int32
    is_cat: jax.Array           # [L-1] bool
    left_child: jax.Array       # [L-1] int32
    right_child: jax.Array      # [L-1] int32
    split_gain: jax.Array       # [L-1] f32
    internal_value: jax.Array   # [L-1] f32 (parent output pre-split)
    internal_count: jax.Array   # [L-1] f32
    leaf_value: jax.Array       # [L] f32
    leaf_count: jax.Array       # [L] f32
    leaf_depth: jax.Array       # [L] int32
    num_leaves: jax.Array       # scalar int32


def _psum(x, axis):
    return jax.lax.psum(x, axis) if axis is not None else x


def build_tree(bins, grad, hess, row_mask, num_bins, is_cat, fmask, ftbl,
               unb=None, *,
               num_leaves: int, num_bins_padded: int, split_kw: tuple,
               max_depth: int, min_data_in_leaf: int,
               min_sum_hessian_in_leaf: float,
               data_axis: Optional[str] = None,
               feature_axis: Optional[str] = None,
               feature_shard_size: int = 0,
               input_dtype: str = "float32",
               voting_k: int = 0,
               num_machines: int = 1,
               hist_exchange: str = "psum",
               cache_parent_hist: bool = True):
    """Grow one tree; runs per-shard inside `shard_map` (or standalone when
    both axes are None).

    bins     : [Floc, Nloc] int  — this shard's STORE columns (= original
               per-feature bins, or bundled columns under EFB); OR a
               sparse ELL triple (cols [1, Nloc, R], binsv [1, Nloc, R],
               zero_bin [1, Floc]) — the shard's column window of the
               sparse store with a leading feature-shard axis that is 1
               per shard_map block (and kept at 1 on the unsharded path
               so both squeeze uniformly)
    grad/hess/row_mask : [Nloc] f32 (row_mask is 0 for padding / out-of-bag)
    num_bins/is_cat/fmask : per-ORIGINAL-feature metadata for this shard
    ftbl     : [5, F] feature→(col, offset, default, nslots, packed) table
               (identity when the store is unbundled)
    unb      : None, or (src, dmask) unbundle-gather tables — then the
               store is bundled (single feature shard only) and every
               histogram is unbundled before split search
    Returns (TreeArrays, leaf_id [Nloc] int32).
    """
    sparse = isinstance(bins, (tuple, list))
    if sparse:
        sp_cols, sp_bins, sp_zb = bins[0][0], bins[1][0], bins[2][0]
        Floc = sp_zb.shape[0]
        Nloc = sp_cols.shape[0]
    else:
        Floc, Nloc = bins.shape
    L = num_leaves
    B = num_bins_padded
    skw = dict(split_kw)
    l1, l2 = skw["lambda_l1"], skw["lambda_l2"]
    f_off = (jax.lax.axis_index(feature_axis) * feature_shard_size
             if feature_axis is not None else jnp.int32(0))

    voting = voting_k > 0 and data_axis is not None
    # psum_scatter exchange (hist_exchange knob; the reference's
    # Network::ReduceScatter ownership, data_parallel_tree_learner.cpp:
    # 118-160): each device reduces and keeps only its Floc/nd slice of
    # the histogram's column axis, split-searches the slice, and the
    # per-leaf records are all_gathered + argmaxed in find_best.  The
    # voting learner routes its selected-subset exchange through the
    # same switch inside find_best_voting.
    hx = (hist_exchange == "psum_scatter" and data_axis is not None
          and not voting)
    hx_vote = hist_exchange == "psum_scatter" and voting
    nd = num_machines if data_axis is not None else 1
    if hx:
        # trace-time guard with a named ValueError (the learner pads the
        # store, so only direct build_tree callers can trip it)
        check_scatter_divisible("store columns", Floc, nd)
    Fs = Floc // nd if hx else Floc

    def make_local_hist(mask):
        if sparse:
            return histogram_full_sparse(sp_cols, sp_bins, sp_zb,
                                         grad, hess, mask,
                                         num_columns_padded=Floc,
                                         num_bins_padded=B,
                                         input_dtype=input_dtype)
        return histogram_full_masked(bins, grad, hess, mask,
                                     num_bins_padded=B,
                                     input_dtype=input_dtype)

    def make_hist(mask):
        h = make_local_hist(mask)
        # voting keeps histograms LOCAL: only the voted feature subset is
        # reduced, inside find_best (PV-Tree,
        # voting_parallel_tree_learner.cpp:314-350)
        if voting:
            return h
        if hx:
            return jax.lax.psum_scatter(h, data_axis, scatter_dimension=0,
                                        tiled=True)
        return _psum(h, data_axis)

    def can_gate(p, sums):
        # can-this-child-be-split-again gate (serial_tree_learner.cpp
        # _can_split checks; depth gate applied by caller via leaf_best)
        can = ((sums[2] >= 2 * min_data_in_leaf)
               & (sums[1] >= 2 * min_sum_hessian_in_leaf))
        gain = jnp.where(can & jnp.isfinite(p[0]) & (p[0] > 0), p[0], NEG_INF)
        return p.at[0].set(gain)

    def find_best(hist, sums):
        """Global best split record given this shard's histogram block
        (the reduce-scattered column slice under psum_scatter) and the
        leaf's GLOBAL (sum_grad, sum_hess, count)."""
        if voting:
            return find_best_voting(hist, sums)
        if hx:
            off = jax.lax.axis_index(data_axis) * Fs
            if unb is None:
                nb_s = jax.lax.dynamic_slice_in_dim(num_bins, off, Fs)
                ic_s = jax.lax.dynamic_slice_in_dim(is_cat, off, Fs)
                fm_s = jax.lax.dynamic_slice_in_dim(fmask, off, Fs)
                # fold the FEATURE-shard base into the slice offset so
                # the shared search emits global feature ids directly
                off = off + f_off
            else:
                nb_s = ic_s = fm_s = None
            p = sharded_slice_search(
                hist, sums, off=off, nb_s=nb_s, ic_s=ic_s, fm_s=fm_s,
                num_bins=num_bins, is_cat=is_cat, fmask=fmask,
                unb=unb, skw=skw)
            p = combine_sharded_records(p, data_axis)
        else:
            rec = best_split(maybe_unbundle(hist, unb, sums),
                             num_bins, is_cat, fmask,
                             sums[0], sums[1], sums[2], **skw)
            p = rec.packed()
            p = p.at[1].add(f_off.astype(jnp.float32))
        if feature_axis is not None:
            allp = jax.lax.all_gather(p, feature_axis)     # [k, 11]
            # argmax picks the first max → smallest shard → smallest
            # feature id among ties (split_info.hpp:100-105 determinism)
            p = allp[jnp.argmax(allp[:, 0])]
        return can_gate(p, sums)

    def find_best_voting(hist_local, sums):
        """PV-Tree split search (voting_parallel_tree_learner.cpp:163-251):
        local per-feature bests with relaxed constraints → local top-k →
        vote all_gather → global top-2k feature subset → psum only those
        features' histograms → exact best split on the subset."""
        from ..ops.split import split_gain_matrix
        local_sums = jnp.stack([jnp.sum(hist_local[0, 0, :]),
                                jnp.sum(hist_local[0, 1, :]),
                                jnp.sum(hist_local[0, 2, :])])
        relaxed = dict(skw)
        relaxed["min_data_in_leaf"] = max(
            1, skw["min_data_in_leaf"] // max(num_machines, 1))
        relaxed["min_sum_hessian_in_leaf"] = (
            skw["min_sum_hessian_in_leaf"] / max(num_machines, 1))
        gains, _, _, _ = split_gain_matrix(
            hist_local, num_bins, is_cat, fmask,
            local_sums[0], local_sums[1], local_sums[2], **relaxed)
        per_feat = jnp.max(gains, axis=1)                  # [F]
        k = min(voting_k, per_feat.shape[0])
        _, topk = jax.lax.top_k(per_feat, k)               # [k] local vote
        allv = jax.lax.all_gather(topk, data_axis).reshape(-1)
        votes = jnp.zeros(per_feat.shape[0], jnp.int32).at[allv].add(1)
        k2 = min(2 * k, per_feat.shape[0])
        _, sel = jax.lax.top_k(votes, k2)                  # [2k] selected
        if hx_vote:
            # same comms layer as the data-parallel learner: reduce-
            # scatter the voted subset over its slot axis (padded to a
            # data-axis multiple by repeating slot 0 — duplicates yield
            # identical records, which the argmax collapses), search this
            # shard's slots only, then allgather + argmax the records
            k2p = pad_cols_to_ndev(k2, nd)
            selp = jnp.concatenate(
                [sel, jnp.broadcast_to(sel[:1], (k2p - k2,))]) \
                if k2p > k2 else sel
            hs = jax.lax.psum_scatter(hist_local[selp], data_axis,
                                      scatter_dimension=0, tiled=True)
            ks = k2p // nd
            sel_s = jax.lax.dynamic_slice_in_dim(
                selp, jax.lax.axis_index(data_axis) * ks, ks)
            rec = best_split(hs, num_bins[sel_s], is_cat[sel_s],
                             fmask[sel_s], sums[0], sums[1], sums[2],
                             **skw)
            p = rec.packed()
            # combine on the GLOBAL slot id so gain ties break by vote
            # rank exactly like the psum path's flat argmax over the
            # [2k, B] selected block (a padded duplicate slot has a
            # larger id and so loses ties to its original); the slot
            # maps back to its feature after the combine
            gslot = jax.lax.axis_index(data_axis) * ks + rec.feature
            p = p.at[1].set(gslot.astype(jnp.float32))
            p = combine_sharded_records(p, data_axis)
            p = p.at[1].set(selp[p[1].astype(jnp.int32)]
                            .astype(jnp.float32))
            return can_gate(p, sums)
        hist_sel = _psum(hist_local[sel], data_axis)       # [2k, 3, B]
        rec = best_split(hist_sel, num_bins[sel], is_cat[sel], fmask[sel],
                         sums[0], sums[1], sums[2], **skw)
        p = rec.packed()
        p = p.at[1].set(sel[rec.feature].astype(jnp.float32))
        return can_gate(p, sums)

    def go_left_row(feat, thr, catf):
        """[Nloc] bool: does each local row go left under the ORIGINAL-
        space split (feat, thr)?  The owning store-column shard evaluates
        the store-space predicate; others contribute zeros."""
        col, T, lo, hi1, dl = bundle_predicate_params(ftbl, feat, thr, catf)
        lf = col - f_off
        owned = (lf >= 0) & (lf < Floc)
        lc = jnp.clip(lf, 0, Floc - 1)
        if sparse:
            featrow = sparse_bin_lookup(sp_cols, sp_bins, sp_zb,
                                        jnp.broadcast_to(lc, (Nloc,)))
        else:
            featrow = jnp.take(bins, lc, axis=0).astype(jnp.int32)
        gl = store_go_left(featrow, T, lo, hi1, dl, catf)
        gl = jnp.where(owned, gl, False)
        if feature_axis is not None:
            gl = jax.lax.psum(gl.astype(jnp.int32), feature_axis) > 0
        return gl

    # ---- root ---------------------------------------------------------------
    if hx:
        # leaf totals must be bitwise REPLICATED across data shards (they
        # gate control flow): partial sums of the LOCAL pass reduced with
        # one tiny psum — the scattered slice's column order differs per
        # shard, so summing it directly would diverge in f32 ulps
        h0_loc = make_local_hist(row_mask)
        root_sums = jax.lax.psum(
            jnp.stack([jnp.sum(h0_loc[0, 0, :]), jnp.sum(h0_loc[0, 1, :]),
                       jnp.sum(h0_loc[0, 2, :])]), data_axis)
        sum_g, sum_h, cnt = root_sums[0], root_sums[1], root_sums[2]
        hist0 = jax.lax.psum_scatter(h0_loc, data_axis,
                                     scatter_dimension=0, tiled=True)
    else:
        hist0 = make_hist(row_mask)
        # every row lands in exactly one bin of each feature, so any
        # single feature's bin sums give the leaf totals; feature blocks
        # are sharded, so reduce a local feature and max over shards
        # (only shards with >=1 real feature agree; all shards see
        # identical rows)
        sum_g = jnp.sum(hist0[0, 0, :])
        sum_h = jnp.sum(hist0[0, 1, :])
        cnt = jnp.sum(hist0[0, 2, :])
        root_sums = jnp.stack([sum_g, sum_h, cnt])
    if voting:
        # hist0 is local in voting mode; root totals are global
        root_sums = _psum(root_sums, data_axis)
        sum_g, sum_h, cnt = root_sums[0], root_sums[1], root_sums[2]
    if feature_axis is not None:
        # shard 0 always holds real features (padding only at the tail)
        root_sums = jax.lax.all_gather(root_sums, feature_axis)[0]
        sum_g, sum_h, cnt = root_sums[0], root_sums[1], root_sums[2]

    leaf_id = jnp.zeros(Nloc, jnp.int32)
    leaf_best = jnp.full((L, 11), NEG_INF, jnp.float32).at[0].set(
        find_best(hist0, root_sums))
    leaf_depth = jnp.zeros(L, jnp.int32)
    leaf_parent = jnp.full(L, -1, jnp.int32)
    leaf_side = jnp.zeros(L, jnp.int32)
    # leaf-hist cache for the parent-subtraction trick; dropped when the
    # pool budget binds (reference HistogramPool, feature_histogram.hpp:
    # 313-475) — both children are then histogrammed directly.  Under
    # psum_scatter the cache holds this shard's column SLICES (nd x less
    # memory per device)
    leaf_hist = (jnp.zeros((L,) + hist0.shape, jnp.float32).at[0].set(hist0)
                 if cache_parent_hist
                 else jnp.zeros((1, 1, 1, 1), jnp.float32))

    arrs = TreeArrays(
        split_feature=jnp.zeros(L - 1, jnp.int32),
        threshold_bin=jnp.zeros(L - 1, jnp.int32),
        is_cat=jnp.zeros(L - 1, bool),
        left_child=jnp.zeros(L - 1, jnp.int32),
        right_child=jnp.zeros(L - 1, jnp.int32),
        split_gain=jnp.zeros(L - 1, jnp.float32),
        internal_value=jnp.zeros(L - 1, jnp.float32),
        internal_count=jnp.zeros(L - 1, jnp.float32),
        leaf_value=jnp.zeros(L, jnp.float32).at[0].set(
            leaf_output(sum_g, sum_h, l1, l2)),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(cnt),
        leaf_depth=jnp.zeros(L, jnp.int32),
        num_leaves=jnp.int32(1),
    )

    def body(i, st):
        (leaf_id, leaf_best, leaf_depth, leaf_parent, leaf_side,
         leaf_hist, arrs) = st
        gated = jnp.where(
            (max_depth <= 0) | (leaf_depth < max_depth),
            leaf_best[:, 0], NEG_INF)
        best_leaf = jnp.argmax(gated).astype(jnp.int32)
        rec = leaf_best[best_leaf]
        do = gated[best_leaf] > 0
        feat = rec[1].astype(jnp.int32)
        thr = rec[2].astype(jnp.int32)
        new_leaf = jnp.int32(i + 1)
        node = jnp.int32(i)

        # decision type lives with the owning shard's metadata (sized by
        # the ORIGINAL feature count, which equals Floc except under EFB)
        Fm = is_cat.shape[0]
        lf = feat - f_off
        owned = (lf >= 0) & (lf < Fm)
        catf = jnp.where(owned, is_cat[jnp.clip(lf, 0, Fm - 1)], False)
        if feature_axis is not None:
            catf = jax.lax.psum(catf.astype(jnp.int32), feature_axis) > 0

        # ---- partition (DataPartition::Split analog, mask-based) ----------
        gl = go_left_row(feat, thr, catf)
        split_mask = do & (leaf_id == best_leaf) & ~gl
        leaf_id2 = jnp.where(split_mask, new_leaf, leaf_id)

        l_sums = rec[3:6]
        r_sums = rec[6:9]
        small_is_left = l_sums[2] <= r_sums[2]
        small_leaf = jnp.where(small_is_left, best_leaf, new_leaf)

        # ---- smaller child histogram + larger by subtraction --------------
        # (serial_tree_learner.cpp smaller/larger trick; do=False → zero
        # mask → zero hist, state select below keeps everything unchanged)
        large_leaf = jnp.where(small_is_left, new_leaf, best_leaf)
        msk = row_mask * (leaf_id2 == small_leaf) * do
        hist_small = make_hist(msk)
        if cache_parent_hist:
            hist_large = leaf_hist[best_leaf] - hist_small
        else:
            hist_large = make_hist(row_mask * (leaf_id2 == large_leaf) * do)

        child_depth = leaf_depth[best_leaf] + 1
        small_sums = jnp.where(small_is_left, l_sums, r_sums)
        large_sums = jnp.where(small_is_left, r_sums, l_sums)
        rec_small = find_best(hist_small, small_sums)
        rec_large = find_best(hist_large, large_sums)
        rec_left = jnp.where(small_is_left, rec_small, rec_large)
        rec_right = jnp.where(small_is_left, rec_large, rec_small)
        if cache_parent_hist:
            hist_left = jnp.where(small_is_left, hist_small, hist_large)
            hist_right = jnp.where(small_is_left, hist_large, hist_small)
            leaf_hist_new = leaf_hist.at[best_leaf].set(hist_left).at[
                new_leaf].set(hist_right)
        else:
            leaf_hist_new = leaf_hist

        # ---- tree arrays (Tree::Split, tree.cpp:52-97) --------------------
        pn = leaf_parent[best_leaf]
        side = leaf_side[best_leaf]
        # out-of-bounds index (L-1) + mode="drop" when no parent / no-op
        lidx = jnp.where((pn >= 0) & (side == 0), pn, L - 1)
        ridx = jnp.where((pn >= 0) & (side == 1), pn, L - 1)
        arrs2 = arrs._replace(
            split_feature=arrs.split_feature.at[node].set(feat),
            threshold_bin=arrs.threshold_bin.at[node].set(thr),
            is_cat=arrs.is_cat.at[node].set(catf),
            split_gain=arrs.split_gain.at[node].set(rec[0]),
            internal_value=arrs.internal_value.at[node].set(
                arrs.leaf_value[best_leaf]),
            internal_count=arrs.internal_count.at[node].set(
                l_sums[2] + r_sums[2]),
            left_child=arrs.left_child.at[lidx].set(
                node, mode="drop").at[node].set(~best_leaf),
            right_child=arrs.right_child.at[ridx].set(
                node, mode="drop").at[node].set(~new_leaf),
            leaf_value=arrs.leaf_value.at[best_leaf].set(
                rec[9]).at[new_leaf].set(rec[10]),
            leaf_count=arrs.leaf_count.at[best_leaf].set(
                l_sums[2]).at[new_leaf].set(r_sums[2]),
            leaf_depth=arrs.leaf_depth.at[best_leaf].set(
                child_depth).at[new_leaf].set(child_depth),
            num_leaves=arrs.num_leaves + 1,
        )
        new_st = (
            leaf_id2,
            leaf_best.at[best_leaf].set(rec_left).at[new_leaf].set(rec_right),
            leaf_depth.at[best_leaf].set(child_depth).at[new_leaf].set(
                child_depth),
            leaf_parent.at[best_leaf].set(node).at[new_leaf].set(node),
            leaf_side.at[best_leaf].set(0).at[new_leaf].set(1),
            leaf_hist_new,
            arrs2,
        )
        old_st = (leaf_id, leaf_best, leaf_depth, leaf_parent,
                  leaf_side, leaf_hist, arrs)
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(do, a, b), new_st, old_st)

    st = (leaf_id, leaf_best, leaf_depth, leaf_parent, leaf_side,
          leaf_hist, arrs)
    st = jax.lax.fori_loop(0, L - 1, body, st)
    return st[-1], st[0]


@jax.jit
def pack_tree_arrays(arrs: TreeArrays) -> jax.Array:
    """Flatten TreeArrays into ONE f32 vector so the host fetches a single
    transfer (per-array fetches cost a device round-trip each — ruinous on
    remote-attached TPUs).  All int fields fit f32 exactly (< 2^24)."""
    return jnp.concatenate(
        [jnp.ravel(x).astype(jnp.float32) for x in arrs]
        + [jnp.zeros(1, jnp.float32)])


def unpack_tree_arrays(vec: np.ndarray, L: int) -> TreeArrays:
    sizes = [L - 1] * 8 + [L] * 3 + [1]
    dts = ([np.int32, np.int32, bool, np.int32, np.int32, np.float32,
            np.float32, np.float32, np.float32, np.float32, np.int32,
            np.int32])
    out, off = [], 0
    for sz, dt in zip(sizes, dts):
        part = vec[off:off + sz]
        out.append(part.astype(dt) if dt != bool else part > 0.5)
        off += sz
    out[-1] = out[-1][0]
    return TreeArrays(*out)


def tree_arrays_to_host(arrs, dataset: Dataset, max_leaves: int) -> Tree:
    """Rehydrate the host Tree model (real feature ids + real-valued
    thresholds via the BinMappers) from device TreeArrays.  Accepts either
    a TreeArrays of device arrays or an already-unpacked numpy TreeArrays."""
    if isinstance(arrs.num_leaves, jax.Array):
        # pack to ONE vector, then ONE explicit fetch (jax.device_get):
        # per-array fetches cost a round-trip each, and np.asarray here
        # would be an implicit transfer under the sanitizer's guard
        a = unpack_tree_arrays(jax.device_get(pack_tree_arrays(arrs)),
                               max_leaves)
    else:
        a = arrs
    n = int(a.num_leaves)
    t = Tree(max_leaves)
    t.num_leaves = n
    if n < 2:
        t.leaf_value[0] = float(a.leaf_value[0])
        return t
    k = n - 1
    t.split_feature_inner[:k] = a.split_feature[:k]
    t.threshold_in_bin[:k] = a.threshold_bin[:k]
    t.decision_type[:k] = np.where(a.is_cat[:k], CATEGORICAL_DECISION,
                                   NUMERICAL_DECISION)
    t.has_categorical = bool(a.is_cat[:k].any())
    t.left_child[:k] = a.left_child[:k]
    t.right_child[:k] = a.right_child[:k]
    t.split_gain[:k] = a.split_gain[:k]
    t.internal_value[:k] = a.internal_value[:k]
    t.internal_count[:k] = np.round(a.internal_count[:k]).astype(np.int64)
    t.leaf_value[:n] = a.leaf_value[:n]
    t.leaf_count[:n] = np.round(a.leaf_count[:n]).astype(np.int64)
    t.leaf_depth[:n] = a.leaf_depth[:n]
    for node in range(k):
        real = dataset.inner_to_real(int(t.split_feature_inner[node]))
        t.split_feature[node] = real
        t.threshold[node] = dataset.mappers[real].bin_to_value(
            int(t.threshold_in_bin[node]))
    return t


class FusedTreeLearner:
    """Mesh-parallel tree learner: `tree_learner=data|feature|serial2d`.

    Pads rows to a multiple of the data-axis size (mask 0) and features to
    a multiple of the feature-axis size (fmask False), then runs
    `build_tree` under `jax.shard_map`.
    """

    def __init__(self, dataset: Dataset, config: Config,
                 mesh: Optional[jax.sharding.Mesh] = None):
        self.dataset = dataset
        self.config = config
        self.mesh = mesh
        self.full_leaf_id = True   # leaf_id valid for out-of-bag rows too
        self.N = dataset.num_data
        self.F = dataset.num_features
        self.B = padded_bin_count(dataset.max_num_bin)

        if mesh is not None:
            axes = mesh_axes(mesh)
        else:
            axes = {}
        self.dd = int(axes.get("data", 1))
        self.df = int(axes.get("feature", 1))
        # multi-process world: this process holds only its row block;
        # the global row axis is assembled per-process (MultiHostRows)
        self.mh = None
        if mesh is not None and jax.process_count() > 1:
            from ..sharded.mesh import MultiHostRows
            self.mh = MultiHostRows(mesh, self.N)
            self.Np = self.mh.np_global
            self._local_np = self.mh.per_proc
        else:
            self.Np = int(self.dd * math.ceil(self.N / self.dd))
            self._local_np = self.Np
        self.Fp = int(self.df * math.ceil(self.F / self.df))

        cfg = config
        voting = (getattr(cfg, "tree_learner", "") == "voting"
                  and self.dd > 1)
        self._voting = voting
        # EFB: histogram over the narrower bundled store.  Feature
        # sharding and voting need per-ORIGINAL-feature store rows (the
        # vote / shard ownership is per feature), so they fall back to
        # the unbundled view of the same plan
        plan = dataset.bundle_plan
        self.use_bundle = plan is not None and self.df == 1 and not voting
        # data-parallel histogram exchange: resolve the collective from
        # the per-pass payload (the voted subset for PV-Tree), then size
        # the store so the histogram's column axis tiles the data axis
        # under psum_scatter
        pay_cols = (dataset.num_store_columns if self.use_bundle
                    else max(1, self.Fp // self.df))
        if voting:
            pay_cols = max(1, min(2 * int(cfg.top_k), self.F))
        self.hist_exchange = resolve_hist_exchange(
            cfg, ndev=self.dd, payload_bytes=4.0 * pay_cols * 3 * self.B)
        hx_pad = (self.hist_exchange == "psum_scatter" and self.dd > 1
                  and not voting)
        if hx_pad and not self.use_bundle:
            # each feature shard's Fp/df column slice must itself tile
            # the data axis, so the unit is the full df*dd product
            self.Fp = pad_cols_to_ndev(self.F, self.df * self.dd)
        # sparse datasets feed the fused builders directly (per-shard ELL
        # windows of the store — no densification); the multi-process
        # row exchange still ships dense blocks, so mh keeps the counted
        # dense fallback (ROADMAP: multi-host sparse ingest)
        self._sparse_feed = dataset.sparse is not None and self.mh is None
        bins_np = None
        if self.use_bundle:
            self.Cstore = dataset.num_store_columns
            cp = 0
            if hx_pad and self.Cstore % self.dd:
                # trivial zero columns so the bundled store tiles the
                # data axis (the unbundle sentinel must sit past them)
                cp = pad_cols_to_ndev(self.Cstore, self.dd) - self.Cstore
                self.Cstore += cp
            if not self._sparse_feed:
                store = dataset.dense_bins(site="fused_feed")
                bins_np = store.astype(np.int32)
                if self._local_np > self.N:
                    bins_np = np.pad(bins_np,
                                     ((0, 0), (0, self._local_np - self.N)))
                if cp:
                    bins_np = np.pad(bins_np, ((0, cp), (0, 0)))
        else:
            self.Cstore = self.Fp
            if not self._sparse_feed:
                base = (dataset.dense_bins(site="fused_feed")
                        if plan is None else dataset.unbundled_bins())
                bins_np = base.astype(np.int32)
                if self.Fp > self.F or self._local_np > self.N:
                    bins_np = np.pad(bins_np,
                                     ((0, self.Fp - self.F),
                                      (0, self._local_np - self.N)))
        nb = np.pad(dataset.num_bins.astype(np.int32),
                    (0, self.Fp - self.F), constant_values=1)
        ic = np.pad(dataset.is_categorical, (0, self.Fp - self.F))
        self._base_fmask = np.pad(np.ones(self.F, bool),
                                  (0, self.Fp - self.F))
        self._row_mask = np.pad(np.ones(self.N, np.float32),
                                (0, self._local_np - self.N))
        # host-numpy tables close over the traced builders as constants
        # (shard_map-safe; a few hundred KB at worst)
        if self.use_bundle:
            ftbl = plan.feat_table()
            unb = dataset.unbundle_tables(self.B, self.Cstore)
        else:
            ftbl = np.asarray(identity_feat_table(nb))
            unb = None

        self.split_kw = make_split_kw(cfg)
        self._feat_rng = np.random.RandomState(cfg.feature_fraction_seed)

        # histogram-memory bound (reference HistogramPool analog); the
        # column count is this shard's local share of the STORE — under
        # psum_scatter each device caches only its column slice
        cache_cols = self.Cstore // self.df
        if hx_pad:
            cache_cols = max(1, cache_cols // self.dd)
        self.cache_parent_hist = use_parent_hist_cache(
            cfg, cache_cols, self.B)
        kw = dict(num_leaves=cfg.num_leaves, num_bins_padded=self.B,
                  split_kw=self.split_kw, max_depth=int(cfg.max_depth),
                  min_data_in_leaf=int(cfg.min_data_in_leaf),
                  min_sum_hessian_in_leaf=float(cfg.min_sum_hessian_in_leaf),
                  voting_k=int(cfg.top_k) if voting else 0,
                  num_machines=self.dd,
                  hist_exchange=self.hist_exchange,
                  cache_parent_hist=self.cache_parent_hist,
                  input_dtype=getattr(cfg, "histogram_dtype", "float32"))
        sp_feed = self._assemble_sparse_feed() if self._sparse_feed \
            else None
        if mesh is None:
            fn = functools.partial(build_tree, ftbl=ftbl, unb=unb, **kw)
            self._build = jax.jit(fn)
            if sp_feed is not None:
                self.bins_dev = tuple(jnp.asarray(x) for x in sp_feed)
            else:
                self.bins_dev = jnp.asarray(bins_np)
        else:
            from jax.sharding import PartitionSpec as P, NamedSharding
            fn = functools.partial(
                build_tree, ftbl=ftbl, unb=unb, **kw,
                data_axis="data" if self.dd > 1 else None,
                feature_axis="feature" if self.df > 1 else None,
                feature_shard_size=self.Fp // self.df)
            da = "data" if self.dd > 1 else None
            fa = "feature" if self.df > 1 else None
            bins_spec = ((P(fa, da, None), P(fa, da, None), P(fa, None))
                         if sp_feed is not None else P(fa, da))
            in_specs = (bins_spec, P(da), P(da), P(da), P(fa), P(fa), P(fa))
            out_specs = (jax.tree_util.tree_map(lambda _: P(), TreeArrays(
                *[0] * len(TreeArrays._fields))), P(da))
            from ..sharded.mesh import compat_shard_map
            self._build = jax.jit(compat_shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False))
            if self.mh is not None:
                self.bins_dev = self.mh.put_rows(bins_np, P(fa, da))
            elif sp_feed is not None:
                self.bins_dev = (
                    jax.device_put(jnp.asarray(sp_feed[0]),
                                   NamedSharding(mesh, P(fa, da, None))),
                    jax.device_put(jnp.asarray(sp_feed[1]),
                                   NamedSharding(mesh, P(fa, da, None))),
                    jax.device_put(jnp.asarray(sp_feed[2]),
                                   NamedSharding(mesh, P(fa, None))))
            else:
                self.bins_dev = jax.device_put(
                    jnp.asarray(bins_np), NamedSharding(mesh, P(fa, da)))
        # replicated metadata stays HOST-side numpy in multi-process mode
        # (jit replicates identical host values across processes; a
        # committed single-device array would be rejected)
        self.num_bins_dev = nb if self.mh is not None else jnp.asarray(nb)
        self.is_cat_dev = ic if self.mh is not None else jnp.asarray(ic)

    def _assemble_sparse_feed(self):
        """Host [df, Np, R] ELL column windows of the sparse store plus
        the [df, Fsh] zero-bin rows — the fused builders' sparse feed.
        Shard j holds its window's entries in LOCAL column ids with
        sentinel Fsh (= the shard's num_columns_padded); padded columns
        carry zero_bin -1.  The leading feature axis stays 1 when
        unsharded so build_tree squeezes both paths uniformly.  Rows are
        padded to the data tile with no entries — every column reads
        its zero bin there, and the zero row_mask keeps padding out of
        the histograms either way."""
        ds = self.dataset
        if self.use_bundle:
            ri, ci, bi, zb = ds.sparse_entries()
            ncols = self.Cstore
        else:
            ri, ci, bi, zb = ds.unbundled_sparse_entries()
            ncols = self.Fp
        zb = np.pad(zb, (0, ncols - zb.size), constant_values=-1)
        df = self.df
        Fsh = ncols // df
        Np = self._local_np
        w = ci // Fsh
        key = w.astype(np.int64) * Np + ri
        cnt = np.bincount(key, minlength=df * Np) if key.size else \
            np.zeros(df * Np, np.int64)
        R = nnz_capacity_tier(int(cnt.max(initial=1)))
        cols_np = np.full((df, Np, R), Fsh, np.int32)
        ell_np = np.zeros((df, Np, R), np.int32)
        if key.size:
            order = np.argsort(key, kind="stable")
            ks = key[order]
            offs = np.concatenate([[0], np.cumsum(cnt)])
            pos = np.arange(ks.size, dtype=np.int64) - offs[ks]
            cols_np[ks // Np, ks % Np, pos] = (ci - w * Fsh)[order]
            ell_np[ks // Np, ks % Np, pos] = bi[order]
        return cols_np, ell_np, zb.reshape(df, Fsh).astype(np.int32)

    @property
    def bins_t(self):
        """Store view for the ScoreUpdater's binned tree traversal:
        [N+1, F] sentinel-padded transpose (same layout as
        SerialTreeLearner.bins_t), or the sparse ELL triple when the
        dataset is sparse — replay then probes the row segments and the
        store never densifies for scoring."""
        if getattr(self, "_bins_t", None) is None:
            if self.dataset.sparse is not None:
                self._bins_t = self.dataset.sparse_triple()
            else:
                self._bins_t = jnp.asarray(sentinel_bins_t(self.dataset))
        return self._bins_t

    def _feature_mask(self):
        frac = self.config.feature_fraction
        if frac >= 1.0:
            # no sampling: cached device copy — re-uploading the constant
            # mask was one implicit transfer per boosting iteration
            if self.mh is not None:
                return self._base_fmask
            if getattr(self, "_fmask_dev", None) is None:
                self._fmask_dev = jax.device_put(self._base_fmask)
            return self._fmask_dev
        m = self._base_fmask.copy()
        k = max(1, int(round(self.F * frac)))
        sel = self._feat_rng.choice(self.F, size=k, replace=False)
        mm = np.zeros(self.Fp, bool)
        mm[sel] = True
        m &= mm
        # per-iteration host draw is the design (reference rng parity);
        # the upload is deliberate, so it is explicit
        return m if self.mh is not None else jax.device_put(m)

    def _pad_rows(self, x: jax.Array):
        if self.mh is not None:
            from jax.sharding import PartitionSpec as P
            return self.mh.put_rows(
                self.mh.pad_local(np.asarray(x, np.float32)), P("data"))
        if self.Np == self.N:
            return x
        return pad_rows_dev(x, pad=self.Np - self.N)

    def _record_comm_stats(self) -> None:
        """Per-tree comms accounting for the data-parallel exchange.
        The fused builder's fori_loop always runs num_leaves-1 bodies
        (no-op splits still execute their collectives), so the per-tree
        byte totals are STATIC — recorded host-side, no device scalar
        needed (unlike the rounds learner's cond-skipped chunks)."""
        if self.dd <= 1:
            return
        from .. import profiling
        L = self.config.num_leaves
        hxs = self.hist_exchange == "psum_scatter"
        calls = 1 + 2 * (L - 1)               # find_best invocations
        if self._voting:
            k2 = max(1, min(2 * int(self.config.top_k), self.F))
            k2p = self.dd * ((k2 + self.dd - 1) // self.dd) if hxs else k2
            per = 4.0 * (k2p // self.dd if hxs else k2) * 3 * self.B
            hx_bytes = per * calls
        else:
            cols = self.Cstore // self.df
            per = 4.0 * (cols // self.dd if hxs else cols) * 3 * self.B
            passes = 1 + (L - 1) * (1 if self.cache_parent_hist else 2)
            hx_bytes = per * passes
        profiling.count(profiling.HIST_EXCHANGE_BYTES, hx_bytes)
        profiling.count(profiling.SPLIT_RECORDS_BYTES,
                        4.0 * self.dd * 11 * calls if hxs else 0.0)

    def train(self, grad: jax.Array, hess: jax.Array,
              bag_idx: Optional[jax.Array] = None,
              bag_count: Optional[int] = None) -> Tuple[Tree, jax.Array]:
        if self.mh is not None:
            mask = self._row_mask
            if bag_idx is not None:
                m2 = np.zeros(self._local_np, np.float32)
                bi = np.asarray(bag_idx)
                m2[bi[bi < self.N]] = 1.0
                mask = m2 * mask
            from jax.sharding import PartitionSpec as P
            mask = self.mh.put_rows(mask, P("data"))
        else:
            if getattr(self, "_row_mask_dev", None) is None:
                self._row_mask_dev = jax.device_put(self._row_mask)
            mask = self._row_mask_dev
            if bag_idx is not None:
                # bag_idx is padded with sentinel N, which IS in bounds
                # when rows are padded (Np > N) — multiply by the base
                # row mask so padding rows can never count
                mask = bag_mask_dev(bag_idx, mask)
        arrs, leaf_id = self._build(
            self.bins_dev, self._pad_rows(grad), self._pad_rows(hess), mask,
            self.num_bins_dev, self.is_cat_dev, self._feature_mask())
        self._record_comm_stats()
        check_tree_divergence("fused/tree", arrs)
        tree = tree_arrays_to_host(arrs, self.dataset,
                                   self.config.num_leaves)
        if self.mh is not None:
            return tree, jnp.asarray(self.mh.local_rows(leaf_id))
        return tree, slice_rows_dev(leaf_id, n=self.N)


def create_tree_learner(dataset: Dataset, config: Config):
    """Factory (reference tree_learner.cpp:9-33).

    tree_learner picks the PARALLELISM (serial / data / feature / voting /
    data2d → mesh axes); tree_growth picks the SCHEDULE:
    - "exact": strict one-split-at-a-time leaf-wise.  On CPU this is the
      host-loop gather learner (learner/serial.py); on TPU it is the fused
      single-split builder (no per-split host syncs).
    - "rounds": batched rounds (learner/rounds.py) — the MXU-efficient
      schedule; equals leaf-wise whenever the num_leaves cap doesn't bind.
    - "auto": rounds on TPU, exact elsewhere (the masked multi-leaf
      formulation is matmul-heavy — right for the MXU, wasteful on CPU,
      where the gather-based exact learner is work-optimal).
    """
    lt = getattr(config, "tree_learner", "serial")
    growth0 = getattr(config, "tree_growth", "auto")
    growth = growth0
    on_tpu = jax.default_backend() == "tpu"
    if growth == "auto":
        growth = "rounds" if on_tpu else "exact"
    if getattr(dataset, "sparse", None) is not None and growth0 == "auto" \
            and growth != "rounds" and lt not in ("feature", "voting"):
        # the nonzero-iterating kernels live in the rounds learner; an
        # exact-growth build over a sparse store on the host-loop serial
        # learner would densify it, so `auto` resolves rounds wherever
        # the store is sparse.  The fused feature-sharded / voting
        # learners consume per-shard ELL windows directly
        # (FusedTreeLearner._assemble_sparse_feed) and keep the fused
        # builder; an EXPLICITLY pinned exact growth takes the counted
        # dense fallback instead.
        from .. import log
        log.info("sparse store: tree_growth=auto resolves to rounds "
                 "(the nonzero-iterating histogram path)")
        growth = "rounds"

    mesh = None
    if lt in ("data", "feature", "voting", "data2d"):
        mesh = make_mesh(lt, getattr(config, "num_machines", 0))
        if mesh is None:
            import warnings
            warnings.warn(f"tree_learner={lt} requested but only one device "
                          "is visible; running single-device")

    feature_sharded = (mesh is not None
                       and mesh_axes(mesh).get("feature", 1) > 1)
    if lt == "voting" and mesh is not None:
        # PV-Tree needs the per-split vote exchange of the fused builder
        return FusedTreeLearner(dataset, config, mesh)
    if growth == "rounds" and (not feature_sharded or lt == "data2d"):
        # data2d + rounds runs the 2-D (data x feature) mesh inside the
        # rounds builder itself: rows shard over both axes, histograms
        # psum over data and reduce-scatter over feature
        # (docs/Distributed-Data.md).  tree_learner=feature keeps the
        # fused exact builder (its feature sharding splits the search
        # over replicated rows, a different decomposition).
        from .rounds import RoundsTreeLearner
        return RoundsTreeLearner(dataset, config, mesh)
    if mesh is not None:
        return FusedTreeLearner(dataset, config, mesh)
    if on_tpu:
        return FusedTreeLearner(dataset, config, None)
    from .serial import SerialTreeLearner
    return SerialTreeLearner(dataset, config)
